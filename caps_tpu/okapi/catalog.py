"""Concrete catalog: namespaces → data sources, with the default in-memory
``session`` namespace.

Mirrors the reference's ``CypherCatalog`` + ``SessionGraphDataSource``
(ref: okapi-api/.../api/graph/CypherCatalog.scala and
spark-cypher/.../impl/io/SessionGraphDataSource.scala — reconstructed,
mount empty; SURVEY.md §2, §3.3).
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple, Union

from caps_tpu.obs.lockgraph import make_rlock

from caps_tpu.okapi.graph import (
    GraphName, Namespace, PropertyGraph, PropertyGraphCatalog, QualifiedGraphName,
)
from caps_tpu.okapi.io import PropertyGraphDataSource

NameLike = Union[str, GraphName, QualifiedGraphName]


def _qualify(name: NameLike) -> QualifiedGraphName:
    if isinstance(name, QualifiedGraphName):
        return name
    if isinstance(name, GraphName):
        return QualifiedGraphName(Namespace(), name)
    return QualifiedGraphName.parse(name)


class SessionGraphDataSource(PropertyGraphDataSource):
    """The default in-memory source behind the ``session`` namespace."""

    def __init__(self):
        self._graphs: Dict[GraphName, PropertyGraph] = {}

    def has_graph(self, name: GraphName) -> bool:
        return name in self._graphs

    def graph(self, name: GraphName) -> PropertyGraph:
        if name not in self._graphs:
            raise KeyError(f"graph {name!r} not found in session catalog")
        return self._graphs[name]

    def store(self, name: GraphName, graph: PropertyGraph) -> None:
        self._graphs[name] = graph

    def delete(self, name: GraphName) -> None:
        self._graphs.pop(name, None)

    def graph_names(self) -> Tuple[GraphName, ...]:
        return tuple(self._graphs.keys())


class CypherCatalog(PropertyGraphCatalog):
    def __init__(self):
        self._sources: Dict[Namespace, PropertyGraphDataSource] = {
            Namespace(): SessionGraphDataSource()
        }
        # bumped on every mutation (observability / coarse fingerprint)
        self.version = 0
        # scoped dependency tokens (relational/plan_cache.py): one
        # counter per qualified name, plus one per namespace for
        # register/deregister — a mutation invalidates exactly the
        # mutated name's dependents, never the whole plan cache
        self._name_versions: Dict[QualifiedGraphName, int] = {}
        self._ns_epochs: Dict[Namespace, int] = {}
        self._listeners: list = []
        # Serializes mutations: store/delete + the version bump + the
        # subscription fan-out (plan-cache eviction) must be atomic, or
        # two serving threads interleaving mutations could leave the
        # token bumped with stale entries still cached.  Reentrant
        # because a listener may legitimately read the catalog back.
        self._lock = make_rlock("catalog.CypherCatalog._lock")

    def subscribe(self, fn) -> None:
        """Register a callback invoked as ``fn(version, qgn)`` after
        every catalog mutation — ``qgn`` is the mutated qualified name,
        or None for a namespace-level change (register/deregister).
        The session plan cache evicts the mutated name's dependents
        through this (scoped — unrelated graphs' plans survive)."""
        with self._lock:
            self._listeners.append(fn)

    def dep_token(self, name: NameLike) -> Tuple[int, int]:
        """The scoped consistency token a cached plan records per
        resolved catalog graph: (namespace epoch, per-name version).
        Any mutation of the name — or of its namespace's source set —
        changes the token, and lookup revalidation drops the plan.

        Deliberately LOCK-FREE: the plan cache validates tokens while
        holding its own lock, and catalog mutations fan out INTO the
        plan cache while holding this one — taking the catalog lock
        here would close a lock-order cycle (the runtime lock graph
        caught exactly that).  The two dict reads are each atomic under
        the GIL and only ever mutated under the catalog lock; a lookup
        that races a mutation reads the pre-mutation token, which is
        indistinguishable from the lookup having happened just before
        the mutation — and the mutation's eager eviction fan-out drops
        the entry right after."""
        qgn = _qualify(name)
        return (self._ns_epochs.get(qgn.namespace, 0),
                self._name_versions.get(qgn, 0))

    def _bump(self, qgn: Optional[QualifiedGraphName] = None) -> None:
        self.version += 1
        if qgn is not None:
            self._name_versions[qgn] = self._name_versions.get(qgn, 0) + 1
        for fn in list(self._listeners):
            fn(self.version, qgn)

    @property
    def session_namespace(self) -> Namespace:
        return Namespace()

    def register_source(self, namespace: Namespace, source: PropertyGraphDataSource) -> None:
        if isinstance(namespace, str):
            namespace = Namespace(namespace)
        with self._lock:
            if namespace in self._sources:
                raise ValueError(f"namespace {namespace!r} already registered")
            self._sources[namespace] = source
            self._ns_epochs[namespace] = \
                self._ns_epochs.get(namespace, 0) + 1
            self._bump()

    def deregister_source(self, namespace: Namespace) -> None:
        if isinstance(namespace, str):
            namespace = Namespace(namespace)
        if namespace == Namespace():
            raise ValueError("cannot deregister the session namespace")
        with self._lock:
            if self._sources.pop(namespace, None) is not None:
                # resolvable graphs changed: every name in the namespace
                # is stale — the epoch bump flips all their dep tokens
                self._ns_epochs[namespace] = \
                    self._ns_epochs.get(namespace, 0) + 1
                self._bump()

    def source(self, namespace: Namespace) -> PropertyGraphDataSource:
        if isinstance(namespace, str):
            namespace = Namespace(namespace)
        if namespace not in self._sources:
            raise KeyError(f"no data source registered for namespace {namespace!r}")
        return self._sources[namespace]

    @property
    def namespaces(self) -> Tuple[Namespace, ...]:
        return tuple(self._sources.keys())

    def has_graph(self, name: NameLike) -> bool:
        qgn = _qualify(name)
        try:
            return self.source(qgn.namespace).has_graph(qgn.graph_name)
        except KeyError:
            return False

    def graph(self, name: NameLike) -> PropertyGraph:
        qgn = _qualify(name)
        return self.source(qgn.namespace).graph(qgn.graph_name)

    def store(self, name: NameLike, graph: PropertyGraph) -> None:
        qgn = _qualify(name)
        with self._lock:
            self.source(qgn.namespace).store(qgn.graph_name, graph)
            self._bump(qgn)

    def delete(self, name: NameLike) -> None:
        qgn = _qualify(name)
        with self._lock:
            self.source(qgn.namespace).delete(qgn.graph_name)
            self._bump(qgn)

    def graph_names(self) -> Tuple[QualifiedGraphName, ...]:
        out = []
        for ns, src in self._sources.items():
            out.extend(QualifiedGraphName(ns, gn) for gn in src.graph_names())
        return tuple(out)

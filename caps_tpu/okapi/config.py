"""Engine configuration and debug flags.

The reference used a small homegrown flag registry backed by JVM system
properties (PrintTimings/PrintIr/PrintLogicalPlan/PrintRelationalPlan/...)
plus the SparkConf passed to the session builder (ref:
okapi-api/.../okapi/impl/configuration/ — reconstructed, mount empty;
SURVEY.md §5.6).  Here: one frozen dataclass with env-var overrides.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Optional, Tuple


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v is not None else default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v is not None else default


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    # Debug printing (the reference's PrintIr / PrintLogicalPlan / ... flags)
    print_timings: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_PRINT_TIMINGS", False))
    print_ir: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_PRINT_IR", False))
    print_logical_plan: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_PRINT_LOGICAL", False))
    print_relational_plan: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_PRINT_RELATIONAL", False))

    # Device backend tuning
    # Row-count buckets: device tables are padded up to the next bucket so
    # query programs compile once per (plan, bucket) key.
    bucket_sizes: Tuple[int, ...] = (256, 1024, 4096, 16384, 65536, 262144, 1048576)
    # Mesh shape for sharded execution; () = single device.
    mesh_shape: Tuple[int, ...] = ()
    mesh_axis: str = "shard"
    # Kernel switches (pallas kernels fall back to jnp when off)
    use_pallas: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_USE_PALLAS", True))
    # Bitonic sort-permutation kernel (ops/sort.py) for order_by /
    # distinct / group sorts on supported tile capacities (compiled TPU
    # only; rides use_pallas + the probe's "sort" family).  Default ON:
    # validated on live TPU v5e 2026-07-31 (``python -m
    # caps_tpu.ops.sort_validate``: 18 compiled cases, 0 failures —
    # recorded in TUNNEL_r05.md probe #6).  CAPS_TPU_SORT_KERNEL=0
    # restores the lax.sort path.
    use_sort_kernel: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_SORT_KERNEL", True))
    # HBM-resident CSR adjacency as the relationship scan's physical
    # layout (ops/expand.py DeviceCSR); joins against it probe indptr
    # instead of sorting + binary-searching the edge table.
    use_csr: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_USE_CSR", True))
    # Aggregate pushdown (relational/count_pattern.py): lower count-only
    # pattern chains to SpMV over the adjacency instead of join+count.
    use_count_pushdown: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_COUNT_PUSHDOWN", True))
    # Matrix/ring expansion strategies (parallel/ring.py): on a mesh,
    # uniform pushdown chains and eligible var-expands ride the ppermute
    # ring schedule instead of XLA-inserted all-reduces; single-chip,
    # the same eligible var-expands run as one SpMV matrix program
    # (VarExpandOp strategy "matrix") instead of the join cascade.
    use_ring: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_USE_RING", True))
    # Worst-case-optimal multiway joins (relational/wcoj.py, ROADMAP
    # item 4): detected cyclic MATCH segments (chain + closing edges)
    # substitute a leapfrog-style multiway intersection over sorted
    # edge keys for the binary join cascade — enumeration AND counting.
    # Cost-selected when the model is on; off = the cascade everywhere
    # (the bench.py cyclic-mode baseline).
    use_wcoj: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_WCOJ", True))
    # Cost-based planning (relational/cost.py + relational/stats.py,
    # ROADMAP item 3): ingest-time cardinality/degree/skew sketches seed
    # a tensor-path cost model that (a) re-roots Expand chains at their
    # cheaper end (logical/optimizer.py), (b) chooses count-pushdown vs
    # cascade and the sharded distribution strategy, and (c) stamps
    # per-operator row estimates so opstats.divergences measures MODEL
    # error and a diverging cached family re-plans itself.  Off = the
    # pre-item-3 fixed heuristics (the bench.py plan-mode baseline).
    use_cost_model: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_COST_MODEL", True))
    # Divergence-triggered re-planning: model-divergent executions per
    # plan family before its cached plan retires through the quarantine
    # path and re-plans with calibrated statistics.  0 disables.
    replan_threshold: int = dataclasses.field(
        default_factory=lambda: _env_int("CAPS_TPU_REPLAN_THRESHOLD", 2))
    # Hand-scheduled distributed joins (parallel/dist_join.py, SURVEY.md
    # §5.8): with a 1-D mesh, large-large joins ride an all_to_all radix
    # exchange (each row crosses ICI once) instead of GSPMD's layout, and
    # small build sides ride an explicit all_gather broadcast join.
    use_dist_join: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_DIST_JOIN", True))
    # Build sides at or under this many rows broadcast instead of
    # exchanging (Spark's autoBroadcastJoinThreshold analog, in rows).
    # With the cost model on this is a model INPUT — the broadcast
    # prior — not a hard cutover (relational/cost.py
    # choose_dist_strategy); <= 0 disables broadcasting either way.
    broadcast_join_threshold: int = dataclasses.field(
        default_factory=lambda: _env_int("CAPS_TPU_BROADCAST_ROWS", 4096))
    # Skew salting for the radix exchange (surgical: ONLY detected-hot
    # keys replicate).  join_salt > 1 forces that salt factor; 1 = pick
    # automatically from the probe-key sample (salt stays 1 when no key
    # exceeds join_hot_factor x the per-device fair share).
    join_salt: int = dataclasses.field(
        default_factory=lambda: _env_int("CAPS_TPU_JOIN_SALT", 1))
    # A sampled key is "hot" when its frequency exceeds this multiple of
    # the per-device fair share (SURVEY.md §5.8 skew handling).
    join_hot_factor: float = dataclasses.field(
        default_factory=lambda: _env_float("CAPS_TPU_JOIN_HOT_FACTOR", 4.0))
    # At most this many hot keys ride the device-resident hot set.
    join_hot_capacity: int = dataclasses.field(
        default_factory=lambda: _env_int("CAPS_TPU_JOIN_HOT_CAP", 16))
    # Fused executor (backends/tpu/fused.py): record data-dependent sizes
    # on a query's first run, replay them sync-free on repeats.
    use_fused: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_USE_FUSED", True))
    # Single-program count pushdown (relational/count_pattern.py): compile
    # the whole seed→hops→masks→correction chain into ONE scatter-free
    # jitted program, cached per (graph, plan shape, params).
    use_fused_count: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_FUSED_COUNT", True))
    # Compile-cache capacity (query programs keyed by plan+bucket shapes)
    compile_cache_size: int = dataclasses.field(
        default_factory=lambda: _env_int("CAPS_TPU_COMPILE_CACHE", 512))
    # Prepared-statement plan cache (relational/plan_cache.py): repeated
    # parameterized queries skip parse/IR/logical/relational planning
    # entirely on a hit — the last un-amortized scalar hot path in the
    # pipelined serving mode.  Keys are value-independent (query text +
    # graph + catalog fingerprint + parameter signature).
    use_plan_cache: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_PLAN_CACHE", True))
    # Max cached plans per session (LRU evicted beyond this).
    plan_cache_size: int = dataclasses.field(
        default_factory=lambda: _env_int("CAPS_TPU_PLAN_CACHE_SIZE", 256))
    # Debug assertion hook for the generic-replay __obj__ invariant
    # (backends/tpu/fused.py): an obj served under generic replay that no
    # downstream relation-checked consume guards raises at query end.
    debug_obj_guard: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_DEBUG_OBJ_GUARD", False))
    # Persistent XLA compilation cache directory ("" = disabled).  Repeat
    # processes skip device compiles entirely — on remote-compile
    # transports this turns a ~100 s cold start into seconds.
    compile_cache_dir: str = dataclasses.field(
        default_factory=lambda: os.environ.get(
            "CAPS_TPU_COMPILE_CACHE_DIR",
            os.path.join(os.path.expanduser("~"), ".cache", "caps_tpu_xla")))
    # Determinism check (SURVEY.md §5.2): run each query twice and compare
    # result digests; raises NondeterministicResultError on mismatch.
    determinism_check: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_DETERMINISM_CHECK", False))
    # Observability (caps_tpu/obs/): ambient tracing for EVERY query.
    # Off by default — the disabled tracer costs one attribute check per
    # instrumented site (<5% overhead budget); PROFILE force-enables it
    # for its one query regardless of this flag.
    trace: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_TRACE", False))
    # PROFILE granularity: sync the device after each operator so per-op
    # spans carry real device time (post-block_until_ready deltas).  Off,
    # the dispatch stream stays async (what steady-state fused replay
    # actually runs) and the TPU session reports device time as ONE
    # per-replay aggregate span — per-op numbers are then host dispatch
    # times and are labeled as such, never silently wrong (docs/tpu.md).
    profile_sync_each_op: bool = dataclasses.field(
        default_factory=lambda: _env_bool("CAPS_TPU_PROFILE_SYNC", True))

    def bucket_for(self, n: int) -> int:
        for b in self.bucket_sizes:
            if n <= b:
                return b
        # Beyond the largest bucket: round up to the next power of two.
        b = self.bucket_sizes[-1]
        while b < n:
            b *= 2
        return b


DEFAULT_CONFIG = EngineConfig()

"""User-facing graph/session API surface.

Mirrors the reference's ``CypherSession``, ``PropertyGraph``,
``CypherResult``/``CypherRecords``, ``QualifiedGraphName``/``Namespace``/
``GraphName`` (ref: okapi-api/.../api/graph/ — reconstructed, mount empty;
SURVEY.md §2 "Graph/session API").

These are pure interfaces; the concrete engine lives in
``caps_tpu.relational`` with backends under ``caps_tpu.backends``.
"""
from __future__ import annotations

import abc
import dataclasses
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

from caps_tpu.okapi.schema import Schema


@dataclasses.dataclass(frozen=True, order=True)
class Namespace:
    value: str = "session"

    def __repr__(self):
        return self.value


@dataclasses.dataclass(frozen=True, order=True)
class GraphName:
    value: str

    def __repr__(self):
        return self.value


@dataclasses.dataclass(frozen=True, order=True)
class QualifiedGraphName:
    namespace: Namespace
    graph_name: GraphName

    @staticmethod
    def parse(qualified: str) -> "QualifiedGraphName":
        """``"ns.path.to.graph"`` → QGN(ns, path.to.graph); a bare name maps
        to the default ``session`` namespace."""
        if "." in qualified:
            ns, _, rest = qualified.partition(".")
            return QualifiedGraphName(Namespace(ns), GraphName(rest))
        return QualifiedGraphName(Namespace(), GraphName(qualified))

    def __repr__(self):
        return f"{self.namespace!r}.{self.graph_name!r}"


class PropertyGraph(abc.ABC):
    """A queryable property graph."""

    @property
    @abc.abstractmethod
    def schema(self) -> Schema:
        ...

    @abc.abstractmethod
    def cypher(self, query: str, parameters: Optional[Mapping[str, Any]] = None) -> "CypherResult":
        ...

    @abc.abstractmethod
    def nodes(self, var: str = "n", labels: Iterable[str] = ()) -> "CypherRecords":
        """All nodes (optionally restricted by labels) as records of one
        node column."""

    @abc.abstractmethod
    def relationships(self, var: str = "r", rel_types: Iterable[str] = ()) -> "CypherRecords":
        ...

    @abc.abstractmethod
    def union_all(self, *others: "PropertyGraph") -> "PropertyGraph":
        ...

    def statistics(self):
        """Ingest-time statistics sketch (cardinalities, degree
        distributions, skew — ``caps_tpu.relational.stats``) used by
        the cost-based planner; None when the graph keeps none.
        Concrete relational graphs compute it lazily at construction
        time and refresh it across versioned commits."""
        return None


class CypherRecords(abc.ABC):
    """A table of Cypher values — the tabular part of a query result."""

    @property
    @abc.abstractmethod
    def columns(self) -> Tuple[str, ...]:
        ...

    @abc.abstractmethod
    def to_maps(self) -> List[Dict[str, Any]]:
        """Materialize as a list of dicts (entities as CypherNode/
        CypherRelationship).  Multiset semantics: duplicates significant,
        order insignificant unless ORDER BY was used."""

    @abc.abstractmethod
    def size(self) -> int:
        ...

    def show(self, n: int = 20) -> None:
        rows = self.to_maps()[:n]
        cols = list(self.columns)
        widths = {c: max([len(c)] + [len(repr(r.get(c))) for r in rows]) for c in cols}
        line = "│ " + " │ ".join(c.ljust(widths[c]) for c in cols) + " │"
        sep = "╪".join("═" * (widths[c] + 2) for c in cols)
        print(line)
        print("╞" + sep + "╡")
        for r in rows:
            print("│ " + " │ ".join(repr(r.get(c)).ljust(widths[c]) for c in cols) + " │")
        print(f"({self.size()} rows)")


class CypherResult(abc.ABC):
    """The result of ``cypher(...)``: records and/or a constructed graph."""

    @property
    @abc.abstractmethod
    def records(self) -> Optional[CypherRecords]:
        ...

    @property
    @abc.abstractmethod
    def graph(self) -> Optional[PropertyGraph]:
        """The graph produced by ``RETURN GRAPH`` / ``CONSTRUCT``."""

    @abc.abstractmethod
    def explain(self) -> str:
        """Pretty-print the IR / logical / relational plans (the reference's
        ``result.plans`` explain facility; SURVEY.md §5.5)."""


class CypherSession(abc.ABC):
    """A Cypher session: catalog + query entry points."""

    @property
    @abc.abstractmethod
    def catalog(self) -> "PropertyGraphCatalog":
        ...

    @abc.abstractmethod
    def cypher(self, query: str, parameters: Optional[Mapping[str, Any]] = None) -> CypherResult:
        ...


class PropertyGraphCatalog(abc.ABC):
    """Catalog of graphs addressable by qualified name, backed by data
    sources registered per namespace."""

    @abc.abstractmethod
    def graph(self, qualified_name) -> PropertyGraph:
        ...

    @abc.abstractmethod
    def store(self, name, graph: PropertyGraph) -> None:
        ...

    @abc.abstractmethod
    def delete(self, name) -> None:
        ...

    @abc.abstractmethod
    def source(self, namespace: Namespace):
        ...

    @abc.abstractmethod
    def register_source(self, namespace: Namespace, source) -> None:
        ...

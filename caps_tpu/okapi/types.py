"""The Cypher type lattice with nullability.

Mirrors the reference's ``CypherType`` family — CTNode(labels),
CTRelationship(types), scalar types, CTList(inner), CTMap, CTAny, CTNull,
CTVoid, with ``.nullable``/``.material`` and ``join``/``meet`` used for
schema inference (ref: okapi-api/.../api/types/CypherType.scala —
reconstructed, mount empty; SURVEY.md §2 "Type system").

Semantics carried over:
  * node label sets are conjunctive ("has all these labels"); join
    intersects them, meet unions them; the empty set means "any node".
  * relationship type sets are disjunctive ("one of these types"); join
    unions them, meet intersects; the empty set means "any relationship".
  * ``CTNull`` is the type of the literal null; joining it into a material
    type yields that type's nullable variant.
  * ``CTVoid`` is the bottom element (the type of an empty union).
  * ``CTInteger join CTFloat = CTNumber``.
"""
from __future__ import annotations

import dataclasses
from typing import FrozenSet, Iterable, Tuple


@dataclasses.dataclass(frozen=True)
class CypherType:
    is_nullable: bool = False

    # -- nullability --------------------------------------------------------

    @property
    def nullable(self) -> "CypherType":
        if self.is_nullable or isinstance(self, (_CTNull, _CTAny, _CTVoid)):
            return self
        return dataclasses.replace(self, is_nullable=True)

    @property
    def material(self) -> "CypherType":
        if isinstance(self, _CTAny):
            return self
        if isinstance(self, _CTNull):
            return CTVoid
        if not self.is_nullable:
            return self
        return dataclasses.replace(self, is_nullable=False)

    # -- lattice ------------------------------------------------------------

    def join(self, other: "CypherType") -> "CypherType":
        """Least upper bound of two types."""
        if self == other:
            return self
        if isinstance(self, _CTVoid):
            return other
        if isinstance(other, _CTVoid):
            return self
        if isinstance(self, _CTNull):
            return other.nullable
        if isinstance(other, _CTNull):
            return self.nullable
        if isinstance(self, _CTAny) or isinstance(other, _CTAny):
            return CTAny
        nullable = self.is_nullable or other.is_nullable
        joined = self.material._join_material(other.material)
        return joined.nullable if nullable else joined

    def _join_material(self, other: "CypherType") -> "CypherType":
        if self == other:
            return self
        if isinstance(self, _CTNode) and isinstance(other, _CTNode):
            return _CTNode(labels=self.labels & other.labels)
        if isinstance(self, _CTRelationship) and isinstance(other, _CTRelationship):
            if not self.rel_types or not other.rel_types:
                return _CTRelationship(rel_types=frozenset())
            return _CTRelationship(rel_types=self.rel_types | other.rel_types)
        if isinstance(self, _CTList) and isinstance(other, _CTList):
            return _CTList(inner=self.inner.join(other.inner))
        number = (_CTInteger, _CTFloat, _CTNumber)
        if isinstance(self, number) and isinstance(other, number):
            return CTNumber
        if isinstance(self, _CTMap) and isinstance(other, _CTMap):
            return CTMap
        return CTAny

    def meet(self, other: "CypherType") -> "CypherType":
        """Greatest lower bound of two types."""
        if self == other:
            return self
        if isinstance(self, _CTAny):
            return other
        if isinstance(other, _CTAny):
            return self
        if isinstance(self, _CTVoid) or isinstance(other, _CTVoid):
            return CTVoid
        if isinstance(self, _CTNull):
            return CTNull if other.is_nullable else CTVoid
        if isinstance(other, _CTNull):
            return CTNull if self.is_nullable else CTVoid
        nullable = self.is_nullable and other.is_nullable
        met = self.material._meet_material(other.material)
        return met.nullable if nullable else met

    def _meet_material(self, other: "CypherType") -> "CypherType":
        if self == other:
            return self
        if isinstance(self, _CTNode) and isinstance(other, _CTNode):
            return _CTNode(labels=self.labels | other.labels)
        if isinstance(self, _CTRelationship) and isinstance(other, _CTRelationship):
            if not self.rel_types:
                return other
            if not other.rel_types:
                return self
            common = self.rel_types & other.rel_types
            return _CTRelationship(rel_types=common) if common else CTVoid
        if isinstance(self, _CTNumber):
            if isinstance(other, (_CTInteger, _CTFloat)):
                return other
        if isinstance(other, _CTNumber):
            if isinstance(self, (_CTInteger, _CTFloat)):
                return self
        if isinstance(self, _CTList) and isinstance(other, _CTList):
            inner = self.inner.meet(other.inner)
            return _CTList(inner=inner)
        return CTVoid

    def subtype_of(self, other: "CypherType") -> bool:
        return self.join(other) == other

    def could_be(self, other: "CypherType") -> bool:
        return self.meet(other) != CTVoid

    # -- convenience --------------------------------------------------------

    @property
    def name(self) -> str:
        return type(self).__name__.lstrip("_")

    def __repr__(self) -> str:
        base = self._repr_material()
        return f"{base}?" if self.is_nullable else base

    def _repr_material(self) -> str:
        return self.name


@dataclasses.dataclass(frozen=True, repr=False)
class _CTVoid(CypherType):
    pass


@dataclasses.dataclass(frozen=True, repr=False)
class _CTNull(CypherType):
    is_nullable: bool = True


@dataclasses.dataclass(frozen=True, repr=False)
class _CTAny(CypherType):
    is_nullable: bool = True


@dataclasses.dataclass(frozen=True, repr=False)
class _CTBoolean(CypherType):
    pass


@dataclasses.dataclass(frozen=True, repr=False)
class _CTInteger(CypherType):
    pass


@dataclasses.dataclass(frozen=True, repr=False)
class _CTFloat(CypherType):
    pass


@dataclasses.dataclass(frozen=True, repr=False)
class _CTNumber(CypherType):
    pass


@dataclasses.dataclass(frozen=True, repr=False)
class _CTString(CypherType):
    pass


@dataclasses.dataclass(frozen=True, repr=False)
class _CTMap(CypherType):
    pass


@dataclasses.dataclass(frozen=True, repr=False)
class _CTPath(CypherType):
    pass


@dataclasses.dataclass(frozen=True, repr=False)
class _CTDate(CypherType):
    pass


@dataclasses.dataclass(frozen=True, repr=False)
class _CTDateTime(CypherType):
    pass


@dataclasses.dataclass(frozen=True, repr=False)
class _CTDuration(CypherType):
    pass


@dataclasses.dataclass(frozen=True, repr=False)
class _CTNode(CypherType):
    labels: FrozenSet[str] = frozenset()

    def _repr_material(self) -> str:
        if not self.labels:
            return "CTNode"
        return "CTNode(" + ":".join(sorted(self.labels)) + ")"


@dataclasses.dataclass(frozen=True, repr=False)
class _CTRelationship(CypherType):
    rel_types: FrozenSet[str] = frozenset()

    def _repr_material(self) -> str:
        if not self.rel_types:
            return "CTRelationship"
        return "CTRelationship(" + "|".join(sorted(self.rel_types)) + ")"


@dataclasses.dataclass(frozen=True, repr=False)
class _CTList(CypherType):
    inner: CypherType = None  # type: ignore[assignment]

    def _repr_material(self) -> str:
        return f"CTList({self.inner!r})"


# Singletons / constructors matching the reference's naming.
CTVoid = _CTVoid()
CTNull = _CTNull()
CTAny = _CTAny()
CTBoolean = _CTBoolean()
CTInteger = _CTInteger()
CTFloat = _CTFloat()
CTNumber = _CTNumber()
CTString = _CTString()
CTMap = _CTMap()
CTPath = _CTPath()
CTDate = _CTDate()
CTDateTime = _CTDateTime()
CTDuration = _CTDuration()


def CTNode(labels: Iterable[str] = ()) -> _CTNode:
    if isinstance(labels, str):
        labels = (labels,)
    return _CTNode(labels=frozenset(labels))


def CTRelationship(rel_types: Iterable[str] = ()) -> _CTRelationship:
    if isinstance(rel_types, str):
        rel_types = (rel_types,)
    return _CTRelationship(rel_types=frozenset(rel_types))


def CTList(inner: CypherType) -> _CTList:
    return _CTList(inner=inner)


def join_all(types: Iterable[CypherType]) -> CypherType:
    out: CypherType = CTVoid
    for t in types:
        out = out.join(t)
    return out


def parse_type(s: str) -> CypherType:
    """Inverse of ``repr``: parse "CTInteger?", "CTNode(A:B)",
    "CTList(CTString)" etc. (used by the fs data source's schema.json)."""
    s = s.strip()
    nullable = s.endswith("?")
    if nullable:
        s = s[:-1]
    simple = {
        "CTVoid": CTVoid, "CTNull": CTNull, "CTAny": CTAny,
        "CTBoolean": CTBoolean, "CTInteger": CTInteger, "CTFloat": CTFloat,
        "CTNumber": CTNumber, "CTString": CTString, "CTMap": CTMap,
        "CTPath": CTPath, "CTNode": _CTNode(), "CTRelationship": _CTRelationship(),
        "CTDate": CTDate, "CTDateTime": CTDateTime, "CTDuration": CTDuration,
    }
    if s in simple:
        t = simple[s]
    elif s.startswith("CTNode(") and s.endswith(")"):
        t = CTNode(s[len("CTNode("):-1].split(":"))
    elif s.startswith("CTRelationship(") and s.endswith(")"):
        t = CTRelationship(s[len("CTRelationship("):-1].split("|"))
    elif s.startswith("CTList(") and s.endswith(")"):
        t = CTList(parse_type(s[len("CTList("):-1]))
    else:
        raise ValueError(f"cannot parse CypherType {s!r}")
    return t.nullable if nullable else t


def from_python(value) -> CypherType:
    """Infer the CypherType of a plain Python value (literals, parameters)."""
    from caps_tpu.okapi import values as v
    if value is None:
        return CTNull
    if isinstance(value, bool):
        return CTBoolean
    if isinstance(value, int):
        return CTInteger
    if isinstance(value, float):
        return CTFloat
    if isinstance(value, str):
        return CTString
    if isinstance(value, v.CypherDate):
        return CTDate
    if isinstance(value, v.CypherDateTime):
        return CTDateTime
    if isinstance(value, v.CypherDuration):
        return CTDuration
    if isinstance(value, v.CypherNode):
        return CTNode(value.labels)
    if isinstance(value, v.CypherRelationship):
        return CTRelationship((value.rel_type,))
    if isinstance(value, (list, tuple, v.CypherList)):
        return CTList(join_all(from_python(x) for x in value))
    if isinstance(value, (dict, v.CypherMap)):
        return CTMap
    raise TypeError(f"no CypherType for Python value of type {type(value)!r}")

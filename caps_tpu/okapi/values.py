"""Runtime Cypher values, including null semantics.

Mirrors the reference's value model: CypherValue, CypherMap, CypherList,
CypherNode, CypherRelationship and the primitives (ref:
okapi-api/.../api/value/CypherValue.scala — reconstructed, mount empty;
SURVEY.md §2 "Value model").

Python adaptation: primitives stay plain Python values (``None``, ``bool``,
``int``, ``float``, ``str``, ``list``, ``dict``) — wrapping every scalar
would fight the columnar backends.  The classes here cover the structured
values that appear in materialized results, plus the Cypher comparison /
equality / ordering helpers whose semantics differ from Python's
(3-valued logic, cross-type global sort order, null handling).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, Mapping, Optional, Sequence, Tuple

# `CypherValue` as a concept = None | bool | int | float | str | list | dict
# | CypherNode | CypherRelationship.  Alias kept for API parity.
CypherValue = Any


class CypherList(list):
    """Marker subclass for lists produced by the engine (e.g. collect())."""


class CypherMap(dict):
    """Marker subclass for maps produced by the engine."""


@dataclasses.dataclass(frozen=True)
class CypherNode:
    """A materialized node: identity, labels, properties."""
    id: int
    labels: FrozenLabels = ()
    properties: Mapping[str, CypherValue] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        object.__setattr__(self, "labels", tuple(sorted(self.labels)))
        object.__setattr__(self, "properties", dict(self.properties))

    def __eq__(self, other):  # identity semantics, like the reference
        return isinstance(other, CypherNode) and other.id == self.id

    def __hash__(self):
        return hash(("node", self.id))

    def __repr__(self):
        lbl = "".join(f":{l}" for l in self.labels)
        props = ", ".join(f"{k}: {_repr_value(v)}" for k, v in sorted(self.properties.items()))
        return f"({lbl} {{{props}}})" if props else f"({lbl})"


@dataclasses.dataclass(frozen=True)
class CypherRelationship:
    """A materialized relationship: identity, endpoints, type, properties."""
    id: int
    start: int
    end: int
    rel_type: str = ""
    properties: Mapping[str, CypherValue] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        object.__setattr__(self, "properties", dict(self.properties))

    def __eq__(self, other):
        return isinstance(other, CypherRelationship) and other.id == self.id

    def __hash__(self):
        return hash(("rel", self.id))

    def __repr__(self):
        props = ", ".join(f"{k}: {_repr_value(v)}" for k, v in sorted(self.properties.items()))
        body = f":{self.rel_type}" + (f" {{{props}}}" if props else "")
        return f"[{body}]"


FrozenLabels = Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class CypherPath:
    """A materialized path: alternating nodes and relationships,
    ``len(nodes) == len(rels) + 1``.  Equality is by the node/rel id
    sequence (path identity), mirroring the reference's path value
    (ref: okapi-api value model — reconstructed, mount empty;
    SURVEY.md §2 "Value model")."""
    nodes: Tuple[CypherNode, ...]
    rels: Tuple["CypherRelationship", ...] = ()

    def __post_init__(self):
        object.__setattr__(self, "nodes", tuple(self.nodes))
        object.__setattr__(self, "rels", tuple(self.rels))
        if len(self.nodes) != len(self.rels) + 1:
            raise ValueError(
                f"path needs {len(self.rels) + 1} nodes, got {len(self.nodes)}")

    @property
    def length(self) -> int:
        return len(self.rels)

    def __eq__(self, other):
        return (isinstance(other, CypherPath)
                and tuple(n.id for n in other.nodes) == tuple(n.id for n in self.nodes)
                and tuple(r.id for r in other.rels) == tuple(r.id for r in self.rels))

    def __hash__(self):
        return hash(("path", tuple(n.id for n in self.nodes),
                     tuple(r.id for r in self.rels)))

    def __repr__(self):
        parts = [repr(self.nodes[0])]
        for i, rel in enumerate(self.rels):
            prev, nxt = self.nodes[i], self.nodes[i + 1]
            if rel.start == prev.id and rel.end == nxt.id:
                parts.append(f"-{rel!r}->")
            else:  # traversed against the stored orientation
                parts.append(f"<-{rel!r}-")
            parts.append(repr(nxt))
        return "<" + "".join(parts) + ">"


def _repr_value(v: CypherValue) -> str:
    if isinstance(v, str):
        return f"'{v}'"
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    return repr(v)


# ---------------------------------------------------------------------------
# Cypher semantics helpers (3-valued logic, equality, global ordering)
# ---------------------------------------------------------------------------

def cypher_equals(a: CypherValue, b: CypherValue) -> Optional[bool]:
    """Cypher `=`: returns True/False/None (null) with 3-valued semantics."""
    if a is None or b is None:
        return None
    if isinstance(a, CypherNode) or isinstance(b, CypherNode):
        return isinstance(a, CypherNode) and isinstance(b, CypherNode) and a.id == b.id
    if isinstance(a, CypherRelationship) or isinstance(b, CypherRelationship):
        return (isinstance(a, CypherRelationship)
                and isinstance(b, CypherRelationship) and a.id == b.id)
    if isinstance(a, CypherPath) or isinstance(b, CypherPath):
        return isinstance(a, CypherPath) and isinstance(b, CypherPath) and a == b
    if isinstance(a, bool) or isinstance(b, bool):
        return isinstance(a, bool) and isinstance(b, bool) and a == b
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a == b  # Python int/float comparison is exact, no precision loss
    if isinstance(a, str) and isinstance(b, str):
        return a == b
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        if len(a) != len(b):
            return False
        out: Optional[bool] = True
        for x, y in zip(a, b):
            e = cypher_equals(x, y)
            if e is False:
                return False
            if e is None:
                out = None
        return out
    if isinstance(a, dict) and isinstance(b, dict):
        if set(a) != set(b):
            return False
        out = True
        for k in a:
            e = cypher_equals(a[k], b[k])
            if e is False:
                return False
            if e is None:
                out = None
        return out
    return False


_ORDER_RANK = {
    "map": 0, "node": 1, "rel": 2, "list": 3, "path": 3.5, "str": 4,
    "bool": 5, "num": 6, "null": 7,
}


def _order_key(v: CypherValue) -> Tuple:
    """Total order over all Cypher values (for ORDER BY): per openCypher,
    within-type natural order; nulls sort last in ascending order."""
    if v is None:
        return (_ORDER_RANK["null"],)
    if isinstance(v, bool):
        return (_ORDER_RANK["bool"], v)
    if isinstance(v, (int, float)):
        return (_ORDER_RANK["num"], v)  # int/float cross-compare exactly
    if isinstance(v, str):
        return (_ORDER_RANK["str"], v)
    if isinstance(v, CypherNode):
        return (_ORDER_RANK["node"], v.id)
    if isinstance(v, CypherRelationship):
        return (_ORDER_RANK["rel"], v.id)
    if isinstance(v, CypherPath):
        return (_ORDER_RANK["path"], tuple(n.id for n in v.nodes),
                tuple(r.id for r in v.rels))
    if isinstance(v, (list, tuple)):
        return (_ORDER_RANK["list"], tuple(_order_key(x) for x in v))
    if isinstance(v, dict):
        return (_ORDER_RANK["map"], tuple(sorted((k, _order_key(x)) for k, x in v.items())))
    raise TypeError(f"unorderable value {v!r}")


def order_key(v: CypherValue) -> Tuple:
    """Sort key for one ORDER BY item; descending order is realized by the
    caller via per-item ``reverse=True`` in a multi-pass stable sort."""
    return _order_key(v)


def cypher_lt(a: CypherValue, b: CypherValue) -> Optional[bool]:
    """Cypher `<`: null if either operand is null or the types are not
    comparable (number vs string etc.)."""
    if a is None or b is None:
        return None
    a_num = isinstance(a, (int, float)) and not isinstance(a, bool)
    b_num = isinstance(b, (int, float)) and not isinstance(b, bool)
    if a_num and b_num:
        return a < b
    if isinstance(a, str) and isinstance(b, str):
        return a < b
    if isinstance(a, bool) and isinstance(b, bool):
        return a < b
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        for x, y in zip(a, b):
            lt = cypher_lt(x, y)
            if lt is None:
                return None
            if lt:
                return True
            gt = cypher_lt(y, x)
            if gt is None:
                return None
            if gt:
                return False
        return len(a) < len(b)
    return None


def is_truthy(v: Optional[bool]) -> bool:
    """WHERE keeps a row iff the predicate is exactly true (null drops)."""
    return v is True

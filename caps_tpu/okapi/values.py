"""Runtime Cypher values, including null semantics.

Mirrors the reference's value model: CypherValue, CypherMap, CypherList,
CypherNode, CypherRelationship and the primitives (ref:
okapi-api/.../api/value/CypherValue.scala — reconstructed, mount empty;
SURVEY.md §2 "Value model").

Python adaptation: primitives stay plain Python values (``None``, ``bool``,
``int``, ``float``, ``str``, ``list``, ``dict``) — wrapping every scalar
would fight the columnar backends.  The classes here cover the structured
values that appear in materialized results, plus the Cypher comparison /
equality / ordering helpers whose semantics differ from Python's
(3-valued logic, cross-type global sort order, null handling).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, Mapping, Optional, Sequence, Tuple

# `CypherValue` as a concept = None | bool | int | float | str | list | dict
# | CypherNode | CypherRelationship.  Alias kept for API parity.
CypherValue = Any


class CypherList(list):
    """Marker subclass for lists produced by the engine (e.g. collect())."""


class CypherMap(dict):
    """Marker subclass for maps produced by the engine."""


@dataclasses.dataclass(frozen=True)
class CypherNode:
    """A materialized node: identity, labels, properties."""
    id: int
    labels: FrozenLabels = ()
    properties: Mapping[str, CypherValue] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        object.__setattr__(self, "labels", tuple(sorted(self.labels)))
        object.__setattr__(self, "properties", dict(self.properties))

    def __eq__(self, other):  # identity semantics, like the reference
        return isinstance(other, CypherNode) and other.id == self.id

    def __hash__(self):
        return hash(("node", self.id))

    def __repr__(self):
        lbl = "".join(f":{l}" for l in self.labels)
        props = ", ".join(f"{k}: {_repr_value(v)}" for k, v in sorted(self.properties.items()))
        return f"({lbl} {{{props}}})" if props else f"({lbl})"


@dataclasses.dataclass(frozen=True)
class CypherRelationship:
    """A materialized relationship: identity, endpoints, type, properties."""
    id: int
    start: int
    end: int
    rel_type: str = ""
    properties: Mapping[str, CypherValue] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        object.__setattr__(self, "properties", dict(self.properties))

    def __eq__(self, other):
        return isinstance(other, CypherRelationship) and other.id == self.id

    def __hash__(self):
        return hash(("rel", self.id))

    def __repr__(self):
        props = ", ".join(f"{k}: {_repr_value(v)}" for k, v in sorted(self.properties.items()))
        body = f":{self.rel_type}" + (f" {{{props}}}" if props else "")
        return f"[{body}]"


FrozenLabels = Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class CypherPath:
    """A materialized path: alternating nodes and relationships,
    ``len(nodes) == len(rels) + 1``.  Equality is by the node/rel id
    sequence (path identity), mirroring the reference's path value
    (ref: okapi-api value model — reconstructed, mount empty;
    SURVEY.md §2 "Value model")."""
    nodes: Tuple[CypherNode, ...]
    rels: Tuple["CypherRelationship", ...] = ()

    def __post_init__(self):
        object.__setattr__(self, "nodes", tuple(self.nodes))
        object.__setattr__(self, "rels", tuple(self.rels))
        if len(self.nodes) != len(self.rels) + 1:
            raise ValueError(
                f"path needs {len(self.rels) + 1} nodes, got {len(self.nodes)}")

    @property
    def length(self) -> int:
        return len(self.rels)

    def __eq__(self, other):
        return (isinstance(other, CypherPath)
                and tuple(n.id for n in other.nodes) == tuple(n.id for n in self.nodes)
                and tuple(r.id for r in other.rels) == tuple(r.id for r in self.rels))

    def __hash__(self):
        return hash(("path", tuple(n.id for n in self.nodes),
                     tuple(r.id for r in self.rels)))

    def __repr__(self):
        parts = [repr(self.nodes[0])]
        for i, rel in enumerate(self.rels):
            prev, nxt = self.nodes[i], self.nodes[i + 1]
            if rel.start == prev.id and rel.end == nxt.id:
                parts.append(f"-{rel!r}->")
            else:  # traversed against the stored orientation
                parts.append(f"<-{rel!r}-")
            parts.append(repr(nxt))
        return "<" + "".join(parts) + ">"


def _repr_value(v: CypherValue) -> str:
    if isinstance(v, str):
        return f"'{v}'"
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    return repr(v)


# ---------------------------------------------------------------------------
# Temporal values (round-5 VERDICT item 6; ref: okapi-api value model's
# temporal family — reconstructed, mount empty).  Minimal but real slice:
# calendar dates as epoch days, wall-clock datetimes (UTC, no zone) as
# epoch microseconds, durations as (months, days, seconds) components.
# Integer encodings make the device representation one int64 column.
# ---------------------------------------------------------------------------

_EPOCH_ORDINAL = 719_163  # datetime.date(1970, 1, 1).toordinal()


@dataclasses.dataclass(frozen=True)
class CypherDate:
    """Calendar date, stored as days since 1970-01-01 (int, may be
    negative)."""
    days: int

    @staticmethod
    def from_components(year: int, month: int = 1, day: int = 1) -> "CypherDate":
        import datetime as _dt
        return CypherDate(_dt.date(year, month, day).toordinal()
                          - _EPOCH_ORDINAL)

    @staticmethod
    def parse(s: str) -> "CypherDate":
        import datetime as _dt
        d = _dt.date.fromisoformat(s)
        return CypherDate(d.toordinal() - _EPOCH_ORDINAL)

    def _date(self):
        import datetime as _dt
        return _dt.date.fromordinal(self.days + _EPOCH_ORDINAL)

    @property
    def year(self) -> int:
        return self._date().year

    @property
    def month(self) -> int:
        return self._date().month

    @property
    def day(self) -> int:
        return self._date().day

    def iso(self) -> str:
        return self._date().isoformat()

    def plus(self, dur: "CypherDuration") -> "CypherDate":
        d = self._date()
        y, m = divmod(d.month - 1 + dur.months, 12)
        import calendar
        import datetime as _dt
        nd = min(d.day, calendar.monthrange(d.year + y, m + 1)[1])
        moved = _dt.date(d.year + y, m + 1, nd)
        # sub-day components truncate toward zero so +PT1S / -PT1S stay
        # symmetric on a date (floor would pull negatives back a full day)
        moved += _dt.timedelta(days=dur.days + int(dur.seconds / 86_400))
        return CypherDate(moved.toordinal() - _EPOCH_ORDINAL)

    def __repr__(self) -> str:
        return self.iso()


@dataclasses.dataclass(frozen=True)
class CypherDateTime:
    """Wall-clock datetime (UTC, zoneless), stored as microseconds since
    the 1970-01-01T00:00:00 epoch."""
    micros: int

    @staticmethod
    def from_components(year: int, month: int = 1, day: int = 1,
                        hour: int = 0, minute: int = 0, second: int = 0,
                        microsecond: int = 0) -> "CypherDateTime":
        import datetime as _dt
        dt = _dt.datetime(year, month, day, hour, minute, second,
                          microsecond)
        days = dt.date().toordinal() - _EPOCH_ORDINAL
        return CypherDateTime(
            days * 86_400_000_000
            + (dt.hour * 3600 + dt.minute * 60 + dt.second) * 1_000_000
            + dt.microsecond)

    @staticmethod
    def parse(s: str) -> "CypherDateTime":
        import datetime as _dt
        if s.endswith("Z") or s.endswith("z"):
            s = s[:-1] + "+00:00"
        dt = _dt.datetime.fromisoformat(s)
        if dt.tzinfo is not None:
            # normalize offset datetimes to the UTC instant (the engine's
            # datetimes are zoneless UTC wall clocks)
            dt = dt.astimezone(_dt.timezone.utc).replace(tzinfo=None)
        return CypherDateTime.from_components(
            dt.year, dt.month, dt.day, dt.hour, dt.minute, dt.second,
            dt.microsecond)

    def _datetime(self):
        import datetime as _dt
        days, rem = divmod(self.micros, 86_400_000_000)
        base = _dt.date.fromordinal(days + _EPOCH_ORDINAL)
        sec, us = divmod(rem, 1_000_000)
        h, rest = divmod(sec, 3600)
        m, s = divmod(rest, 60)
        return _dt.datetime(base.year, base.month, base.day, h, m, s, us)

    @property
    def year(self) -> int:
        return self._datetime().year

    @property
    def month(self) -> int:
        return self._datetime().month

    @property
    def day(self) -> int:
        return self._datetime().day

    @property
    def hour(self) -> int:
        return self._datetime().hour

    @property
    def minute(self) -> int:
        return self._datetime().minute

    @property
    def second(self) -> int:
        return self._datetime().second

    def date(self) -> CypherDate:
        return CypherDate(self.micros // 86_400_000_000)

    def plus(self, dur: "CypherDuration") -> "CypherDateTime":
        dt = self._datetime()
        y, m = divmod(dt.month - 1 + dur.months, 12)
        import calendar
        import datetime as _dt
        nd = min(dt.day, calendar.monthrange(dt.year + y, m + 1)[1])
        moved = dt.replace(year=dt.year + y, month=m + 1, day=nd)
        moved += _dt.timedelta(days=dur.days, seconds=dur.seconds)
        return CypherDateTime.from_components(
            moved.year, moved.month, moved.day, moved.hour, moved.minute,
            moved.second, moved.microsecond)

    def iso(self) -> str:
        return self._datetime().isoformat()

    def __repr__(self) -> str:
        return self.iso()


@dataclasses.dataclass(frozen=True)
class CypherDuration:
    """Duration as the Cypher component triple (months, days, seconds) —
    kept separate because months have no fixed length.  Not orderable
    (per openCypher); equality is componentwise."""
    months: int = 0
    days: int = 0
    seconds: int = 0

    @property
    def years_part(self) -> int:
        return self.months // 12

    def plus(self, other: "CypherDuration") -> "CypherDuration":
        return CypherDuration(self.months + other.months,
                              self.days + other.days,
                              self.seconds + other.seconds)

    def negate(self) -> "CypherDuration":
        return CypherDuration(-self.months, -self.days, -self.seconds)

    def iso(self) -> str:
        # components render with their own signs (Neo4j style, e.g.
        # 'PT-30S'); truncate toward zero so negatives don't borrow
        def tdiv(a: int, b: int):
            q = int(a / b)
            return q, a - q * b

        out = "P"
        if self.months:
            y, m = tdiv(self.months, 12)
            if y:
                out += f"{y}Y"
            if m:
                out += f"{m}M"
        if self.days:
            out += f"{self.days}D"
        if self.seconds:
            h, rest = tdiv(self.seconds, 3600)
            m, s = tdiv(rest, 60)
            out += "T"
            if h:
                out += f"{h}H"
            if m:
                out += f"{m}M"
            if s:
                out += f"{s}S"
        return out if out != "P" else "PT0S"

    def __repr__(self) -> str:
        return self.iso()


def temporal_construct(name: str, value=None):
    """Shared ``date()``/``datetime()``/``localdatetime()``/``duration()``
    constructor used by both expression evaluators and the graph factory.
    Accepts ISO strings, component maps, or an already-typed value; null
    propagates.  Raises ValueError on malformed input."""
    if value is None:
        raise ValueError(
            f"{name}() without an argument (current time) is "
            "non-deterministic and not supported; pass a string or map")
    name = name.lower()
    if name == "date":
        if isinstance(value, CypherDate):
            return value
        if isinstance(value, CypherDateTime):
            return value.date()
        if isinstance(value, str):
            return CypherDate.parse(value)
        if isinstance(value, Mapping):
            return CypherDate.from_components(
                int(value["year"]), int(value.get("month", 1)),
                int(value.get("day", 1)))
    elif name in ("datetime", "localdatetime"):
        if isinstance(value, CypherDateTime):
            return value
        if isinstance(value, CypherDate):
            return CypherDateTime(value.days * 86_400_000_000)
        if isinstance(value, str):
            return CypherDateTime.parse(value)
        if isinstance(value, Mapping):
            return CypherDateTime.from_components(
                int(value["year"]), int(value.get("month", 1)),
                int(value.get("day", 1)), int(value.get("hour", 0)),
                int(value.get("minute", 0)), int(value.get("second", 0)))
    elif name == "duration":
        if isinstance(value, CypherDuration):
            return value
        if isinstance(value, str):
            return _parse_iso_duration(value)
        if isinstance(value, Mapping):
            months = int(value.get("years", 0)) * 12 \
                + int(value.get("months", 0))
            days = int(value.get("weeks", 0)) * 7 + int(value.get("days", 0))
            seconds = (int(value.get("hours", 0)) * 3600
                       + int(value.get("minutes", 0)) * 60
                       + int(value.get("seconds", 0)))
            return CypherDuration(months, days, seconds)
    raise ValueError(f"cannot construct {name}() from {value!r}")


def _parse_iso_duration(s: str) -> CypherDuration:
    import re as _re
    m = _re.fullmatch(
        r"P(?:(\d+)Y)?(?:(\d+)M)?(?:(\d+)W)?(?:(\d+)D)?"
        r"(?:T(?:(\d+)H)?(?:(\d+)M)?(?:(\d+)S)?)?", s)
    if m is None or s in ("P", "PT"):
        raise ValueError(f"malformed ISO-8601 duration {s!r}")
    y, mo, w, d, h, mi, sec = (int(g) if g else 0 for g in m.groups())
    return CypherDuration(y * 12 + mo, w * 7 + d,
                          h * 3600 + mi * 60 + sec)


_TEMPORAL_FIELDS = {
    CypherDate: {"year": "year", "month": "month", "day": "day"},
    CypherDateTime: {"year": "year", "month": "month", "day": "day",
                     "hour": "hour", "minute": "minute", "second": "second"},
}


def temporal_component(v, key: str):
    """``.year``/``.month``/... accessor on a temporal value (None when
    the component doesn't exist on that type)."""
    if isinstance(v, CypherDuration):
        k = key.lower()
        if k == "months":
            return v.months
        if k == "years":
            return v.months // 12
        if k == "days":
            return v.days
        if k == "seconds":
            return v.seconds
        if k == "hours":
            return v.seconds // 3600
        if k == "minutes":
            return v.seconds // 60
        return None
    fields = _TEMPORAL_FIELDS.get(type(v))
    if fields is None or key.lower() not in fields:
        return None
    return getattr(v, fields[key.lower()])


def is_temporal(v) -> bool:
    return isinstance(v, (CypherDate, CypherDateTime, CypherDuration))


# ---------------------------------------------------------------------------
# Cypher semantics helpers (3-valued logic, equality, global ordering)
# ---------------------------------------------------------------------------

def cypher_equals(a: CypherValue, b: CypherValue) -> Optional[bool]:
    """Cypher `=`: returns True/False/None (null) with 3-valued semantics."""
    if a is None or b is None:
        return None
    if isinstance(a, CypherNode) or isinstance(b, CypherNode):
        return isinstance(a, CypherNode) and isinstance(b, CypherNode) and a.id == b.id
    if isinstance(a, CypherRelationship) or isinstance(b, CypherRelationship):
        return (isinstance(a, CypherRelationship)
                and isinstance(b, CypherRelationship) and a.id == b.id)
    if isinstance(a, CypherPath) or isinstance(b, CypherPath):
        return isinstance(a, CypherPath) and isinstance(b, CypherPath) and a == b
    if isinstance(a, bool) or isinstance(b, bool):
        return isinstance(a, bool) and isinstance(b, bool) and a == b
    if isinstance(a, (CypherDate, CypherDateTime, CypherDuration)) \
            or isinstance(b, (CypherDate, CypherDateTime, CypherDuration)):
        return type(a) is type(b) and a == b
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a == b  # Python int/float comparison is exact, no precision loss
    if isinstance(a, str) and isinstance(b, str):
        return a == b
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        if len(a) != len(b):
            return False
        out: Optional[bool] = True
        for x, y in zip(a, b):
            e = cypher_equals(x, y)
            if e is False:
                return False
            if e is None:
                out = None
        return out
    if isinstance(a, dict) and isinstance(b, dict):
        if set(a) != set(b):
            return False
        out = True
        for k in a:
            e = cypher_equals(a[k], b[k])
            if e is False:
                return False
            if e is None:
                out = None
        return out
    return False


_ORDER_RANK = {
    "map": 0, "node": 1, "rel": 2, "list": 3, "path": 3.5, "str": 4,
    "bool": 5, "num": 6, "datetime": 6.2, "date": 6.4, "duration": 6.6,
    "null": 7,
}


def _order_key(v: CypherValue) -> Tuple:
    """Total order over all Cypher values (for ORDER BY): per openCypher,
    within-type natural order; nulls sort last in ascending order."""
    if v is None:
        return (_ORDER_RANK["null"],)
    if isinstance(v, bool):
        return (_ORDER_RANK["bool"], v)
    if isinstance(v, (int, float)):
        return (_ORDER_RANK["num"], v)  # int/float cross-compare exactly
    if isinstance(v, str):
        return (_ORDER_RANK["str"], v)
    if isinstance(v, CypherNode):
        return (_ORDER_RANK["node"], v.id)
    if isinstance(v, CypherRelationship):
        return (_ORDER_RANK["rel"], v.id)
    if isinstance(v, CypherPath):
        return (_ORDER_RANK["path"], tuple(n.id for n in v.nodes),
                tuple(r.id for r in v.rels))
    if isinstance(v, CypherDate):
        return (_ORDER_RANK["date"], v.days)
    if isinstance(v, CypherDateTime):
        return (_ORDER_RANK["datetime"], v.micros)
    if isinstance(v, CypherDuration):
        # durations are not comparable in Cypher; a deterministic ORDER BY
        # key is still required — component tuple
        return (_ORDER_RANK["duration"], v.months, v.days, v.seconds)
    if isinstance(v, (list, tuple)):
        return (_ORDER_RANK["list"], tuple(_order_key(x) for x in v))
    if isinstance(v, dict):
        return (_ORDER_RANK["map"], tuple(sorted((k, _order_key(x)) for k, x in v.items())))
    raise TypeError(f"unorderable value {v!r}")


def order_key(v: CypherValue) -> Tuple:
    """Sort key for one ORDER BY item; descending order is realized by the
    caller via per-item ``reverse=True`` in a multi-pass stable sort."""
    return _order_key(v)


def cypher_lt(a: CypherValue, b: CypherValue) -> Optional[bool]:
    """Cypher `<`: null if either operand is null or the types are not
    comparable (number vs string etc.)."""
    if a is None or b is None:
        return None
    a_num = isinstance(a, (int, float)) and not isinstance(a, bool)
    b_num = isinstance(b, (int, float)) and not isinstance(b, bool)
    if a_num and b_num:
        return a < b
    if isinstance(a, str) and isinstance(b, str):
        return a < b
    if isinstance(a, bool) and isinstance(b, bool):
        return a < b
    if isinstance(a, CypherDate) and isinstance(b, CypherDate):
        return a.days < b.days
    if isinstance(a, CypherDateTime) and isinstance(b, CypherDateTime):
        return a.micros < b.micros
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        for x, y in zip(a, b):
            lt = cypher_lt(x, y)
            if lt is None:
                return None
            if lt:
                return True
            gt = cypher_lt(y, x)
            if gt is None:
                return None
            if gt:
                return False
        return len(a) < len(b)
    return None


def is_truthy(v: Optional[bool]) -> bool:
    """WHERE keeps a row iff the predicate is exactly true (null drops)."""
    return v is True

"""Pallas TPU kernels for the hot relational operators.

SURVEY.md §2 "native components": the reference leans on Spark's Tungsten
(whole-stage codegen) and shuffle for its performance-critical paths; the
TPU-native equivalents are hand-written Pallas/Mosaic kernels.  Every
kernel here is a real ``pallas_call`` with a ``jax.numpy`` reference twin
(``*_ref``) used for differential testing (SURVEY.md §7 step 6).

Kernels run compiled on TPU and in interpreter mode everywhere else, so
the unit suite (CPU, 8 virtual devices) exercises the same kernel code.
"""
from caps_tpu.ops.segment import (
    dense_segment_agg,
    dense_segment_agg_ref,
    dense_segment_agg_sharded,
    default_interpret,
)
from caps_tpu.ops.expand import (
    DeviceCSR,
    build_csr,
    expand_positions,
    expand_positions_ref,
    join_expand_via_positions,
)
from caps_tpu.ops.probe import pallas_usable

__all__ = [
    "dense_segment_agg",
    "dense_segment_agg_ref",
    "dense_segment_agg_sharded",
    "default_interpret",
    "DeviceCSR",
    "build_csr",
    "expand_positions",
    "expand_positions_ref",
    "join_expand_via_positions",
    "pallas_usable",
]

"""Runtime capability probe for compiled Pallas kernels.

The engine's Pallas kernels are differential-tested in interpreter mode
everywhere, but whether they *compile* on the active TPU stack depends on
the toolchain (e.g. remote-compile transports may reject scalar-prefetch
grids, or hang on specific kernel shapes).  A broken kernel must degrade
to its jnp twin, never crash or wedge a query — so the first compiled use
is gated by a one-time probe that builds representative kernels in a
subprocess (immune to compiler hangs) and caches the verdict on disk per
jaxlib version.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Optional

_VERDICT: Optional[bool] = None

_PROBE_SRC = r"""
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import functools
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# family 1: plain grid + iota/compare/reduce (segment aggregation shape)
def k1(x_ref, o_ref):
    t = jax.lax.broadcasted_iota(jnp.int32, (256, 128), 1)
    offs = x_ref[:].reshape(256, 1)
    o_ref[:] = jnp.sum((offs <= t).astype(jnp.int32), axis=1,
                       dtype=jnp.int32)
x = jnp.arange(256, dtype=jnp.int32)
out = pl.pallas_call(k1, out_shape=jax.ShapeDtypeStruct((256,), jnp.int32))(x)
out.block_until_ready()

# family 2: scalar-prefetch grid with data-dependent block indexing
def k2(blk_ref, x_ref, o_ref):
    o_ref[:] = x_ref[:] * 2
tile, n_tiles = 256, 4
xs = jnp.arange(tile * n_tiles, dtype=jnp.int32)
blk = jnp.arange(n_tiles, dtype=jnp.int32)
grid_spec = pltpu.PrefetchScalarGridSpec(
    num_scalar_prefetch=1,
    grid=(n_tiles,),
    in_specs=[pl.BlockSpec((tile,), lambda i, blk: (blk[i],),
                           memory_space=pltpu.VMEM)],
    out_specs=[pl.BlockSpec((tile,), lambda i, blk: (i,),
                            memory_space=pltpu.VMEM)],
)
out2 = pl.pallas_call(k2, grid_spec=grid_spec,
                      out_shape=[jax.ShapeDtypeStruct((tile * n_tiles,),
                                                      jnp.int32)])(blk, xs)
out2[0].block_until_ready()
print("PALLAS_PROBE_OK")
"""


def _cache_path() -> str:
    import jaxlib
    ver = getattr(jaxlib, "__version__", "unknown")
    return os.path.join(os.path.expanduser("~"), ".cache",
                        f"caps_tpu_pallas_probe_{ver}.json")


_PALLAS_ERR_MARKERS = ("pallas", "mosaic", "RecursionError",
                       "remote_compile", "tpu_compile")


def pallas_usable(timeout_s: float = 180.0) -> bool:
    """True if compiled Pallas kernels work on the default backend.

    Non-TPU backends always return True (kernels run in interpreter mode
    there).  On TPU the verdict comes from a subprocess probe, cached in
    memory and on disk.  ``CAPS_TPU_PALLAS_PROBE=1`` / ``0`` overrides
    the probe entirely (and is the recovery knob for a stale cached
    verdict — delete the cache file or set the env).  A subprocess that
    failed WITHOUT a Pallas/Mosaic-shaped error (e.g. it could not
    acquire an exclusively-held local device) does not condemn the
    stack — the probe retries in-process, where only the quick failure
    modes can occur.
    """
    global _VERDICT
    override = os.environ.get("CAPS_TPU_PALLAS_PROBE")
    if override is not None:
        return override.strip().lower() in ("1", "true", "yes", "on")
    if _VERDICT is not None:
        return _VERDICT
    import jax
    if jax.default_backend() != "tpu":
        _VERDICT = True
        return True
    path = _cache_path()
    try:
        with open(path) as f:
            _VERDICT = bool(json.load(f)["usable"])
            return _VERDICT
    except Exception:
        pass
    reason = ""
    try:
        proc = subprocess.run([sys.executable, "-c", _PROBE_SRC],
                              capture_output=True, text=True,
                              timeout=timeout_s)
        ok = proc.returncode == 0 and "PALLAS_PROBE_OK" in proc.stdout
        if not ok:
            err = (proc.stderr or "") + (proc.stdout or "")
            reason = err[-500:]
            if not any(m.lower() in err.lower()
                       for m in _PALLAS_ERR_MARKERS):
                # failure unrelated to Pallas (device contention, env):
                # probe in-process — crash-style failures raise quickly
                ok, reason = _probe_inprocess()
    except subprocess.TimeoutExpired:
        ok, reason = False, f"probe timed out after {timeout_s}s"
    except Exception as ex:
        ok, reason = _probe_inprocess()
        reason = reason or str(ex)
    if not ok:
        import logging
        logging.getLogger("caps_tpu").warning(
            "compiled Pallas kernels disabled on this TPU stack "
            "(falling back to jnp twins): %s — override with "
            "CAPS_TPU_PALLAS_PROBE=1 or delete %s", reason.strip()[:200],
            path)
    _VERDICT = ok
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump({"usable": ok, "reason": reason.strip()[:500]}, f)
    except Exception:
        pass
    return ok


def _probe_inprocess():
    """Last-resort probe in this process (no hang protection; used only
    when the subprocess failed for reasons unrelated to Pallas)."""
    try:
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def k1(x_ref, o_ref):
            t = jax.lax.broadcasted_iota(jnp.int32, (256, 128), 1)
            offs = x_ref[:].reshape(256, 1)
            o_ref[:] = jnp.sum((offs <= t).astype(jnp.int32), axis=1,
                               dtype=jnp.int32)

        x = jnp.arange(256, dtype=jnp.int32)
        pl.pallas_call(
            k1, out_shape=jax.ShapeDtypeStruct((256,), jnp.int32)
        )(x).block_until_ready()

        # scalar-prefetch grids are the feature remote-compile stacks
        # reject; the engine's expand kernel needs them
        from jax.experimental.pallas import tpu as pltpu

        def k2(blk_ref, x_ref, o_ref):
            o_ref[:] = x_ref[:] * 2

        tile, n_tiles = 256, 4
        xs = jnp.arange(tile * n_tiles, dtype=jnp.int32)
        blk = jnp.arange(n_tiles, dtype=jnp.int32)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_tiles,),
            in_specs=[pl.BlockSpec((tile,), lambda i, b: (b[i],),
                                   memory_space=pltpu.VMEM)],
            out_specs=[pl.BlockSpec((tile,), lambda i, b: (i,),
                                    memory_space=pltpu.VMEM)],
        )
        out = pl.pallas_call(
            k2, grid_spec=grid_spec,
            out_shape=[jax.ShapeDtypeStruct((tile * n_tiles,), jnp.int32)],
        )(blk, xs)
        out[0].block_until_ready()
        return True, ""
    except Exception as ex:
        return False, str(ex)[:500]

"""Runtime capability probe for compiled Pallas kernels.

The engine's Pallas kernels are differential-tested in interpreter mode
everywhere, but whether they *compile* on the active TPU stack depends on
the toolchain — and not uniformly: remote-compile transports have been
observed to reject scalar-prefetch grids (and 1-D blocked operands) while
compiling plain-grid and full-tile kernels fine.  A broken kernel must
degrade to its jnp twin, never crash or wedge a query — so the first
compiled use is gated by a one-time probe that builds one representative
kernel per FEATURE FAMILY, each in its OWN subprocess (immune to compiler
hangs, and a hang in one family cannot condemn the others), and caches
per-family verdicts on disk per jaxlib version:

    basic    — the REAL segment-histogram kernel at a multi-row-tile
               shape (1-D blocked operands; a single-block mini-kernel
               passed while blocked operands failed on v5e)
    prefetch — PrefetchScalarGridSpec with data-dependent block indexing
               (the CSR expand-positions kernel)
    sort     — grid-stepped compare-exchange with sublane reshape/concat
               swaps + tile transposes (the bitonic sort kernel)

A subprocess that failed WITHOUT a Pallas/Mosaic-shaped error (e.g. it
could not acquire an exclusively-held device) does not condemn the
family — it stays unknown=False for this process WITHOUT writing the
disk cache, so a healthy later process re-probes.  (No family retries
in-process anymore: every probe now compiles a real kernel, and an
in-process compile has no hang protection — a hung remote compile would
wedge the engine process itself.)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Dict, Optional

FEATURES = ("basic", "prefetch", "sort")

_VERDICT: Optional[Dict[str, bool]] = None

_COMMON = r"""
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import functools
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
"""

_PROBE_SRCS = {
    # the real segment-histogram kernel at a MULTI-row-tile shape — a
    # single-block mini-kernel passed here while the real kernel's
    # blocked operands failed layout verification on the live stack
    # (1-D blocks < T(1024)), so probe the thing itself, like "sort"
    "basic": _COMMON + r"""
import sys
sys.path.insert(0, %(repo)r)
import numpy as np
from caps_tpu.ops.segment import dense_segment_agg, dense_segment_agg_ref
rng = np.random.RandomState(0)
# two shapes so BOTH output tilings compile: segs=130 -> one whole-array
# 256-slot block; segs=1500 -> seg_tile 1024, TWO segment tiles.  n=4096
# -> four 1024-row tiles.  Two kinds cover the sum/accumulate and the
# min/max reduce codegen paths.
for segs, kind in ((130, "count"), (1500, "max_f32")):
    n = 4096
    codes = jnp.asarray(rng.randint(0, segs, n).astype(np.int32))
    ok = jnp.asarray(rng.rand(n) < 0.9)
    vals = jnp.asarray(rng.randn(n).astype(np.float32))
    got = dense_segment_agg(codes, ok, vals, segs, kind, interpret=False)
    got.block_until_ready()
    want = dense_segment_agg_ref(codes, ok, vals, segs, kind)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=1e-5)
print("PALLAS_PROBE_OK", flush=True)
""" % {"repo": os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))},
    # scalar-prefetch grid with data-dependent block indexing
    "prefetch": _COMMON + r"""
def k2(blk_ref, x_ref, o_ref):
    o_ref[:] = x_ref[:] * 2
tile, n_tiles = 256, 4
xs = jnp.arange(tile * n_tiles, dtype=jnp.int32)
blk = jnp.arange(n_tiles, dtype=jnp.int32)
grid_spec = pltpu.PrefetchScalarGridSpec(
    num_scalar_prefetch=1,
    grid=(n_tiles,),
    in_specs=[pl.BlockSpec((tile,), lambda i, blk: (blk[i],),
                           memory_space=pltpu.VMEM)],
    out_specs=[pl.BlockSpec((tile,), lambda i, blk: (i,),
                            memory_space=pltpu.VMEM)],
)
out2 = pl.pallas_call(k2, grid_spec=grid_spec,
                      out_shape=[jax.ShapeDtypeStruct((tile * n_tiles,),
                                                      jnp.int32)])(blk, xs)
out2[0].block_until_ready()
print("PALLAS_PROBE_OK", flush=True)
""",
    # the real sort kernel at its smallest capacity (grid-stepped
    # compare-exchange, reshape/concat swaps, transposes, revisited
    # aliased blocks) — representative mini-kernels have proven too
    # optimistic for this family, so probe the thing itself
    "sort": _COMMON + r"""
import sys
sys.path.insert(0, %(repo)r)
import numpy as np
from caps_tpu.ops.sort import sort_perm_pallas
from caps_tpu.backends.tpu import kernels as K
rng = np.random.RandomState(0)
keys = [jnp.asarray(rng.randint(0, 50, 256).astype(np.int64))]
got = sort_perm_pallas(keys, 256)
got.block_until_ready()
want = K.sort_perm(keys, 256)
np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
print("PALLAS_PROBE_OK", flush=True)
""" % {"repo": os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))},
}

_MARKER = "PALLAS_PROBE_OK"

# No family retries in-process (the old basic-family retry was removed
# when its probe became the real multi-tile segment kernel: an
# in-process compile has no hang protection, and a hung remote compile
# wedges the whole engine process — TUNNEL_r05.md probes #5/#7).  A
# non-conclusive subprocess failure leaves the family unknown=False for
# this process (twins, no disk write); a healthy later process re-probes.
_PALLAS_ERR_MARKERS = ("pallas", "mosaic", "RecursionError",
                       "remote_compile", "tpu_compile",
                       # real-kernel probes compare against the jnp twin;
                       # a numerical mismatch is a CONCLUSIVE wrong-results
                       # verdict that must reach the disk cache
                       "Mismatched elements", "Arrays are not")


def _cache_path() -> str:
    import jaxlib
    ver = getattr(jaxlib, "__version__", "unknown")
    return os.path.join(os.path.expanduser("~"), ".cache",
                        f"caps_tpu_pallas_probe4_{ver}.json")


def _probe_family(feature: str, timeout_s: float):
    """(verdict, reason, conclusive): run one family in a subprocess.
    Non-conclusive failures (no Pallas-shaped error) must not be written
    to the disk cache."""
    try:
        proc = subprocess.run([sys.executable, "-c", _PROBE_SRCS[feature]],
                              capture_output=True, text=True,
                              timeout=timeout_s)
        if proc.returncode == 0 and _MARKER in (proc.stdout or ""):
            return True, "", True
        err = (proc.stderr or "") + (proc.stdout or "")
        pallas_shaped = any(m.lower() in err.lower()
                            for m in _PALLAS_ERR_MARKERS)
        return False, err[-400:], pallas_shaped
    except subprocess.TimeoutExpired:
        # a compiler hang IS a verdict for the hang-prone families
        return False, f"probe timed out after {timeout_s}s", True
    except Exception as ex:  # environment failure — not conclusive
        return False, str(ex)[:400], False


_SANE: Optional[bool] = None


def _device_sane() -> bool:
    """Can a THROWAWAY subprocess reach the device?  False either when
    the device/tunnel is wedged (family timeouts would be transport
    verdicts, not compiler ones) or when this process holds the device
    exclusively (subprocess probes can't run; in-process can)."""
    global _SANE
    if _SANE is None:
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax, jax.numpy as jnp; "
                 "print(int(jnp.arange(8).sum()))"],
                capture_output=True, text=True, timeout=90.0)
            _SANE = proc.returncode == 0
        except Exception:
            _SANE = False
    return _SANE


def pallas_usable(feature: str = "basic", timeout_s: float = 240.0) -> bool:
    """True if compiled Pallas kernels of this feature family work on the
    default backend.

    Non-TPU backends always return True (kernels run in interpreter mode
    there).  On TPU each family is probed LAZILY on first request — a
    config-gated family (e.g. the sort kernel behind use_sort_kernel)
    costs nothing until something actually asks for it — and verdicts
    are cached in memory and merged per-family into the on-disk cache.
    ``CAPS_TPU_PALLAS_PROBE=1`` / ``0`` overrides every family (and is
    the recovery knob for a stale cached verdict — delete the cache file
    or set the env)."""
    assert feature in FEATURES, feature
    global _VERDICT
    override = os.environ.get("CAPS_TPU_PALLAS_PROBE")
    if override is not None:
        return override.strip().lower() in ("1", "true", "yes", "on")
    if _VERDICT is None:
        _VERDICT = {}
    if feature in _VERDICT:
        return _VERDICT[feature]
    import jax
    if jax.default_backend() != "tpu":
        for f in FEATURES:
            _VERDICT[f] = True
        return True
    path = _cache_path()
    cached = {}
    try:
        with open(path) as f:
            cached = json.load(f)
        if not isinstance(cached, dict):
            cached = {}  # corrupt cache: self-heal on next write
    except Exception:
        pass
    if feature in cached:
        _VERDICT[feature] = bool(cached[feature])
        return _VERDICT[feature]

    import logging
    log = logging.getLogger("caps_tpu")
    if not _device_sane():
        # Unreachable from a subprocess: wedged transport or an
        # exclusively-held device.  The two are indistinguishable from
        # here, and an in-process attempt would hang forever on a wedged
        # transport (block_until_ready is not interruptible), so the
        # only safe verdict is False — in-memory ONLY, never cached; a
        # healthy later process re-probes, and CAPS_TPU_PALLAS_PROBE=1
        # is the documented override for exclusive-hold stacks.
        log.warning(
            "compiled Pallas %r kernels disabled for this process "
            "(not cached): device unreachable from probe subprocess "
            "(wedged transport or exclusively-held device) — override "
            "with CAPS_TPU_PALLAS_PROBE=1", feature)
        _VERDICT[feature] = False
        return False

    ok, reason, conclusive = _probe_family(feature, timeout_s)
    if not ok:
        log.warning(
            "compiled Pallas %r kernels disabled on this TPU stack "
            "(falling back to jnp twins): %s — override with "
            "CAPS_TPU_PALLAS_PROBE=1 or delete %s", feature,
            reason.strip()[:200], path)
    _VERDICT[feature] = ok
    if conclusive:
        # merge this family's verdict; inconclusive ones (contention,
        # env) stay in-memory only so a healthy later process re-probes
        try:
            cached[feature] = ok
            reasons = dict(cached.get("reasons", {}))
            if reason:
                reasons[feature] = reason.strip()[:400]
            cached["reasons"] = reasons
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                json.dump(cached, f)
        except Exception:
            pass
    return ok

"""Dense-domain segment aggregation: the group-by histogram kernel.

The reference's ``group`` delegates to Spark's hash aggregation
(ref: spark-cypher/.../impl/table/SparkTable.scala ``group`` via
``RelationalGroupedDataset`` — reconstructed, mount empty; SURVEY.md §2).
TPUs have no scatter-friendly hash tables, and ``lax.sort`` is O(n log²n)
on the VPU — but our string pool already dictionary-encodes group keys to
*dense* int32 codes, so a group-by over a string/bool key is a histogram
over a small dense domain.  This kernel aggregates straight into the
code-indexed output with no sort and no scatter:

    grid = (segment_tiles, row_tiles)   # row tiles innermost
    hit[r, s] = (codes[r] == s) & ok[r]          (VPU compare)
    count:  out[s] += Σ_r hit[r, s]              (VPU reduce)
    sum:    out[s] += v[None, :] @ hit           (MXU matmul)
    min/max: out[s] = min/max(out[s], Σ-free masked reduce)

The output block (one segment tile) stays resident in VMEM while the row
tiles stream through — the classic Pallas accumulation pattern.

Integer sums are NOT offered in f32 (exactness); the engine routes int
sums to the sorted path and uses this kernel for count/min/max and f32
sums where rounding semantics allow.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Blocks are 1-D; the live TPU stack verifies Mosaic's derived layout
# against XLA's, and XLA tiles a 1-D 32-bit operand of padded size S at
# T(min(1024, S)) — so every 1-D block (inputs AND output) must be
# exactly min(1024, padded_array_size) or Mosaic is rejected with
# "XLA layout ({0:T(1024)}) does not match Mosaic layout ({0:T(512)})"
# (observed on v5e 2026-07-31 at s32[4096]/block 512 and s32[256]/block
# 128).  Rows therefore pad to 1024 multiples with a fixed 1024 tile;
# the segment axis uses ONE whole-array block up to 1024 and 1024-tiles
# beyond.
ROW_TILE = 1024
SEG_QUANTUM = 128

_KINDS = ("count", "sum_f32", "sum_i32", "min_i32", "max_i32",
          "min_f32", "max_f32")

_IDENT = {
    "min_i32": jnp.iinfo(jnp.int32).max,
    "max_i32": jnp.iinfo(jnp.int32).min,
    "min_f32": jnp.inf,
    "max_f32": -jnp.inf,
}


def default_interpret() -> bool:
    """Compiled on TPU; interpreter elsewhere (CPU unit suite)."""
    return jax.default_backend() != "tpu"


def _out_dtype(kind: str):
    return jnp.float32 if kind.endswith("f32") else jnp.int32


def _agg_kernel(codes_ref, ok_ref, val_ref, out_ref, *, kind: str,
                row_tile: int, seg_tile: int):
    i = pl.program_id(1)  # row tile (innermost: out block stays resident)
    j = pl.program_id(0)
    seg = j * seg_tile + jax.lax.broadcasted_iota(
        jnp.int32, (row_tile, seg_tile), 1)
    # reshape the int32 refs BEFORE comparing: Mosaic cannot insert a minor
    # dim on i1 vectors ("only supported for 32-bit types")
    codes2d = codes_ref[:].reshape(row_tile, 1)
    ok2d = ok_ref[:].reshape(row_tile, 1) != 0
    hit = (codes2d == seg) & ok2d
    # NB: dtype= on the reductions — x64 mode is enabled globally and the
    # default int32→int64 promotion does not lower on Mosaic TPU.
    if kind == "count":
        part = jnp.sum(hit.astype(jnp.int32), axis=0, dtype=jnp.int32)
    elif kind == "sum_f32":
        v = jnp.where(ok_ref[:] != 0, val_ref[:], jnp.float32(0))
        # HIGHEST: the MXU's default f32 precision truncates operands to
        # bf16, which is visible data loss in an aggregate (observed
        # ~2e-2 abs drift on live v5e); bf16x6 passes restore f32 sums
        part = jnp.dot(v.reshape(1, row_tile), hit.astype(jnp.float32),
                       preferred_element_type=jnp.float32,
                       precision=jax.lax.Precision.HIGHEST
                       ).reshape(seg_tile)
    elif kind == "sum_i32":
        v = val_ref[:].reshape(row_tile, 1)
        part = jnp.sum(jnp.where(hit, v, jnp.int32(0)), axis=0,
                       dtype=jnp.int32)
    elif kind in ("min_i32", "min_f32"):
        v = val_ref[:].reshape(row_tile, 1)
        ident = jnp.asarray(_IDENT[kind], val_ref.dtype)
        part = jnp.min(jnp.where(hit, v, ident), axis=0)
    elif kind in ("max_i32", "max_f32"):
        v = val_ref[:].reshape(row_tile, 1)
        ident = jnp.asarray(_IDENT[kind], val_ref.dtype)
        part = jnp.max(jnp.where(hit, v, ident), axis=0)
    else:  # pragma: no cover
        raise ValueError(f"unknown kind {kind}")

    @pl.when(i == 0)
    def _init():
        out_ref[:] = part

    @pl.when(i != 0)
    def _accumulate():
        if kind.startswith("min"):
            out_ref[:] = jnp.minimum(out_ref[:], part)
        elif kind.startswith("max"):
            out_ref[:] = jnp.maximum(out_ref[:], part)
        else:
            out_ref[:] = out_ref[:] + part


def _pad1(x, multiple: int, fill):
    n = x.shape[0]
    rem = (-n) % multiple
    if rem:
        x = jnp.concatenate([x, jnp.full((rem,), fill, x.dtype)])
    return x


@functools.partial(jax.jit,
                   static_argnames=("num_segments", "kind", "interpret"))
def dense_segment_agg(codes: jnp.ndarray, ok: jnp.ndarray,
                      values, num_segments: int, kind: str,
                      interpret: bool = False) -> jnp.ndarray:
    """Aggregate ``values`` (or row counts) into ``num_segments`` dense
    slots indexed by ``codes``; rows with ``ok == False`` are ignored.

    codes: (n,) int32 in [0, num_segments); ok: (n,) bool;
    values: (n,) f32/i32 (ignored for kind="count" — pass codes).
    """
    assert kind in _KINDS, kind
    n = codes.shape[0]
    if n == 0:
        ident = _IDENT.get(kind, 0)
        return jnp.full((num_segments,), ident, _out_dtype(kind))
    row_tile = ROW_TILE  # fixed: sub-1024 1-D blocks fail layout checks
    codes_p = _pad1(codes.astype(jnp.int32), row_tile, -1)
    ok_p = _pad1(ok.astype(jnp.int32), row_tile, 0)
    if kind == "count":
        vals_p = codes_p  # unused; same shape keeps the specs uniform
    else:
        want = jnp.float32 if kind.endswith("f32") else jnp.int32
        vals_p = _pad1(values.astype(want), row_tile, 0)
    seg_pad = ((num_segments + SEG_QUANTUM - 1) // SEG_QUANTUM) * SEG_QUANTUM
    if seg_pad > 1024:
        seg_tile = 1024
        seg_pad = ((seg_pad + 1023) // 1024) * 1024
    else:
        seg_tile = seg_pad  # single whole-array output block
    n_pad = codes_p.shape[0]
    grid = (seg_pad // seg_tile, n_pad // row_tile)
    kernel = functools.partial(_agg_kernel, kind=kind, row_tile=row_tile,
                               seg_tile=seg_tile)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((row_tile,), lambda j, i: (i,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_tile,), lambda j, i: (i,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_tile,), lambda j, i: (i,),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((seg_tile,), lambda j, i: (j,),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((seg_pad,), _out_dtype(kind)),
        interpret=interpret,
    )(codes_p, ok_p, vals_p)
    return out[:num_segments]


@functools.lru_cache(maxsize=256)
def _sharded_agg_fn(mesh, num_segments: int, kind: str, interpret: bool):
    from caps_tpu.obs.compile import charged as _compile_charged
    from caps_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    # rows split over EVERY mesh axis (matches DeviceBackend.place_rows):
    # on a 2-D DCN x ICI mesh each device keeps its own row block and only
    # the final (num_segments,) partials cross DCN in the combine
    axes = tuple(mesh.axis_names)

    def body(c, o, v):
        local = dense_segment_agg(c, o, v, num_segments, kind,
                                  interpret=interpret)
        if kind.startswith("min"):
            return jax.lax.pmin(local, axes)
        if kind.startswith("max"):
            return jax.lax.pmax(local, axes)
        return jax.lax.psum(local, axes)

    # check_vma=False: pallas_call outputs don't carry varying-mesh-axis
    # metadata, so shard_map's vma checker can't see through them.
    # An lru_cache miss here is a compile boundary (obs/compile.py).
    with _compile_charged("dist_join",
                          shape=f"segagg:{num_segments}:{kind}"):
        return jax.jit(shard_map(body, mesh=mesh,
                                 in_specs=(P(axes), P(axes), P(axes)),
                                 out_specs=P(), check_vma=False))


def dense_segment_agg_sharded(mesh, axis: str, codes, ok, values,
                              num_segments: int, kind: str,
                              interpret: bool = False) -> jnp.ndarray:
    """Distributed histogram: each device aggregates its row block with
    the Pallas kernel, partials combine over the mesh (psum / pmin /
    pmax; ICI within a slice, DCN only for the final partials) — the
    engine's partial-aggregation shuffle (SURVEY.md §5.8).  The jitted
    shard_map program is cached per (mesh, segments, kind)."""
    del axis  # rows always split over every mesh axis (place_rows layout)
    fn = _sharded_agg_fn(mesh, num_segments, kind, interpret)
    return fn(codes.astype(jnp.int32), ok,
              values if kind != "count" else codes.astype(jnp.int32))


def dense_segment_agg_ref(codes, ok, values, num_segments: int,
                          kind: str) -> jnp.ndarray:
    """jnp reference twin (tests only — SURVEY.md §2 native components)."""
    codes = codes.astype(jnp.int32)
    safe = jnp.where(ok, codes, num_segments)  # shunt masked rows off-range
    if kind == "count":
        return jax.ops.segment_sum(ok.astype(jnp.int32), safe,
                                   num_segments=num_segments + 1
                                   )[:num_segments]
    want = jnp.float32 if kind.endswith("f32") else jnp.int32
    v = values.astype(want)
    if kind.startswith("sum"):
        out = jax.ops.segment_sum(jnp.where(ok, v, 0), safe,
                                  num_segments=num_segments + 1)
        return out[:num_segments]
    ident = jnp.asarray(_IDENT[kind], want)
    v = jnp.where(ok, v, ident)
    fn = jax.ops.segment_min if kind.startswith("min") else jax.ops.segment_max
    out = fn(v, safe, num_segments=num_segments + 1)[:num_segments]
    # segment_min/max fill empty segments with dtype extremes; align to ident
    return jnp.where(jnp.isin(jnp.arange(num_segments), safe), out, ident)

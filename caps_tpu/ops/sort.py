"""Pallas multi-column bitonic sort — the order_by / distinct / group-by
sort permutation kernel.

The reference delegates sorting to Spark's shuffle/Tungsten sort (ref:
spark-cypher/.../impl/table/SparkTable.scala ``orderBy``/``distinct`` —
reconstructed, mount empty; SURVEY.md §2 native components).  Here the
whole multi-key comparator network runs in one Pallas kernel, VMEM
resident (SURVEY.md §7 step 6, the last jnp stand-in the survey named).

Layout.  The flat array of ``cap = R·128`` elements maps COLUMN-major
onto a (R, 128) tile: flat index ``i = r + R·c``.  A bitonic
compare-exchange at distance ``d`` pairs ``i ↔ i^d``:

  * ``d < R``  (77 of 105 stages at cap=16k): a SUBLANE permutation —
    implemented as reshape (R/2d, 2, d, 128) + swap of the middle pair +
    reshape back, i.e. static slices/concats Mosaic handles natively;
  * ``d ≥ R``: a LANE permutation with XOR stride ``d/R`` — the tile is
    transposed (≤128×128), the same sublane swap applied, transposed
    back.  Only the top log2(128/R)+… stages pay the two transposes.

Multi-column keys arrive as int32 PLANES (``split_planes``): int64 keys
split into (hi, biased-lo) pairs — exact for the full 64-bit range, in
particular ints ≥ 2^53 that a float64 squeeze would collide — and
float64 keys bitcast through the standard monotone mapping that matches
XLA's total order (-NaN < -Inf < … < -0 < +0 < … < +Inf < +NaN).  The
comparator chains plane-wise (gt, eq) lexicographically with the running
row index as the final tiebreaker, which makes the network a strict
total order and therefore STABLE — bit-identical permutations to the
``lax.sort(…, is_stable=True)`` twin (kernels.sort_perm), which remains
the differential-test oracle and the fallback for shapes the tile form
does not cover.
"""
from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

jax.config.update("jax_enable_x64", True)

LANES = 128
ROWS_MAX = 128          # one tile: cap <= 128*128 = 16384 elements
_I64_MIN = jnp.int64(-(2 ** 63))


def sort_cap_supported(cap: int) -> bool:
    """True when the one-tile kernel covers this capacity."""
    r = cap // LANES
    return (cap % LANES == 0 and 2 <= r <= ROWS_MAX
            and (r & (r - 1)) == 0)


def split_planes(keys: Sequence[jnp.ndarray]) -> List[jnp.ndarray]:
    """Lexicographic key columns -> int32 comparison planes (see module
    docstring).  Ascending int32 order on the planes == ascending
    int64/float64 total order on the originals."""
    out: List[jnp.ndarray] = []
    for k in keys:
        if k.dtype == jnp.float64:
            b = jax.lax.bitcast_convert_type(k, jnp.int64)
            k = jnp.where(b >= 0, b, (~b) ^ _I64_MIN)
        if k.dtype == jnp.int64:
            out.append((k >> 32).astype(jnp.int32))
            out.append(((k & 0xFFFFFFFF) - (1 << 31)).astype(jnp.int32))
        else:  # bool / int32 already compare correctly in int32
            out.append(k.astype(jnp.int32))
    return out


def _swap_rows(x: jnp.ndarray, d: int) -> jnp.ndarray:
    """y[r, c] = x[r ^ d, c] for power-of-two d < R (static slices)."""
    r, c = x.shape
    g = x.reshape(r // (2 * d), 2, d, c)
    g = jnp.concatenate([g[:, 1], g[:, 0]], axis=1)
    return g.reshape(r, c)


def _partner(x: jnp.ndarray, d: int, rows: int) -> jnp.ndarray:
    if d < rows:
        return _swap_rows(x, d)
    return _swap_rows(x.T, d // rows).T


def _exchange_step(planes: List[jnp.ndarray], i_mat: jnp.ndarray,
                   dir_bit: jnp.ndarray, d: int,
                   rows: int) -> List[jnp.ndarray]:
    """One compare-exchange stage at static distance ``d`` — THE shared
    comparator body: both the XLA twin (differential tests) and the
    Pallas kernel call exactly this, so the tests validate the kernel's
    logic, not a copy."""
    partners = [_partner(p, d, rows) for p in planes]
    gt = jnp.zeros((rows, LANES), jnp.bool_)
    eq = jnp.ones((rows, LANES), jnp.bool_)
    for a, b in zip(planes, partners):
        gt = gt | (eq & (a > b))
        eq = eq & (a == b)
    take_min = ((i_mat & d) == 0) ^ (dir_bit == 1)
    # NOT jnp.where(take_min, gt, ~gt): a select over BOOL operands
    # lowers through an i8->i1 vector trunci Mosaic rejects on TPU
    # (observed live, TUNNEL_r05.md probe 4); the XOR form is identical.
    sel_p = ~(gt ^ take_min)
    return [jnp.where(sel_p, pb, pa) for pa, pb in zip(planes, partners)]


def _network(planes: List[jnp.ndarray], rows: int,
             total_levels: int) -> jnp.ndarray:
    """The full bitonic network on (rows, 128) tiles; returns the
    original-position payload tile.  Pure jnp — the CPU twin and the
    differential tests run it directly under XLA; the Pallas kernel
    steps the same _exchange_step per grid step."""
    # running original-position payload; also the final comparator
    # tiebreaker, which makes the order strict (=> stable network)
    r_iota = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0)
    c_iota = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1)
    i_mat = r_iota + rows * c_iota
    planes = list(planes) + [i_mat]

    for m in range(1, total_levels + 1):
        dir_bit = (i_mat >> m) & 1  # 1 = descending block this level
        d = 1 << (m - 1)
        while d >= 1:
            planes = _exchange_step(planes, i_mat, dir_bit, d, rows)
            d //= 2
    return planes[-1]


def _stage_kernel(*refs, rows: int, total_levels: int):
    """One grid step = one compare-exchange stage of the network.

    Fully unrolling the 105-stage network into one Mosaic program hangs
    the TPU compiler (observed >7 min at cap=256), so the grid iterates
    stages instead: program_id = (level-1, within-level j), distance
    d = 2^(level-1-j), and the body predicates over the log2(cap)
    possible static distances with pl.when — each branch carries the
    static-shape swap that distance needs.  Plane refs are input/output
    aliased whole-array blocks, so they stay VMEM-resident across the
    whole grid; steps with j >= level are no-ops (the rectangular grid
    over a triangular stage table)."""
    n = len(refs) // 2
    in_refs, out_refs = refs[:n], refs[n:]
    m = pl.program_id(0) + 1          # level: merge size 2^m
    j = pl.program_id(1)              # stage within level
    first = (m == 1) & (j == 0)
    k_idx = (m - 1) - j               # d = 2^k_idx

    r_iota = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0)
    c_iota = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1)
    i_mat = r_iota + rows * c_iota

    @pl.when(first)
    def _load():
        for i_ref, o_ref in zip(in_refs, out_refs):
            o_ref[:, :] = i_ref[:, :]

    @pl.when(j < m)
    def _stage():
        planes = [o[:, :] for o in out_refs]
        dir_bit = (i_mat >> m) & 1
        for k in range(total_levels):
            @pl.when(k_idx == k)
            def _exchange(k=k):
                new = _exchange_step(planes, i_mat, dir_bit, 1 << k, rows)
                for o_ref, p in zip(out_refs, new):
                    o_ref[:, :] = p


@functools.partial(jax.jit, static_argnames=("interpret",))
def bitonic_sort_perm(planes: Tuple[jnp.ndarray, ...],
                      interpret: bool = False) -> jnp.ndarray:
    """Stable ascending-lexicographic sort permutation of int32 planes
    (cap,), cap = R*128 with R a power of two <= 128."""
    cap = planes[0].shape[0]
    rows = cap // LANES
    assert sort_cap_supported(cap), cap
    total_levels = cap.bit_length() - 1
    tiles = [p.reshape(LANES, rows).T for p in planes]  # [r,c]=flat[r+R*c]
    r_iota = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0)
    c_iota = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1)
    tiles = tiles + [r_iota + rows * c_iota]  # position payload/tiebreak
    kernel = functools.partial(_stage_kernel, rows=rows,
                               total_levels=total_levels)
    # index map must yield i32: under this module's x64 mode plain
    # Python 0s trace as i64 and Mosaic rejects the (i64,i64) return
    # (observed live on TPU, TUNNEL_r05.md probe 4)
    whole = pl.BlockSpec((rows, LANES),
                         lambda m, j: (jnp.int32(0), jnp.int32(0)))
    outs = pl.pallas_call(
        kernel,
        grid=(total_levels, total_levels),
        in_specs=[whole] * len(tiles),
        out_specs=[whole] * len(tiles),
        out_shape=[jax.ShapeDtypeStruct((rows, LANES), jnp.int32)
                   for _ in tiles],
        interpret=interpret,
    )(*tiles)
    return outs[-1].T.reshape(cap)


def bitonic_sort_perm_twin(planes: Tuple[jnp.ndarray, ...]) -> jnp.ndarray:
    """The identical network under plain XLA, EAGER on purpose — the
    differential twin for the CPU suite.  (Jitting the unrolled network
    through XLA:CPU takes ~30 s at cap=256; op-by-op dispatch runs it in
    seconds and tests only need values, not speed.)"""
    cap = planes[0].shape[0]
    rows = cap // LANES
    assert sort_cap_supported(cap), cap
    tiles = [p.reshape(LANES, rows).T for p in planes]
    out = _network(tiles, rows, cap.bit_length() - 1)
    return out.T.reshape(cap)


def sort_perm_pallas(keys: Sequence[jnp.ndarray], cap: int,
                     interpret: bool = False) -> jnp.ndarray:
    """Drop-in for kernels.sort_perm on supported capacities: same key
    contract (pre-transformed columns, nulls folded), same stable
    ascending permutation, int32 positions."""
    planes = split_planes(keys)
    return bitonic_sort_perm(tuple(planes), interpret=interpret)


def validate(compiled: bool = False, seed: int = 0) -> dict:
    """Differential validation of the bitonic sort-permutation against
    the ``lax.sort`` reference across capacities and key mixes.

    ``compiled=False`` exercises the kernel's ROUTING logic (plane
    splitting, tiling, network schedule) through the eager XLA twin —
    CPU-provable, the fallback the round-4 VERDICT asked for while the
    TPU tunnel is wedged.  ``compiled=True`` runs the real pallas_call
    on the active backend (the recorded run that justifies flipping
    ``use_sort_kernel`` on).  Returns {"cases": n, "failures": [...]}.
    """
    import numpy as np
    from caps_tpu.backends.tpu import kernels as K

    rng = np.random.RandomState(seed)
    failures = []
    cases = 0
    # routing (eager-twin) validation: small caps — the op-by-op network
    # at cap 1024 takes minutes on CPU; the compiled sweep covers them
    caps = [c for c in ((128, 256) if not compiled
                        else (128, 256, 512, 1024))
            if sort_cap_supported(c)]
    for cap in caps:
        for nkeys in (1, 2, 3):
            for rep in range(2):
                keys = []
                for _ in range(nkeys):
                    if rep == 0:  # heavy duplicates: stability stress
                        keys.append(jnp.asarray(
                            rng.randint(0, 4, cap).astype(np.int64)))
                    else:
                        keys.append(jnp.asarray(
                            rng.randint(-(2**40), 2**40, cap)
                            .astype(np.int64)))
                want = np.asarray(K.sort_perm(keys, cap))
                if compiled:
                    got = np.asarray(sort_perm_pallas(keys, cap))
                else:
                    got = np.asarray(bitonic_sort_perm_twin(
                        tuple(split_planes(keys))))
                cases += 1
                if not np.array_equal(want, got):
                    failures.append((cap, nkeys, rep))
    return {"cases": cases, "failures": failures}

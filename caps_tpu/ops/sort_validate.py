"""``python -m caps_tpu.ops.sort_validate``: the pre-staged
use_sort_kernel flip protocol (TUNNEL_r05.md).

1. Probe the device from a throwaway subprocess BEFORE importing any
   array-creating module (a wedged axon tunnel hangs the first array
   constant, which ops/sort.py builds at import time).
2. Run the CPU-provable routing validation (eager twin of the bitonic
   network) — the round-4 VERDICT's fallback while hardware is away.
3. On a live TPU, run the COMPILED pallas kernel validation; on success
   print the flip instruction for okapi/config.py use_sort_kernel.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys


def main() -> int:
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, timeout=30, text=True)
        reachable = proc.returncode == 0 and "cpu" not in proc.stdout
    except subprocess.TimeoutExpired:
        reachable = False
    if not reachable:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    if not reachable:
        jax.config.update("jax_platforms", "cpu")
        try:
            from jax._src import xla_bridge as _xb
            _xb._backend_factories.pop("axon", None)
        except Exception:
            pass

    from caps_tpu.ops.sort import validate

    res = validate(compiled=False)
    ok = not res["failures"]
    out = {"routing_validation": res, "backend": jax.default_backend()}
    if jax.default_backend() == "tpu":
        resc = validate(compiled=True)
        out["compiled_validation"] = resc
        ok = ok and not resc["failures"]
        if ok:
            out["action"] = (
                "PASS on live TPU: flip okapi/config.py use_sort_kernel "
                "default to True (or set CAPS_TPU_SORT_KERNEL=1) and "
                "commit this output as the recorded validation run")
    else:
        out["action"] = (
            "routing validated on CPU; rerun on a live TPU for the "
            "compiled run that justifies the default flip")
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

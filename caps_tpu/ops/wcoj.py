"""Worst-case-optimal multiway-join primitives over sorted edge keys.

Cyclic MATCH patterns (triangles, diamonds, k-cycles) compiled as binary
join cascades materialize every *open* sub-pattern before the closing
edge filters it — the classic intermediate blow-up the worst-case-
optimal join literature (TrieJax, PAPERS.md; Ngo et al.) eliminates by
intersecting all adjacency constraints *while* a new vertex binds.

This module is the kernel layer of that path (relational/wcoj.py builds
the operator on top).  Everything rides one physical structure:

    key(e) = frm(e) * n + to(e)          (int64; n = node-id domain)

sorted ascending — ONE device sort per (edge scan, orientation), routed
through the engine's sort gate so it rides the live-validated bitonic
sort kernel on TPU (ops/sort.py) and ``lax.sort`` elsewhere.  The sorted
order gives both leapfrog views at once:

* **adjacency**: the neighbours of ``u`` occupy the contiguous key range
  ``[u*n, (u+1)*n)`` — and within it they are SORTED BY NEIGHBOUR ID,
  the ordering guarantee leapfrog intersection needs (``probe_adj`` is
  two ``searchsorted``s, no per-row scan);
* **membership / multiplicity**: the parallel edges between a bound
  pair ``(u, v)`` occupy ``[u*n+v, u*n+v]`` — ``probe_pair`` returns
  their exact multiplicity and start offset, so a closing edge both
  *semi-filters* candidates (count > 0) and later *enumerates* each
  parallel edge as its own binding.

Enumeration keeps the engine's pad-and-mask discipline: candidate
expansion inverts ``cumsum(counts)`` through ``ops/expand.py``'s
``expand_positions`` Pallas kernel (jnp twin off-TPU), output
capacities are size-bucketed by the caller through the ``shapes.py``
lattice, and validity is an exact live-row prefix — so every step is a
fixed-shape device program and the whole pattern replays through the
fused executor with zero host syncs beyond the consume seams.

Dead rows fold their key to :data:`PAD_KEY` (sorts last, matches no
probe).  All functions are pure jax (tracer-purity checked: they are
jit roots for capslint's purity closure).
"""
from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from caps_tpu.ops.expand import expand_positions, expand_positions_ref

#: key sentinel for masked-out edges/ids: sorts after every real key
#: (real keys are < n^2 <= 2^52 under the domain guard) and can never
#: equal a probe key.
PAD_KEY = jnp.int64(2) ** 62


@jax.jit
def edge_keys(frm: jnp.ndarray, to: jnp.ndarray, ok: jnp.ndarray,
              n: jnp.ndarray) -> jnp.ndarray:
    """Composite sort keys ``frm*n + to`` (int64), dead rows folded to
    :data:`PAD_KEY`.  ``n`` is a traced scalar so one compiled program
    serves every graph/domain size."""
    n64 = jnp.asarray(n, jnp.int64)
    k = frm.astype(jnp.int64) * n64 + to.astype(jnp.int64)
    good = ok & (frm >= 0) & (to >= 0) & (frm < n64) & (to < n64)
    return jnp.where(good, k, PAD_KEY)


def sorted_edges(frm: jnp.ndarray, to: jnp.ndarray, ok: jnp.ndarray,
                 n, sort_perm: Callable[[list], jnp.ndarray]
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(keys_sorted, perm): the one sorted structure both probes read.
    ``sort_perm`` is the caller's gated sort (DeviceTable._sort_perm —
    bitonic kernel on supported TPU capacities, lax.sort twin
    otherwise), so the ordering guarantee is the sort kernel's."""
    keys = edge_keys(frm, to, ok, jnp.int64(int(n)))
    perm = sort_perm([keys])
    return keys[perm], perm


@jax.jit
def sorted_ids(ids: jnp.ndarray, ok: jnp.ndarray) -> jnp.ndarray:
    """Masked int64 id keys for a node scan (PAD-folded); the caller
    sorts them through its gated sort like :func:`sorted_edges`."""
    good = ok & (ids >= 0)
    return jnp.where(good, ids.astype(jnp.int64), PAD_KEY)


@jax.jit
def probe_adj(keys_sorted: jnp.ndarray, u: jnp.ndarray, ok: jnp.ndarray,
              n: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-probe-row (counts, lo) of u's neighbour segment
    ``[u*n, (u+1)*n)`` — the leapfrog adjacency view: two searchsorteds
    against the sorted keys, no gather, no per-row loop."""
    n64 = jnp.asarray(n, jnp.int64)
    in_dom = ok & (u >= 0) & (u < n64)
    base = jnp.where(in_dom, u.astype(jnp.int64), 0) * n64
    lo = jnp.searchsorted(keys_sorted, base, side="left")
    hi = jnp.searchsorted(keys_sorted, base + n64, side="left")
    counts = jnp.where(in_dom, hi - lo, 0)
    return counts, lo


@jax.jit
def probe_pair(keys_sorted: jnp.ndarray, u: jnp.ndarray, v: jnp.ndarray,
               ok: jnp.ndarray, n: jnp.ndarray
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row (multiplicity, lo) of the exact pair key ``u*n + v`` —
    the membership/closing view: multiplicity 0 semi-filters a
    candidate, multiplicity k enumerates k parallel-edge bindings."""
    n64 = jnp.asarray(n, jnp.int64)
    in_dom = ok & (u >= 0) & (u < n64) & (v >= 0) & (v < n64)
    q = jnp.where(in_dom, u.astype(jnp.int64) * n64 + v.astype(jnp.int64),
                  PAD_KEY - 1)
    lo = jnp.searchsorted(keys_sorted, q, side="left")
    hi = jnp.searchsorted(keys_sorted, q, side="right")
    counts = jnp.where(in_dom, hi - lo, 0)
    return counts, lo


@jax.jit
def multiplicity(keys_sorted: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Multiplicity of raw composite keys ``q`` in the sorted table —
    the probe CountCycleOp's batched 2-path counting specializes to."""
    lo = jnp.searchsorted(keys_sorted, q, side="left")
    hi = jnp.searchsorted(keys_sorted, q, side="right")
    return (hi - lo).astype(jnp.int64)


@jax.jit
def probe_id(ids_sorted: jnp.ndarray, cand: jnp.ndarray, ok: jnp.ndarray
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-candidate (count, lo) against a sorted node-id table — the
    node-scan membership check (labels + predicates pre-filtered by the
    caller) that doubles as the id -> scan-row lookup via the sort
    permutation."""
    safe = jnp.where(ok & (cand >= 0), cand.astype(jnp.int64), PAD_KEY - 1)
    lo = jnp.searchsorted(ids_sorted, safe, side="left")
    hi = jnp.searchsorted(ids_sorted, safe, side="right")
    counts = jnp.where(ok, hi - lo, 0)
    return counts, lo


def _positions(counts: jnp.ndarray, lo: jnp.ndarray, out_cap: int,
               use_pallas: bool, interpret: bool
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    if use_pallas:
        return expand_positions(counts, lo, out_cap, interpret=interpret)
    return expand_positions_ref(counts, lo, out_cap)


@jax.jit
def _extend_gather(keys_sorted: jnp.ndarray, perm: jnp.ndarray,
                   pos: jnp.ndarray, ok: jnp.ndarray, n: jnp.ndarray
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Candidate neighbour id + original edge row for expanded slots."""
    n64 = jnp.asarray(n, jnp.int64)
    pos = jnp.clip(pos, 0, keys_sorted.shape[0] - 1)
    key = keys_sorted[pos]
    cand = jnp.where(ok & (key < PAD_KEY), key % n64, 0)
    return cand, perm[pos]


def extend(keys_sorted: jnp.ndarray, perm: jnp.ndarray, u: jnp.ndarray,
           valid: jnp.ndarray, n, out_cap: int, *,
           counts: Optional[jnp.ndarray] = None,
           lo: Optional[jnp.ndarray] = None,
           use_pallas: bool = False, interpret: bool = False
           ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One leapfrog extension: enumerate every (frontier row, incident
    edge) pair along the anchor adjacency.

    Returns ``(l_idx, cand, edge_row, ok)`` — the frontier row each
    output slot came from, the new vertex candidate (the neighbour id,
    read from the SORTED key segment), the anchor edge's scan row (the
    relationship binding), and the exact live-prefix validity mask.
    The caller semi-filters ``cand`` against the other incident edges
    (:func:`probe_pair` counts) before compacting — intermediates never
    exceed the true partial-match count plus this step's expansion.
    ``counts``/``lo`` accept the :func:`probe_adj` results the caller
    already computed to size ``out_cap`` (the hot path never probes the
    same adjacency twice).
    """
    n64 = jnp.int64(int(n))
    if counts is None or lo is None:
        counts, lo = probe_adj(keys_sorted, u, valid, n64)
    l_idx, pos, ok = _positions(counts, lo, out_cap, use_pallas, interpret)
    cand, edge_row = _extend_gather(keys_sorted, perm, pos, ok, n64)
    return l_idx, cand, edge_row, ok


def close(keys_sorted: jnp.ndarray, perm: jnp.ndarray, u: jnp.ndarray,
          v: jnp.ndarray, valid: jnp.ndarray, n, out_cap: int, *,
          counts: Optional[jnp.ndarray] = None,
          lo: Optional[jnp.ndarray] = None,
          use_pallas: bool = False, interpret: bool = False
          ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Close one edge between two bound vertices: expand each frontier
    row by the pair's parallel-edge multiplicity, binding each edge's
    scan row.  Returns ``(l_idx, edge_row, ok)``; ``counts``/``lo``
    reuse the caller's sizing :func:`probe_pair` like :func:`extend`."""
    n64 = jnp.int64(int(n))
    if counts is None or lo is None:
        counts, lo = probe_pair(keys_sorted, u, v, valid, n64)
    l_idx, pos, ok = _positions(counts, lo, out_cap, use_pallas, interpret)
    pos = jnp.clip(pos, 0, perm.shape[0] - 1)
    return l_idx, perm[pos], ok


@jax.jit
def adj_total(counts: jnp.ndarray) -> jnp.ndarray:
    """Total expansion size of one step (the device scalar the caller
    routes through ``backend.consume_rows`` before bucketing)."""
    return counts.sum()

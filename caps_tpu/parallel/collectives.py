"""Collective primitives for sharded query execution.

The engine's "shuffle service" (SURVEY.md §5.8): thin wrappers over
``jax.lax`` collectives used inside ``shard_map``ped query programs.

    exchange_by_shard   all_to_all radix repartition by key hash — the
                        analog of Spark's hash shuffle before joins/aggs
    ring_shift          ppermute rotation — the ring schedule for k-hop
                        frontier expansion against resident shards
    broadcast_concat    all_gather of a small build side — broadcast join
    global_sum          psum tree — global aggregates

All take the mesh axis name; they only mean something inside shard_map.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from caps_tpu.obs import active_tracer, global_registry


def note_collective(op: str, *arrays, scale: int = 1, **attrs) -> None:
    """Observability hook for collective launches (obs/ — ISSUE 3).

    These wrappers execute at TRACE time (once per XLA compile of the
    enclosing shard_map program), not per device execution, so counts
    and byte totals are per-compile — recorded under
    ``collectives.<op>.*`` in the process-global registry and as
    ``when="trace"`` tracer events, never mislabeled as per-run wire
    traffic.  ``scale`` multiplies the byte estimate when the traced
    launch runs more than once per compile (a ring rotation inside a
    fori_loop body traces once but fires n_shards times).  The
    per-execution wire/payload accounting stays with the callers that
    know the run context (backends/tpu/table.py dist joins, which emit
    their own ``dist_join.*`` events)."""
    try:
        nbytes = scale * sum(int(a.size) * a.dtype.itemsize for a in arrays)
    except Exception:  # abstract avals without sizes: count the call only
        nbytes = 0
    reg = global_registry()
    reg.counter(f"collectives.{op}.calls").inc()
    reg.counter(f"collectives.{op}.traced_bytes").inc(nbytes)
    tr = active_tracer()
    if tr.enabled:
        tr.event(f"collective.{op}", kind="collective", bytes=nbytes,
                 when="trace", **attrs)


def shard_of(key: jnp.ndarray, n_shards: int) -> jnp.ndarray:
    """Destination shard for a join/group key (dense ids: range partition
    by modulo — cheap and balanced for hashed/dense ids)."""
    return (key % n_shards).astype(jnp.int32)


def salted_dest(key: jnp.ndarray, n_shards: int, salt: int,
                salt_id: jnp.ndarray | None) -> jnp.ndarray:
    """Destination device of a key.  With salting, sub-bucket ``s`` of a
    key lands ``s * (n_shards // salt)`` devices away — the ``salt``
    sub-buckets of one key hit ``salt`` DISTINCT devices (stride
    ``n // salt``, ids ``s*stride < n`` pairwise distinct).  The skew
    guard of the radix-exchange join (SURVEY.md §5.8 'salting hot keys')."""
    base = (jnp.abs(key) % jnp.int64(n_shards)).astype(jnp.int32)
    if salt > 1 and salt_id is not None:
        stride = max(1, n_shards // salt)
        base = (base + salt_id.astype(jnp.int32) * stride) % n_shards
    return base


def bin_positions(dest: jnp.ndarray, ok: jnp.ndarray, n_shards: int,
                  bin_cap: int):
    """Within-bin position per row for a binned exchange; overflowed rows
    are counted and get an out-of-range destination so the scatter drops
    them (callers retry with a bigger ``bin_cap`` when ``dropped > 0``)."""
    dest = jnp.where(ok, dest, n_shards)
    one_hot = (dest[:, None] == jnp.arange(n_shards)[None, :]).astype(jnp.int32)
    pos = jnp.cumsum(one_hot, axis=0) - 1
    row_pos = jnp.where(ok, jnp.take_along_axis(
        pos, jnp.clip(dest, 0, n_shards - 1)[:, None], axis=1)[:, 0], 0)
    sent = ok & (row_pos < bin_cap)
    dropped = (ok & ~sent).sum()
    dest = jnp.where(sent, dest, n_shards)
    return dest, row_pos, dropped


def exchange_binned(arr: jnp.ndarray, dest: jnp.ndarray,
                    row_pos: jnp.ndarray, n_shards: int, bin_cap: int,
                    axis, fill) -> jnp.ndarray:
    """Scatter local rows into (n_shards, bin_cap, *trailing) bins
    (out-of-range destinations drop) and all_to_all: device i receives
    every other device's bin i → (n_shards, bin_cap, *trailing).
    Trailing dims carry matrix payloads (e.g. list columns); ``axis`` may
    be a tuple of mesh axes (2-D DCN×ICI meshes — the collective runs
    over the flattened product)."""
    binned = jnp.full((n_shards, bin_cap) + arr.shape[1:], fill, arr.dtype)
    binned = binned.at[dest, jnp.clip(row_pos, 0, bin_cap - 1)].set(
        arr, mode="drop")
    note_collective("all_to_all", binned)
    return lax.all_to_all(binned, axis, split_axis=0, concat_axis=0,
                          tiled=False)


def exchange_by_shard(data: jnp.ndarray, dest: jnp.ndarray, n_shards: int,
                      axis: str, capacity: int) -> jnp.ndarray:
    """All-to-all exchange: each device buckets its rows by ``dest`` into
    fixed-capacity bins, then all_to_all delivers bin i to device i.
    Returns the received (n_shards, capacity) buckets; slots beyond each
    bin's fill are garbage — callers carry a validity channel the same way.
    """
    ok = jnp.ones(data.shape[0], bool)
    dest, row_pos, _ = bin_positions(dest, ok, n_shards, capacity)
    return exchange_binned(data, dest, row_pos, n_shards, capacity, axis,
                           jnp.zeros((), data.dtype))


def ring_shift(x: jnp.ndarray, axis: str, n_shards: int,
               offset: int = 1) -> jnp.ndarray:
    """Rotate a block one step around the ICI ring (ppermute) — the
    communication pattern of ring attention, applied to frontier blocks in
    multi-hop expansion (SURVEY.md §5.7)."""
    perm = [(i, (i + offset) % n_shards) for i in range(n_shards)]
    note_collective("ppermute", x)
    return lax.ppermute(x, axis, perm)


def broadcast_concat(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """all_gather a small table side to every device (broadcast-hash join
    analog of Spark's TorrentBroadcast)."""
    note_collective("all_gather", x)
    return lax.all_gather(x, axis, tiled=True)


def global_sum(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    note_collective("psum", x)
    return lax.psum(x, axis)

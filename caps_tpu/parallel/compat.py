"""JAX version compatibility for the sharded execution paths.

The engine targets the final ``jax.shard_map`` function API (with the
``check_vma`` keyword).  Older toolchains ship it as
``jax.experimental.shard_map.shard_map`` with the keyword spelled
``check_rep`` — same semantics (the varying-mesh-axis checker was
renamed from the replication checker).  Import ``shard_map`` from here
so every call site works on both."""
from __future__ import annotations

try:  # final API: jax >= 0.6
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
    _NATIVE = True
except ImportError:  # experimental module: jax 0.4.x / 0.5.x
    from jax.experimental.shard_map import shard_map as _shard_map
    _NATIVE = False


def shard_map(f, mesh, in_specs, out_specs, **kwargs):
    if not _NATIVE:
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        else:
            # The engine's bodies are written against the final vma
            # checker (pcast annotations); the older replication checker
            # predates those and rejects the same valid programs the new
            # one needed pcast for.  The checker is a static analysis
            # only — disable it rather than fight it per call site.
            kwargs.setdefault("check_rep", False)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)


def pcast(x, axis_name, to: str):
    """``jax.lax.pcast`` when available; identity otherwise.  The cast
    only informs the new API's varying-mesh-axis checker — on older
    toolchains the checker is disabled above, so dropping the
    annotation is sound."""
    import jax
    fn = getattr(jax.lax, "pcast", None)
    if fn is None:
        return x
    return fn(x, axis_name, to=to)

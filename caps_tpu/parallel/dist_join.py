"""Hand-scheduled distributed joins over a 1-D device mesh.

The engine's explicit "shuffle join" (SURVEY.md §5.8; round-4 VERDICT
item 4): instead of trusting GSPMD to lay out the collectives for a
sharded sort-merge join (which tends to all_gather both sides over ICI),
the two strategies the reference inherits from Spark are scheduled by
hand inside ``shard_map``:

* **Radix-partition exchange join** (Spark's shuffle-hash/sort-merge
  join): both sides bucket rows by ``key mod n_shards`` and one
  ``all_to_all`` delivers bucket *i* to device *i*; each device then
  sort-merge joins only its hash partition.  Each row crosses ICI once —
  versus *n* times for an all_gather — and local join work shrinks by
  ~1/n.  Hot keys can be **salted** (``salt > 1``): probe rows of a key
  spread round-robin over ``salt`` devices while build rows replicate
  into all of them, bounding per-device skew at the cost of ``salt``×
  build traffic (Spark's classic skew-salting recipe).

* **Broadcast join** (Spark's TorrentBroadcast / auto-broadcast): a small
  build side is ``all_gather``ed to every device once; the probe side
  never moves.  Chosen by the caller when the build side is under the
  configured row threshold.

Both run as two phases so output capacities stay static under ``jit``:
phase 1 exchanges rows and returns per-device match counts plus overflow
counters — the host doubles the bin capacity and retries on overflow;
phase 2 expands matches into output rows at a host-chosen bucket size.
Exchanged buckets stay device-resident between the phases (sharded
``shard_map`` outputs), so each row crosses ICI exactly once.

ICI traffic is accounted by the caller (static byte counts of the
exchanged / gathered buffers) into ``DeviceBackend.ici_bytes`` and every
result's metrics — SURVEY.md §5.5's "bytes shuffled" column.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax

jax.config.update("jax_enable_x64", True)  # int64 join keys/sentinels

import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from caps_tpu.parallel.collectives import (
    bin_positions as _bin_positions,
    broadcast_concat as _broadcast_concat,
    exchange_binned as _exchange,
    salted_dest as _dest_for,
)

# Join-key sentinels (match backends/tpu/kernels.py): nulls never match.
_L_NULL = jnp.int64(-(2**63) + 1)
_R_NULL = jnp.int64(-(2**63) + 2)


def _expand_matches(counts, lo, perm, lok, rok, out_cap_dev: int,
                    left_join: bool):
    """Segmented expansion of per-probe-row match counts into output row
    index pairs (the device-local analog of kernels.join_expand, shared by
    the radix phase-2 and broadcast programs)."""
    matched = counts > 0
    eff = jnp.where(lok & ~matched, 1, counts) if left_join else counts
    offsets = jnp.cumsum(eff)
    total = offsets[-1] if eff.shape[0] > 0 else jnp.int64(0)
    t = jnp.arange(out_cap_dev)
    l_idx = jnp.clip(jnp.searchsorted(offsets, t, side="right"),
                     0, counts.shape[0] - 1)
    seg_start = jnp.where(l_idx > 0, offsets[l_idx - 1], 0)
    within = t - seg_start
    r_pos = jnp.clip(lo[l_idx] + within, 0, perm.shape[0] - 1)
    r_idx = perm[r_pos]
    out_valid = t < total
    r_matched = out_valid & matched[l_idx]
    l_valid = out_valid & lok[l_idx]
    r_valid = r_matched & rok[r_idx]
    return l_idx, r_idx, l_valid, r_valid


@functools.lru_cache(maxsize=64)
def make_radix_join_phase1(mesh: Mesh, axis: str, n_shards: int,
                           n_l: int, n_r: int,
                           l_dtypes: Tuple[str, ...],
                           r_dtypes: Tuple[str, ...],
                           bin_cap: int, salt: int):
    """Phase 1: exchange both sides, sort the received build partition,
    count matches per received probe row.  All row outputs stay sharded
    (device-resident) for phase 2."""

    def body(l_key, l_ok, r_key, r_ok, *flat):
        l_arrs = flat[:n_l]
        r_arrs = flat[n_l:n_l + n_r]

        # probe side: one exchange, sub-bucket round-robin over rows
        sid = (jnp.arange(l_key.shape[0]) % max(salt, 1)).astype(jnp.int32)
        dest = _dest_for(l_key, n_shards, salt, sid)
        dest, row_pos, l_drop = _bin_positions(dest, l_ok, n_shards, bin_cap)
        lk_recv = _exchange(jnp.where(l_ok, l_key, _L_NULL), dest, row_pos,
                            n_shards, bin_cap, axis, _L_NULL).reshape(-1)
        lok_recv = _exchange(l_ok, dest, row_pos, n_shards, bin_cap,
                             axis, False).reshape(-1)
        l_recv = tuple(
            _exchange(a, dest, row_pos, n_shards, bin_cap, axis,
                      jnp.zeros((), a.dtype)).reshape(-1) for a in l_arrs)

        # build side: replicated into every salt sub-bucket
        rk_parts: List[jnp.ndarray] = []
        rok_parts: List[jnp.ndarray] = []
        r_parts: List[List[jnp.ndarray]] = [[] for _ in r_arrs]
        r_drop = jnp.int64(0)
        for s in range(max(salt, 1)):
            sid_r = jnp.full(r_key.shape, s, jnp.int32)
            dest_r = _dest_for(r_key, n_shards, salt, sid_r)
            dest_r, pos_r, drop_s = _bin_positions(dest_r, r_ok, n_shards,
                                                   bin_cap)
            r_drop = r_drop + drop_s
            rk_parts.append(_exchange(
                jnp.where(r_ok, r_key, _R_NULL), dest_r, pos_r,
                n_shards, bin_cap, axis, _R_NULL))
            rok_parts.append(_exchange(r_ok, dest_r, pos_r, n_shards,
                                       bin_cap, axis, False))
            for i, a in enumerate(r_arrs):
                r_parts[i].append(_exchange(
                    a, dest_r, pos_r, n_shards, bin_cap, axis,
                    jnp.zeros((), a.dtype)))
        rk_recv = jnp.concatenate(rk_parts, axis=1).reshape(-1)
        rok_recv = jnp.concatenate(rok_parts, axis=1).reshape(-1)
        r_recv = tuple(jnp.concatenate(p, axis=1).reshape(-1)
                       for p in r_parts)

        # local sort-merge count on the received hash partitions
        rk = jnp.where(rok_recv, rk_recv, _R_NULL)
        rk_sorted, perm = lax.sort((rk, jnp.arange(rk.shape[0])), num_keys=1)
        lk = jnp.where(lok_recv, lk_recv, _L_NULL)
        lo = jnp.searchsorted(rk_sorted, lk, side="left")
        hi = jnp.searchsorted(rk_sorted, lk, side="right")
        counts = jnp.where(lok_recv, hi - lo, 0)
        my_total = counts.sum()
        max_total = lax.pmax(my_total, axis)
        max_left = lax.pmax(
            (counts + jnp.where(lok_recv & (counts == 0), 1, 0)).sum(), axis)
        dropped = lax.psum(l_drop + r_drop, axis)
        return (lok_recv, counts, lo, perm, rok_recv, max_total, max_left,
                dropped) + l_recv + r_recv

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis),) * (4 + n_l + n_r),
        out_specs=(P(axis),) * 5 + (P(), P(), P()) + (P(axis),) * (n_l + n_r),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=64)
def make_radix_join_phase2(mesh: Mesh, axis: str, n_l: int, n_r: int,
                           out_cap_dev: int, left_join: bool):
    """Phase 2: expand matches into output rows (static per-device cap)."""

    def body(lok, counts, lo, perm, rok, *flat):
        l_recv = flat[:n_l]
        r_recv = flat[n_l:n_l + n_r]
        l_idx, r_idx, l_valid, r_valid = _expand_matches(
            counts, lo, perm, lok, rok, out_cap_dev, left_join)
        outs = tuple(a[l_idx] for a in l_recv) + \
            tuple(a[r_idx] for a in r_recv)
        return (l_valid, r_valid) + outs

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis),) * (5 + n_l + n_r),
        out_specs=(P(axis),) * (2 + n_l + n_r),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=64)
def make_broadcast_join(mesh: Mesh, axis: str, n_l: int, n_r: int,
                        out_cap_dev: int, left_join: bool,
                        count_only: bool):
    """Broadcast join: all_gather the (small) build side once, probe
    locally.  ``count_only`` is the phase-1 variant returning only the
    max per-device output size (the host then picks the bucket)."""

    def body(l_key, l_ok, r_key, r_ok, *flat):
        l_arrs = flat[:n_l]
        r_arrs = flat[n_l:n_l + n_r]
        rk_all = _broadcast_concat(jnp.where(r_ok, r_key, _R_NULL), axis)
        rok_all = _broadcast_concat(r_ok, axis)
        rk = jnp.where(rok_all, rk_all, _R_NULL)
        rk_sorted, perm = lax.sort((rk, jnp.arange(rk.shape[0])), num_keys=1)
        lk = jnp.where(l_ok, l_key, _L_NULL)
        lo = jnp.searchsorted(rk_sorted, lk, side="left")
        hi = jnp.searchsorted(rk_sorted, lk, side="right")
        counts = jnp.where(l_ok, hi - lo, 0)
        eff = jnp.where(left_join & l_ok & (counts == 0), 1, counts) \
            if left_join else counts
        max_total = lax.pmax(eff.sum(), axis)
        if count_only:
            return (max_total,)
        r_all = tuple(_broadcast_concat(a, axis) for a in r_arrs)
        l_idx, r_idx, l_valid, r_valid = _expand_matches(
            counts, lo, perm, l_ok, rok_all, out_cap_dev, left_join)
        outs = tuple(a[l_idx] for a in l_arrs) + \
            tuple(a[r_idx] for a in r_all)
        return (l_valid, r_valid) + outs

    n_out = 1 if count_only else (2 + n_l + n_r)
    out_specs = (P(),) if count_only else (P(axis),) * n_out
    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis),) * (4 + n_l + n_r),
        out_specs=out_specs,
    )
    return jax.jit(mapped)

"""Hand-scheduled distributed joins over 1-D and 2-D device meshes.

The engine's explicit "shuffle join" (SURVEY.md §5.8; round-4 VERDICT
item 4, round-5 items 7–8): instead of trusting GSPMD to lay out the
collectives for a sharded sort-merge join (which tends to all_gather both
sides over ICI), the two strategies the reference inherits from Spark are
scheduled by hand inside ``shard_map``:

* **Radix-partition exchange join** (Spark's shuffle-hash/sort-merge
  join): both sides bucket rows by ``key mod n_shards`` and one
  ``all_to_all`` delivers bucket *i* to device *i*; each device then
  sort-merge joins only its hash partition.  Each row crosses ICI once —
  versus *n* times for an all_gather — and local join work shrinks by
  ~1/n.

  **Surgical skew salting**: a device-resident HOT-KEY set (detected by
  the caller from a host-side key sample) marks the keys whose
  frequency would overload one device.  Probe rows of hot keys spread
  round-robin over ``salt`` devices; ONLY hot build rows replicate into
  the extra ``salt-1`` sub-buckets (exchanged at a smaller
  ``hot_bin_cap``) — non-hot keys pay nothing, fixing round-4's
  whole-build-side replication tax.

* **Broadcast join** (Spark's TorrentBroadcast / auto-broadcast): a small
  build side is ``all_gather``ed to every device once; the probe side
  never moves.  Chosen by the caller when the build side is under the
  configured row threshold.

Both run as two phases so output capacities stay static under ``jit``:
phase 1 exchanges rows and returns per-device match counts plus overflow
counters — the host doubles the bin capacity and retries on overflow;
phase 2 expands matches into output rows at a host-chosen bucket size.
Exchanged buckets stay device-resident between the phases (sharded
``shard_map`` outputs), so each row crosses ICI exactly once.

**2-D (DCN×ICI) meshes**: ``axis`` may be a tuple of mesh axis names —
the collectives then operate over the flattened device product
(DCN-major, matching ``DeviceBackend.place_rows``) and the same radix
schedule runs across slices.

ICI traffic is accounted two ways (round-5 VERDICT item 7): the caller's
static byte count of the PADDED exchange buffers (the wire truth for a
binned all_to_all) goes to ``DeviceBackend.ici_bytes``; phase 1
additionally returns device-measured counts of live rows that left their
home device, from which the caller computes ``ici_payload_bytes`` — the
cross-check that the estimate brackets reality.
"""
from __future__ import annotations

import functools
from typing import List, Tuple, Union

import jax

jax.config.update("jax_enable_x64", True)  # int64 join keys/sentinels

import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from caps_tpu.parallel.compat import shard_map

from caps_tpu.parallel.collectives import (
    bin_positions as _bin_positions,
    broadcast_concat as _broadcast_concat,
    exchange_binned as _exchange,
    salted_dest as _dest_for,
)

Axis = Union[str, Tuple[str, ...]]

# Join-key sentinels (match backends/tpu/kernels.py): nulls never match.
_L_NULL = jnp.int64(-(2**63) + 1)
_R_NULL = jnp.int64(-(2**63) + 2)


def _is_hot(key: jnp.ndarray, hot_keys: jnp.ndarray) -> jnp.ndarray:
    """Membership of each key in the sorted hot-key set (sentinel-padded;
    the sentinel never matches a real key)."""
    if hot_keys.shape[0] == 0:
        return jnp.zeros(key.shape, bool)
    pos = jnp.searchsorted(hot_keys, key)
    pos = jnp.clip(pos, 0, hot_keys.shape[0] - 1)
    return hot_keys[pos] == key


def _off_home(dest: jnp.ndarray, me, n_shards: int) -> jnp.ndarray:
    """Count of rows bound for a different device (live, in-range)."""
    return ((dest != me) & (dest < n_shards)).sum()


def _expand_matches(counts, lo, perm, lok, rok, out_cap_dev: int,
                    left_join: bool):
    """Segmented expansion of per-probe-row match counts into output row
    index pairs (the device-local analog of kernels.join_expand, shared by
    the radix phase-2 and broadcast programs)."""
    matched = counts > 0
    eff = jnp.where(lok & ~matched, 1, counts) if left_join else counts
    offsets = jnp.cumsum(eff)
    total = offsets[-1] if eff.shape[0] > 0 else jnp.int64(0)
    t = jnp.arange(out_cap_dev)
    l_idx = jnp.clip(jnp.searchsorted(offsets, t, side="right"),
                     0, counts.shape[0] - 1)
    seg_start = jnp.where(l_idx > 0, offsets[l_idx - 1], 0)
    within = t - seg_start
    r_pos = jnp.clip(lo[l_idx] + within, 0, perm.shape[0] - 1)
    r_idx = perm[r_pos]
    out_valid = t < total
    r_matched = out_valid & matched[l_idx]
    l_valid = out_valid & lok[l_idx]
    r_valid = r_matched & rok[r_idx]
    return l_idx, r_idx, l_valid, r_valid


@functools.lru_cache(maxsize=64)
def make_radix_join_phase1(mesh: Mesh, axis: Axis, n_shards: int,
                           n_l: int, n_r: int,
                           l_dtypes: Tuple[str, ...],
                           r_dtypes: Tuple[str, ...],
                           bin_cap: int, salt: int, hot_bin_cap: int):
    """Phase 1: exchange both sides, sort the received build partition,
    count matches per received probe row.  All row outputs stay sharded
    (device-resident) for phase 2.  ``hot_keys`` (sorted, sentinel-padded
    device array) drives surgical salting; with ``salt == 1`` it is
    ignored."""

    def body(hot_keys, l_key, l_ok, r_key, r_ok, *flat):
        l_arrs = flat[:n_l]
        r_arrs = flat[n_l:n_l + n_r]
        me = lax.axis_index(axis)

        # probe side: one exchange; ONLY hot keys round-robin over the
        # salt sub-buckets, everything else goes straight home
        if salt > 1:
            hot_l = _is_hot(l_key, hot_keys)
            sid = jnp.where(
                hot_l,
                (jnp.arange(l_key.shape[0]) % salt).astype(jnp.int32), 0)
        else:
            sid = jnp.zeros(l_key.shape, jnp.int32)
        dest = _dest_for(l_key, n_shards, salt, sid)
        dest, row_pos, l_drop = _bin_positions(dest, l_ok, n_shards, bin_cap)
        sent_l = _off_home(dest, me, n_shards)
        lk_recv = _exchange(jnp.where(l_ok, l_key, _L_NULL), dest, row_pos,
                            n_shards, bin_cap, axis, _L_NULL).reshape(-1)
        lok_recv = _exchange(l_ok, dest, row_pos, n_shards, bin_cap,
                             axis, False).reshape(-1)
        l_recv = tuple(
            _exchange(a, dest, row_pos, n_shards, bin_cap, axis,
                      jnp.zeros((), a.dtype)).reshape(
                          (-1,) + a.shape[1:]) for a in l_arrs)

        # build side: copy 0 carries every row; copies 1..salt-1 carry
        # ONLY hot rows (smaller bins — the surgical part)
        hot_r = _is_hot(r_key, hot_keys) if salt > 1 else None
        rk_parts: List[jnp.ndarray] = []
        rok_parts: List[jnp.ndarray] = []
        r_parts: List[List[jnp.ndarray]] = [[] for _ in r_arrs]
        r_drop = jnp.int64(0)
        sent_r = jnp.int64(0)
        for s in range(max(salt, 1)):
            cap_s = bin_cap if s == 0 else hot_bin_cap
            ok_s = r_ok if s == 0 else (r_ok & hot_r)
            sid_r = jnp.full(r_key.shape, s, jnp.int32)
            dest_r = _dest_for(r_key, n_shards, salt, sid_r)
            dest_r, pos_r, drop_s = _bin_positions(dest_r, ok_s, n_shards,
                                                   cap_s)
            r_drop = r_drop + drop_s
            sent_r = sent_r + _off_home(dest_r, me, n_shards)
            rk_parts.append(_exchange(
                jnp.where(ok_s, r_key, _R_NULL), dest_r, pos_r,
                n_shards, cap_s, axis, _R_NULL))
            rok_parts.append(_exchange(ok_s, dest_r, pos_r, n_shards,
                                       cap_s, axis, False))
            for i, a in enumerate(r_arrs):
                r_parts[i].append(_exchange(
                    a, dest_r, pos_r, n_shards, cap_s, axis,
                    jnp.zeros((), a.dtype)))
        rk_recv = jnp.concatenate(rk_parts, axis=1).reshape(-1)
        rok_recv = jnp.concatenate(rok_parts, axis=1).reshape(-1)
        r_recv = tuple(
            jnp.concatenate(p, axis=1).reshape((-1,) + p[0].shape[2:])
            for p in r_parts)

        # local sort-merge count on the received hash partitions
        rk = jnp.where(rok_recv, rk_recv, _R_NULL)
        rk_sorted, perm = lax.sort((rk, jnp.arange(rk.shape[0])), num_keys=1)
        lk = jnp.where(lok_recv, lk_recv, _L_NULL)
        lo = jnp.searchsorted(rk_sorted, lk, side="left")
        hi = jnp.searchsorted(rk_sorted, lk, side="right")
        counts = jnp.where(lok_recv, hi - lo, 0)
        my_total = counts.sum()
        max_total = lax.pmax(my_total, axis)
        max_left = lax.pmax(
            (counts + jnp.where(lok_recv & (counts == 0), 1, 0)).sum(), axis)
        dropped = lax.psum(l_drop + r_drop, axis)
        sent_l = lax.psum(sent_l, axis)
        sent_r = lax.psum(sent_r, axis)
        return (lok_recv, counts, lo, perm, rok_recv, max_total, max_left,
                dropped, sent_l, sent_r) + l_recv + r_recv

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(),) + (P(axis),) * (4 + n_l + n_r),
        out_specs=(P(axis),) * 5 + (P(),) * 5 + (P(axis),) * (n_l + n_r),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=64)
def make_radix_join_phase2(mesh: Mesh, axis: Axis, n_l: int, n_r: int,
                           out_cap_dev: int, left_join: bool):
    """Phase 2: expand matches into output rows (static per-device cap)."""

    def body(lok, counts, lo, perm, rok, *flat):
        l_recv = flat[:n_l]
        r_recv = flat[n_l:n_l + n_r]
        l_idx, r_idx, l_valid, r_valid = _expand_matches(
            counts, lo, perm, lok, rok, out_cap_dev, left_join)
        outs = tuple(a[l_idx] for a in l_recv) + \
            tuple(a[r_idx] for a in r_recv)
        return (l_valid, r_valid) + outs

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis),) * (5 + n_l + n_r),
        out_specs=(P(axis),) * (2 + n_l + n_r),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=64)
def make_broadcast_join(mesh: Mesh, axis: Axis, n_l: int, n_r: int,
                        out_cap_dev: int, left_join: bool,
                        count_only: bool):
    """Broadcast join: all_gather the (small) build side once, probe
    locally.  ``count_only`` is the phase-1 variant returning only the
    max per-device output size plus the live build-row count (the host
    then picks the bucket and accounts payload bytes)."""

    def body(l_key, l_ok, r_key, r_ok, *flat):
        l_arrs = flat[:n_l]
        r_arrs = flat[n_l:n_l + n_r]
        rk_all = _broadcast_concat(jnp.where(r_ok, r_key, _R_NULL), axis)
        rok_all = _broadcast_concat(r_ok, axis)
        rk = jnp.where(rok_all, rk_all, _R_NULL)
        rk_sorted, perm = lax.sort((rk, jnp.arange(rk.shape[0])), num_keys=1)
        lk = jnp.where(l_ok, l_key, _L_NULL)
        lo = jnp.searchsorted(rk_sorted, lk, side="left")
        hi = jnp.searchsorted(rk_sorted, lk, side="right")
        counts = jnp.where(l_ok, hi - lo, 0)
        eff = jnp.where(left_join & l_ok & (counts == 0), 1, counts) \
            if left_join else counts
        max_total = lax.pmax(eff.sum(), axis)
        if count_only:
            live_r = lax.psum(r_ok.sum(), axis)
            return (max_total, live_r)
        r_all = tuple(_broadcast_concat(a, axis) for a in r_arrs)
        l_idx, r_idx, l_valid, r_valid = _expand_matches(
            counts, lo, perm, l_ok, rok_all, out_cap_dev, left_join)
        outs = tuple(a[l_idx] for a in l_arrs) + \
            tuple(a[r_idx] for a in r_all)
        return (l_valid, r_valid) + outs

    n_out = 2 if count_only else (2 + n_l + n_r)
    out_specs = (P(), P()) if count_only else (P(axis),) * n_out
    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis),) * (4 + n_l + n_r),
        out_specs=out_specs,
    )
    return jax.jit(mapped)

"""Device mesh construction.

One axis ("shard") for horizontal table/graph partitioning — the analog of
the reference's Spark partition count (SURVEY.md §2 parallelism inventory
item 1).  The same program runs on a 1-chip or v5e-8 mesh; mesh size is
config, mirroring the reference's local[*] ≡ cluster property (§4 carry-over).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def _take_devices(n: int):
    devices = jax.devices()
    if len(devices) < n:
        raise ValueError(
            f"need {n} devices, have {len(devices)} "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N "
            "with JAX_PLATFORMS=cpu for virtual meshes)")
    return devices[:n]


def make_mesh(n_devices: Optional[int] = None, axis: str = "shard") -> Mesh:
    devices = (jax.devices() if n_devices is None
               else _take_devices(n_devices))
    return Mesh(np.array(devices), (axis,))


def make_mesh_2d(shape: Sequence[int], axis: str = "shard") -> Mesh:
    """Two-level mesh for multi-slice topologies: ("dcn", axis) with the
    slow axis OUTER — row sharding flattens over (dcn, axis) so
    consecutive blocks live on one slice, XLA keeps bulk collectives on
    ICI within a slice and crosses DCN only for the final combines
    (SURVEY.md §5.8: ICI within a slice, DCN across slices).  The
    hand-scheduled ppermute rings are a 1-D-mesh optimization; on 2-D
    meshes the engine uses the GSPMD partitioner paths."""
    n_dcn, n_ici = int(shape[0]), int(shape[1])
    arr = np.array(_take_devices(n_dcn * n_ici)).reshape(n_dcn, n_ici)
    return Mesh(arr, ("dcn", axis))

"""Sharded query execution steps over a device mesh.

The multi-chip execution path (SURVEY.md §5.8, §7 step 7): the graph's edge
table is sharded across the mesh axis; node-indexed frontier vectors are
combined with ``psum`` over ICI.  The same program runs on a 1-device or
v5e-8 mesh.

The flagship step is the 2-hop friend-of-friend MATCH (benchmark config 1)
in aggregate-pushdown form: counting paths (a)-[:KNOWS]->(b)-[:KNOWS]->(c)
with a seed predicate on ``a`` needs no row materialization — per-hop path
counts propagate as dense node vectors:

    cnt1[v] = Σ_{edges (u,v)} seed(u)          (segment-sum, psum)
    paths   = Σ_{edges (b,c)} cnt1[b]          (gather, psum)

which is two sparse-matrix/vector products against the adjacency — the
tensor-execution formulation of pattern joins (cf. PAPERS.md dimensional-
collapse / TrieJax lines of work).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from caps_tpu.parallel.compat import shard_map

from caps_tpu.parallel.collectives import (
    broadcast_concat, exchange_by_shard, global_sum, ring_shift, shard_of,
)


def two_hop_count_kernel(name_codes, edge_src, edge_dst, edge_ok, seed_code,
                         *, axis: str, n_nodes: int):
    """Per-device body (inside shard_map): edges are the local shard;
    ``name_codes`` is the replicated node property vector."""
    is_seed_edge = edge_ok & (name_codes[edge_src] == seed_code)
    local_cnt1 = jax.ops.segment_sum(
        is_seed_edge.astype(jnp.int32), edge_dst, num_segments=n_nodes)
    cnt1 = global_sum(local_cnt1, axis)          # frontier vector over ICI
    hop2 = jnp.where(edge_ok, cnt1[edge_src], 0)
    local_cnt2 = jax.ops.segment_sum(hop2, edge_dst, num_segments=n_nodes)
    cnt2 = global_sum(local_cnt2, axis)
    total = cnt2.sum()
    return total, cnt2


def make_sharded_two_hop(mesh: Mesh, n_nodes: int, axis: str = "shard"):
    """Build the jitted sharded 2-hop step for a mesh: edges sharded over
    ``axis``, node vector replicated, outputs replicated."""
    fn = functools.partial(two_hop_count_kernel, axis=axis, n_nodes=n_nodes)
    mapped = shard_map(
        fn, mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(axis), P()),
        out_specs=(P(), P()),
    )
    return jax.jit(mapped)


def collectives_smoke_kernel(x, *, axis: str, n_shards: int):
    """Exercises every collective the engine uses — all_to_all radix
    exchange, ppermute ring shift, all_gather broadcast, psum — in one
    shard_map body (used by the multichip dryrun)."""
    dest = shard_of(x, n_shards)
    exchanged = exchange_by_shard(x, dest, n_shards, axis, x.shape[0])
    shifted = ring_shift(exchanged.sum(axis=0), axis, n_shards)
    gathered = broadcast_concat(x[:4], axis)
    total = global_sum(x.sum() + shifted.sum() + gathered.sum(), axis)
    return total


def make_collectives_smoke(mesh: Mesh, axis: str = "shard"):
    n = mesh.devices.size
    fn = functools.partial(collectives_smoke_kernel, axis=axis, n_shards=n)
    mapped = shard_map(fn, mesh=mesh, in_specs=(P(axis),), out_specs=P())
    return jax.jit(mapped)

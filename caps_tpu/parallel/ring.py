"""Ring-scheduled k-hop frontier expansion over the ICI.

SURVEY.md §5.7: ``BoundedVarLengthExpand`` is the engine's "long sequence"
— a data-dependent frontier growing hop by hop.  For sharded graphs the
frontier (a dense per-node count vector, the aggregate-pushdown form of
expansion — see query_step.py) is **node-block partitioned**, adjacency
shards stay resident, and blocks rotate around the ring with ``ppermute``
— ring attention's communication schedule with (gather ⋈ segment-sum) in
place of (QKᵀ · softmax):

    step t: shard s holds frontier block (s - t) mod S
            local edges whose src falls in that block pick up cnt[src]
    after S steps every local edge has its source count; one segment-sum
    by dst + psum_scatter returns the next frontier, again block-sharded.

Per hop each shard sends N/S counts S-1 times — the same bytes as an
all_gather, but pipelined against the local gather so compute hides the
ICI latency, and no shard ever materializes the full frontier.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from caps_tpu.obs.compile import charged as _compile_charged
from caps_tpu.parallel.collectives import note_collective
from caps_tpu.parallel.compat import pcast, shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _ring_hop(cnt_block, edge_src, edge_dst, edge_ok, *, axis: str,
              n_nodes: int, n_shards: int):
    """One hop: node-block-sharded counts -> next counts, block-sharded."""
    nb = n_nodes // n_shards
    my = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    # trace-time accounting (obs/): the fori body traces ONCE but the
    # rotation runs n_shards times per hop — scale the byte estimate
    note_collective("ring.ppermute", cnt_block, scale=n_shards,
                    rotations=n_shards)

    def body(t, carry):
        blk, acc = carry
        block_id = (my - t) % n_shards
        lo = block_id * nb
        m = edge_ok & (edge_src >= lo) & (edge_src < lo + nb)
        local = jnp.clip(edge_src - lo, 0, nb - 1)
        acc = acc + jnp.where(m, blk[local], 0)
        blk = jax.lax.ppermute(blk, axis, perm)
        return blk, acc

    # the accumulator becomes device-varying on the first iteration, so the
    # loop carry must start with matching vma type
    acc0 = pcast(jnp.zeros(edge_src.shape, cnt_block.dtype), axis,
                 to="varying")
    _, per_edge = jax.lax.fori_loop(0, n_shards, body, (cnt_block, acc0))
    local_out = jax.ops.segment_sum(per_edge, edge_dst,
                                    num_segments=n_nodes)
    # psum + scatter back to node blocks in one collective
    note_collective("ring.psum_scatter", local_out)
    return jax.lax.psum_scatter(local_out, axis, scatter_dimension=0,
                                tiled=True)


def make_ring_khop(mesh: Mesh, n_nodes: int, n_hops: int,
                   axis: str = "shard", masked: bool = False):
    """Build the jitted k-hop ring expansion: seed counts and edges come
    in sharded (node blocks / edge shards), result is the total path count
    and the final block-sharded frontier.  With ``masked``, a node-block-
    sharded mask vector is multiplied into the frontier after every hop
    (the planner's per-hop node-existence/label mask)."""
    n_shards = int(mesh.devices.size)
    if n_nodes % n_shards:
        raise ValueError(f"n_nodes {n_nodes} must divide over {n_shards}")
    hop = functools.partial(_ring_hop, axis=axis, n_nodes=n_nodes,
                            n_shards=n_shards)

    def check_edges(edge_src, edge_dst, edge_ok):
        for name, arr in (("edge_src", edge_src), ("edge_dst", edge_dst),
                          ("edge_ok", edge_ok)):
            if arr.shape[0] % n_shards:
                raise ValueError(
                    f"{name} length {arr.shape[0]} must divide over "
                    f"{n_shards} shards; pad edges (edge_ok=False) to a "
                    f"multiple of the shard count")

    if masked:
        def body(seed_block, edge_src, edge_dst, edge_ok, mask_block):
            blk = seed_block
            for _ in range(n_hops):
                blk = hop(blk, edge_src, edge_dst, edge_ok) * mask_block
            total = jax.lax.psum(blk.sum(), axis)
            return total, blk
        in_specs = (P(axis),) * 5
    else:
        def body(seed_block, edge_src, edge_dst, edge_ok):
            blk = seed_block
            for _ in range(n_hops):
                blk = hop(blk, edge_src, edge_dst, edge_ok)
            total = jax.lax.psum(blk.sum(), axis)
            return total, blk
        in_specs = (P(axis),) * 4

    mapped = shard_map(body, mesh=mesh, in_specs=in_specs,
                       out_specs=(P(), P(axis)))
    jitted = jax.jit(mapped)

    def call(seed_block, edge_src, edge_dst, edge_ok, mask_block=None):
        check_edges(edge_src, edge_dst, edge_ok)
        if seed_block.shape[0] != n_nodes:
            raise ValueError(f"seed length {seed_block.shape[0]} != n_nodes "
                             f"{n_nodes}")
        if masked != (mask_block is not None):
            raise ValueError("mask_block must be passed iff masked=True")
        args = (seed_block, edge_src, edge_dst, edge_ok)
        return jitted(*args, mask_block) if masked else jitted(*args)

    return call


def _ring_hop_matrix(f_block, edge_src, edge_dst, edge_ok, *, axis: str,
                     n_nodes: int, n_shards: int, edge_w=None):
    """One hop of the MATRIX frontier: ``f_block`` is the (seeds,
    node-block) slice of a per-seed path-count matrix F[s, v].  Blocks
    rotate around the ring exactly as in ``_ring_hop``; the seed axis
    stays local, so this is the general VarExpand frontier exchange — the
    aggregate form above is the seeds==1 special case.  ``edge_w``
    weights each edge's contribution (the 3-hop isomorphism correction
    applies weighted sparse hops)."""
    nb = n_nodes // n_shards
    n_seeds = f_block.shape[0]
    my = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    note_collective("ring.ppermute", f_block, scale=n_shards,
                    rotations=n_shards)

    def body(t, carry):
        blk, acc = carry  # blk: (S, nb); acc: (S, E_local)
        block_id = (my - t) % n_shards
        lo = block_id * nb
        m = edge_ok & (edge_src >= lo) & (edge_src < lo + nb)
        local = jnp.clip(edge_src - lo, 0, nb - 1)
        contrib = blk[:, local]
        if edge_w is not None:
            contrib = contrib * edge_w[None, :]
        acc = acc + jnp.where(m[None, :], contrib, 0)
        blk = jax.lax.ppermute(blk, axis, perm)
        return blk, acc

    acc0 = pcast(
        jnp.zeros((n_seeds, edge_src.shape[0]), f_block.dtype), axis,
        to="varying")
    _, per_edge = jax.lax.fori_loop(0, n_shards, body, (f_block, acc0))
    local_out = jax.ops.segment_sum(per_edge.T, edge_dst,
                                    num_segments=n_nodes)  # (N, S)
    note_collective("ring.psum_scatter", local_out)
    out = jax.lax.psum_scatter(local_out, axis, scatter_dimension=0,
                               tiled=True)  # (nb, S)
    return out.T


def make_ring_varexpand(mesh: Mesh, n_nodes: int, lengths: tuple,
                        axis: str = "shard", correction: str = "loops"):
    """Jitted ring-scheduled var-length expand: per-seed PATH-count matrix
    over the union of ``lengths`` (each in 0..2), with the relationship-
    isomorphism correction applied at length 2.  ``correction`` names the
    invalid-walk structure of the edge list:

      * ``"loops"`` (uniform OUT/IN direction): the only length-2 walk
        reusing its relationship is a self-loop taken twice — subtract
        the per-node self-loop count on the diagonal;
      * ``"degree"`` (undirected — the edge list arrives symmetrized,
        self-loops once): every incident edge yields exactly one
        there-and-back walk s -e- m -e- s — subtract the per-node count
        of symmetrized edges leaving the node (which counts non-loop
        incident edges once per endpoint and self-loops once).

    Inputs arrive sharded: the seed-indicator matrix F0 (seeds, n_nodes)
    node-block sharded on its node axis, edges edge-sharded, the
    target-node mask node-block sharded.  Output is the (seeds, n_nodes)
    multiplicity matrix M[s, v] = #paths seed_s ->..-> v with len in
    ``lengths`` and v in the mask."""
    n_shards = int(mesh.devices.size)
    if n_nodes % n_shards:
        raise ValueError(f"n_nodes {n_nodes} must divide over {n_shards}")
    if correction not in ("loops", "degree"):
        raise ValueError(correction)
    max_len = max(lengths) if lengths else 0
    if max_len > 2:
        raise ValueError("ring var-expand supports lengths <= 2")
    hop = functools.partial(_ring_hop_matrix, axis=axis, n_nodes=n_nodes,
                            n_shards=n_shards)

    def body(f0_block, edge_src, edge_dst, edge_ok, tmask_block):
        out = jnp.zeros_like(f0_block)
        if 0 in lengths:
            out = out + f0_block * tmask_block[None, :]
        f = f0_block
        for length in range(1, max_len + 1):
            f = hop(f, edge_src, edge_dst, edge_ok)
            if length == 2:
                # relationship-isomorphism correction on the diagonal
                # (see docstring)
                loc = _r2_vector(edge_src, edge_dst, edge_ok, n_nodes,
                                 f.dtype, correction)
                corr = jax.lax.psum_scatter(loc, axis, scatter_dimension=0,
                                            tiled=True)  # (nb,)
                f = f - f0_block * corr[None, :]
            if length in lengths:
                out = out + f * tmask_block[None, :]
        return out

    mapped = shard_map(body, mesh=mesh,
                       in_specs=(P(None, axis), P(axis), P(axis), P(axis),
                                 P(axis)),
                       out_specs=P(None, axis))
    return jax.jit(mapped)


def _r2_vector(edge_src, edge_dst, edge_ok, n_nodes, dtype,
               correction: str):
    """Per-node reuse-pair count: self-loops (uniform direction) or the
    symmetrized degree (undirected) — the length-2 isomorphism
    correction vector, also the A12/A23 factor of the 3-hop one."""
    if correction == "loops":
        bad = edge_ok & (edge_src == edge_dst)
    else:
        bad = edge_ok
    return jax.ops.segment_sum(bad.astype(dtype), edge_src,
                               num_segments=n_nodes)


def make_ring_varexpand3(mesh: Mesh, n_nodes: int, lengths: tuple,
                         axis: str = "shard", correction: str = "loops"):
    """Ring-scheduled var-expand for lengths up to 3.  Walk counts are
    SpMV hops; relationship isomorphism is restored per length:

        P2 = W2 − F0·r2                                (reuse at start)
        P3 = W3 − A12 − A23 − A13 + 2T   (inclusion–exclusion over the
                                          pairs (1,2), (2,3), (1,3);
                                          every pairwise intersection is
                                          the all-equal triple T)
        A12 = H(F0 ⊙ r2)        — same-rel pair first, any third hop
        A23 = H(F0) ⊙ r2        — any first hop, same-rel pair after
        A13 = H_sp13(F0)        — first rel reused as third; the free
                                  middle hop's count is folded into a
                                  host-built weighted sparse hop
        T   = H_spT(F0)         — all three the same rel

    Extra inputs beyond make_ring_varexpand's: the two weighted sparse
    edge lists (sp13/spT as (src, dst, w) triples, edge-sharded)."""
    n_shards = int(mesh.devices.size)
    if n_nodes % n_shards:
        raise ValueError(f"n_nodes {n_nodes} must divide over {n_shards}")
    if correction not in ("loops", "degree"):
        raise ValueError(correction)
    max_len = max(lengths) if lengths else 0
    if max_len != 3:
        raise ValueError("use make_ring_varexpand for lengths <= 2")
    hop = functools.partial(_ring_hop_matrix, axis=axis, n_nodes=n_nodes,
                            n_shards=n_shards)

    def body(f0, e_src, e_dst, e_ok, tmask, s13_src, s13_dst, s13_w,
             st_src, st_dst, st_w):
        loc = _r2_vector(e_src, e_dst, e_ok, n_nodes, f0.dtype, correction)
        r2 = jax.lax.psum_scatter(loc, axis, scatter_dimension=0,
                                  tiled=True)  # (nb,) node-block sharded
        out = jnp.zeros_like(f0)
        if 0 in lengths:
            out = out + f0 * tmask[None, :]
        f1 = hop(f0, e_src, e_dst, e_ok)
        if 1 in lengths:
            out = out + f1 * tmask[None, :]
        f2 = hop(f1, e_src, e_dst, e_ok)
        if 2 in lengths:
            out = out + (f2 - f0 * r2[None, :]) * tmask[None, :]
        f3 = hop(f2, e_src, e_dst, e_ok)
        a12 = hop(f0 * r2[None, :], e_src, e_dst, e_ok)
        a23 = f1 * r2[None, :]
        a13 = hop(f0, s13_src, s13_dst, s13_w > 0, edge_w=s13_w)
        t3 = hop(f0, st_src, st_dst, st_w > 0, edge_w=st_w)
        p3 = f3 - a12 - a23 - a13 + 2 * t3
        return out + p3 * tmask[None, :]

    mapped = shard_map(body, mesh=mesh,
                       in_specs=(P(None, axis),) + (P(axis),) * 10,
                       out_specs=P(None, axis))
    return jax.jit(mapped)


def ring_varexpand3_reference(f0, edge_src, edge_dst, edge_ok, tmask,
                              lengths: tuple, s13, st,
                              correction: str = "loops"):
    """Single-device jnp twin of make_ring_varexpand3 (``s13``/``st`` are
    (src, dst, w) array triples)."""
    if (max(lengths) if lengths else 0) != 3:
        raise ValueError("use ring_varexpand_reference for lengths <= 2")
    n_nodes = f0.shape[1]

    def hop(f, src, dst, ok, w=None):
        per_edge = jnp.where(ok[None, :], f[:, src], 0)
        if w is not None:
            per_edge = per_edge * w[None, :]
        return jax.ops.segment_sum(per_edge.T, dst,
                                   num_segments=n_nodes).T

    r2 = _r2_vector(edge_src, edge_dst, edge_ok, n_nodes, f0.dtype,
                    correction)
    out = jnp.zeros_like(f0)
    if 0 in lengths:
        out = out + f0 * tmask[None, :]
    f1 = hop(f0, edge_src, edge_dst, edge_ok)
    if 1 in lengths:
        out = out + f1 * tmask[None, :]
    f2 = hop(f1, edge_src, edge_dst, edge_ok)
    if 2 in lengths:
        out = out + (f2 - f0 * r2[None, :]) * tmask[None, :]
    f3 = hop(f2, edge_src, edge_dst, edge_ok)
    a12 = hop(f0 * r2[None, :], edge_src, edge_dst, edge_ok)
    a23 = f1 * r2[None, :]
    a13 = hop(f0, s13[0], s13[1], s13[2] > 0, w=s13[2])
    t3 = hop(f0, st[0], st[1], st[2] > 0, w=st[2])
    return out + (f3 - a12 - a23 - a13 + 2 * t3) * tmask[None, :]


@functools.lru_cache(maxsize=128)
def ring_varexpand3_cached(mesh: Mesh, n_nodes: int, lengths: tuple,
                           axis: str = "shard",
                           correction: str = "loops"):
    return make_ring_varexpand3(mesh, n_nodes, lengths, axis, correction)


@functools.lru_cache(maxsize=32)
def ring_varexpand3_single(lengths: tuple, correction: str = "loops"):
    @jax.jit
    def fn(f0, edge_src, edge_dst, edge_ok, tmask, s13_src, s13_dst,
           s13_w, st_src, st_dst, st_w):
        return ring_varexpand3_reference(
            f0, edge_src, edge_dst, edge_ok, tmask, lengths,
            (s13_src, s13_dst, s13_w), (st_src, st_dst, st_w), correction)

    return fn


def build_iso3_sparse(frm, to, rid, n_nodes: int):
    """Host-side weighted sparse edge lists for the 3-hop correction.

    ``frm``/``to``/``rid`` describe the ENTRY list the hops traverse
    (symmetrized for undirected patterns; each entry carries its
    underlying relationship id).  Returns (sp13, spT) as (src, dst, w)
    numpy triples:

      * sp13: for each ordered orientation pair (o1, o3) of one
        relationship, an edge from(o1) -> to(o3) weighted by the number
        of entries that can serve as the free middle hop
        to(o1) -> from(o3);
      * spT: for each orientation chain o1 -> o2 -> o3 of one
        relationship, an edge from(o1) -> to(o3) with weight 1.
    """
    import numpy as np
    frm = np.asarray(frm, dtype=np.int64)
    to = np.asarray(to, dtype=np.int64)
    rid = np.asarray(rid, dtype=np.int64)

    # entry-count lookup between ordered node pairs
    keys = np.sort(frm * n_nodes + to)

    def cnt(x, y):
        q = x * n_nodes + y
        return (np.searchsorted(keys, q, side="right")
                - np.searchsorted(keys, q, side="left"))

    # group entries by relationship id: 1 orientation (directed or a
    # loop) or 2 (undirected non-loop)
    order = np.argsort(rid, kind="stable")
    r_sorted = rid[order]
    first = np.ones(len(rid), dtype=bool)
    first[1:] = r_sorted[1:] != r_sorted[:-1]
    starts = np.nonzero(first)[0]
    counts = np.diff(np.append(starts, len(rid)))

    s13_s, s13_d, s13_w = [], [], []
    st_s, st_d, st_w = [], [], []
    if counts.size and int(counts.max()) > 2:
        # a rel id appearing 3+ times means a malformed entry list
        # (e.g. double symmetrization); an omitted correction would be a
        # silent wrong answer, so fail loudly
        raise ValueError("entry list has a relationship id with more "
                         "than two orientations")
    one = starts[counts == 1]
    u1, v1 = frm[order[one]], to[order[one]]
    # single-orientation rels: (o1, o3) = (e, e); chain o1->o2->o3 needs
    # o2 = e too, which chains only for loops
    s13_s.append(u1)
    s13_d.append(v1)
    s13_w.append(cnt(v1, u1))
    lo = u1 == v1
    st_s.append(u1[lo])
    st_d.append(v1[lo])
    st_w.append(np.ones(int(lo.sum()), dtype=np.int64))
    two = starts[counts == 2]
    if len(two):
        ua, va = frm[order[two]], to[order[two]]        # orientation uv
        # orientation pairs (see make_ring_varexpand3 docstring)
        s13_s.append(np.concatenate([ua, ua, va, va]))
        s13_d.append(np.concatenate([va, ua, va, ua]))
        s13_w.append(np.concatenate([cnt(va, ua), cnt(va, va),
                                     cnt(ua, ua), cnt(ua, va)]))
        # chains: u -e- v -e- u -e- v and the reverse
        st_s.append(np.concatenate([ua, va]))
        st_d.append(np.concatenate([va, ua]))
        st_w.append(np.ones(2 * len(two), dtype=np.int64))

    def pack(ss, dd, ww):
        s = np.concatenate(ss) if ss else np.zeros(0, np.int64)
        d = np.concatenate(dd) if dd else np.zeros(0, np.int64)
        w = np.concatenate(ww) if ww else np.zeros(0, np.int64)
        keep = w > 0
        return (s[keep].astype(np.int32), d[keep].astype(np.int32),
                w[keep])

    return pack(s13_s, s13_d, s13_w), pack(st_s, st_d, st_w)


def ring_varexpand_reference(f0, edge_src, edge_dst, edge_ok, tmask,
                             lengths: tuple, correction: str = "loops"):
    """Single-device jnp twin for differential tests."""
    n_nodes = f0.shape[1]
    out = jnp.zeros_like(f0)
    if 0 in lengths:
        out = out + f0 * tmask[None, :]
    f = f0
    for length in range(1, (max(lengths) if lengths else 0) + 1):
        per_edge = jnp.where(edge_ok[None, :], f[:, edge_src], 0)
        f = jax.ops.segment_sum(per_edge.T, edge_dst,
                                num_segments=n_nodes).T
        if length == 2:
            corr = _r2_vector(edge_src, edge_dst, edge_ok, n_nodes,
                              f.dtype, correction)
            f = f - f0 * corr[None, :]
        if length in lengths:
            out = out + f * tmask[None, :]
    return out


@functools.lru_cache(maxsize=128)
def ring_varexpand_cached(mesh: Mesh, n_nodes: int, lengths: tuple,
                          axis: str = "shard", correction: str = "loops"):
    """Memoized make_ring_varexpand (compiled program reuse per shape).
    A miss is a compile boundary: it charges the compile ledger
    (obs/compile.py) under the executing query's family."""
    with _compile_charged("dist_join",
                          shape=f"varexpand:{n_nodes}:{lengths}:"
                                f"{correction}"):
        return make_ring_varexpand(mesh, n_nodes, lengths, axis, correction)


@functools.lru_cache(maxsize=32)
def ring_varexpand_single(lengths: tuple, correction: str = "loops"):
    """Single-device matrix var-expand: the same SpMV-hop computation as
    the ring body, without collectives, as one jitted program (the
    VarExpand matrix strategy off-mesh).  One wrapper per (lengths,
    correction) — jax's own trace cache handles the shapes.  A miss
    charges the compile ledger (the jit wrapper build; the per-shape
    trace+compile lands on the first dispatch)."""
    with _compile_charged("dist_join",
                          shape=f"varexpand1:{lengths}:{correction}"):
        @jax.jit
        def fn(f0, edge_src, edge_dst, edge_ok, tmask):
            return ring_varexpand_reference(f0, edge_src, edge_dst, edge_ok,
                                            tmask, lengths, correction)

        return fn


@functools.lru_cache(maxsize=128)
def ring_khop_cached(mesh: Mesh, n_nodes: int, n_hops: int,
                     axis: str = "shard", masked: bool = False):
    """Memoized make_ring_khop: repeat queries reuse the traced + compiled
    shard_map program instead of re-jitting per call.  A miss charges
    the compile ledger (obs/compile.py)."""
    with _compile_charged("dist_join",
                          shape=f"khop:{n_nodes}:{n_hops}:{masked}"):
        return make_ring_khop(mesh, n_nodes, n_hops, axis, masked)


def ring_khop_reference(seed_counts, edge_src, edge_dst, edge_ok,
                        n_hops: int, n_nodes: int):
    """Single-device jnp twin for differential tests."""
    cnt = seed_counts
    for _ in range(n_hops):
        per_edge = jnp.where(edge_ok, cnt[edge_src], 0)
        cnt = jax.ops.segment_sum(per_edge, edge_dst,
                                  num_segments=n_nodes)
    return cnt.sum(), cnt

"""CONSTRUCT / RETURN GRAPH planning (multiple-graph queries).

Mirrors the reference's ``ConstructGraphPlanner`` — CLONE/NEW/SET over the
driving rows, id-space management, result graph = UnionGraph(built, ON
graphs) (ref: okapi-relational/.../impl/graph/ConstructGraphPlanner.scala —
reconstructed, mount empty; SURVEY.md §3.4).

Semantics implemented:
  * ``CONSTRUCT ON g1, g2`` seeds the result with the union of those graphs;
  * ``CLONE a [AS b]`` copies the bound entity (distinct by id) into the
    built graph — skipped when ON graphs are present and no SET touches it
    (the entity is already in the union);
  * ``NEW (x)-[:T]->(y)`` creates entities per driving row; endpoints may
    be bound/cloned vars (their ids) or fresh vars (ids allocated beyond
    every id visible in the inputs);
  * ``SET x.k = expr / SET x:Label`` applies to cloned/new entities.

The build step materializes the driving rows host-side and groups new
entities by label combination / relationship type into scan tables — the
CONSTRUCT path is catalog machinery, not the per-query hot path.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from caps_tpu.frontend import ast
from caps_tpu.ir import exprs as E
from caps_tpu.okapi.types import CTInteger, from_python, join_all
from caps_tpu.relational import ops as R
from caps_tpu.relational.header import RecordHeader


class ConstructError(Exception):
    pass


class GraphResultOp(R.RelationalOperator):
    """A relational operator whose result is a graph (RETURN GRAPH)."""

    def __init__(self, context, graph):
        super().__init__(context)
        self._graph = graph

    @property
    def result_graph(self):
        return self._graph

    def _compute(self):
        return RecordHeader.empty(), self.context.factory.unit()


class ConstructOp(R.RelationalOperator):
    def __init__(self, context, parent: R.RelationalOperator,
                 on_graphs: Tuple, clones, news, sets, session,
                 working_graph):
        super().__init__(context, [parent])
        self.on_graphs = on_graphs
        self.clones = clones
        self.news = news
        self.sets = sets
        self.session = session
        self.working_graph = working_graph
        self._graph_cache = None

    def _compute(self):
        return RecordHeader.empty(), self.context.factory.unit()

    @property
    def result_graph(self):
        if self._graph_cache is None:
            self._graph_cache = self._build_graph()
        return self._graph_cache

    # ------------------------------------------------------------------

    def _build_graph(self):
        from caps_tpu.relational.graphs import UnionGraph
        parent = self.children[0]
        header, table = parent.result
        # exact: CONSTRUCT mints entity ids per input row — a served
        # upper bound (generic fused replay) would mint phantom entities
        n = table.exact_size()
        params = self.context.parameters

        set_vars = {s.var for s in self.sets}
        clone_specs: Dict[str, E.Expr] = {c.var: c.source for c in self.clones}

        # Vars used as NEW endpoints that are bound in scope become
        # implicit clones.
        bound = set(header.vars)
        for pat in self.news:
            for part in pat.parts:
                for el in part.elements:
                    if isinstance(el, ast.NodePattern) and el.var \
                            and el.var in bound and el.var not in clone_specs:
                        clone_specs[el.var] = E.Var(el.var)

        # SET on a cloned ON-graph entity must *replace* the original, not
        # add a modified twin beside it (UnionGraph ids are disjoint).  In
        # that case the ON graphs are materialized into the build and the
        # union is dropped — overlay semantics.
        overlay = bool(self.on_graphs) and bool(set_vars & set(clone_specs))

        # Materialize what each bound entity var looks like per row.
        def entity_rows(var: str):
            v = E.Var(var)
            ids = table.column_values(header.column(v))
            labels = []
            props = []
            for e in header.exprs:
                if isinstance(e, E.HasLabel) and e.node == v:
                    labels.append((e.label,
                                   table.column_values(header.column(e))))
                elif isinstance(e, E.Property) and e.entity == v:
                    props.append((e.key,
                                  table.column_values(header.column(e))))
            return ids, labels, props

        def rel_rows(var: str):
            v = E.Var(var)
            ids = table.column_values(header.column(v))
            srcs = table.column_values(header.column(E.StartNode(v)))
            tgts = table.column_values(header.column(E.EndNode(v)))
            typs = table.column_values(header.column(E.Type(v)))
            props = []
            for e in header.exprs:
                if isinstance(e, E.Property) and e.entity == v:
                    props.append((e.key,
                                  table.column_values(header.column(e))))
            return ids, srcs, tgts, typs, props

        # Base for freshly-allocated ids: beyond everything visible.
        max_id = 0
        for var in header.entity_vars:
            vals = table.column_values(header.column(E.Var(var)))
            max_id = max([max_id] + [v for v in vals if v is not None])
        for g in self.on_graphs + ((self.working_graph,)
                                   if self.working_graph else ()):
            max_id = max(max_id, _max_graph_id(g))
        next_id = [max_id + 1]

        def alloc(count: int) -> List[int]:
            base = next_id[0]
            next_id[0] += count
            return list(range(base, base + count))

        # nodes[id] = (set(labels), {key: value}); collected then grouped
        nodes: Dict[int, Tuple[set, Dict[str, Any]]] = {}
        # rels[id] = [src, tgt, type, {key: value}]
        rels: Dict[int, List[Any]] = {}
        # per-row id bindings for construct-scope vars
        row_ids: Dict[str, List[Optional[int]]] = {}

        if overlay:
            for g in self.on_graphs:
                _materialize_graph_into(nodes, rels, g)

        from caps_tpu.okapi.types import _CTRelationship
        # 1. clones
        for var, src in clone_specs.items():
            if not isinstance(src, E.Var):
                raise ConstructError("CLONE source must be a variable")
            src_t = header.var_type(src.name).material
            if isinstance(src_t, _CTRelationship):
                ids, srcs, tgts, typs, props = rel_rows(src.name)
                row_ids[var] = ids
                if self.on_graphs and not overlay and var not in set_vars:
                    continue  # entity already present via the ON-union
                for i, rid in enumerate(ids):
                    if rid is None or rid in rels:
                        continue
                    p = {k: col[i] for k, col in props if col[i] is not None}
                    rels[rid] = [srcs[i], tgts[i], typs[i] or "", p]
            else:
                ids, labels, props = entity_rows(src.name)
                row_ids[var] = ids
                if self.on_graphs and not overlay and var not in set_vars:
                    continue  # entity already present via the ON-union
                for i, nid in enumerate(ids):
                    if nid is None or nid in nodes:
                        continue
                    lbls = {l for l, col in labels if col[i] is True}
                    p = {k: col[i] for k, col in props if col[i] is not None}
                    nodes[nid] = (lbls, p)

        # 2. NEW patterns
        def eval_props(props_expr: Optional[E.Expr]) -> List[Dict[str, Any]]:
            if props_expr is None:
                return [{} for _ in range(n)]
            if not isinstance(props_expr, E.MapLit):
                raise ConstructError("NEW properties must be a map literal")
            from caps_tpu.backends.local.expr import evaluate
            out: List[Dict[str, Any]] = [dict() for _ in range(n)]
            for key, vexpr in zip(props_expr.keys, props_expr.values):
                resolved = R.resolve_expr(vexpr, header)
                col = evaluate(resolved, n, lambda c: table.column_values(c),
                               header, params)
                for i in range(n):
                    if col[i] is not None:
                        out[i][key] = col[i]
            return out

        for pat in self.news:
            for part in pat.parts:
                prev_ids: Optional[List[Optional[int]]] = None
                pending_rel: Optional[ast.RelPattern] = None
                for el in part.elements:
                    if isinstance(el, ast.NodePattern):
                        if el.var and el.var in row_ids:
                            ids = row_ids[el.var]
                            if el.labels or el.properties is not None:
                                props = eval_props(el.properties)
                                for i, nid in enumerate(ids):
                                    if nid is None or nid not in nodes:
                                        continue
                                    nodes[nid][0].update(el.labels)
                                    nodes[nid][1].update(props[i])
                        else:
                            ids = alloc(n)
                            props = eval_props(el.properties)
                            for i, nid in enumerate(ids):
                                nodes[nid] = (set(el.labels), props[i])
                            if el.var:
                                row_ids[el.var] = ids
                        if pending_rel is not None:
                            rel = pending_rel
                            if len(rel.rel_types) != 1:
                                raise ConstructError(
                                    "NEW relationships need exactly one type")
                            rprops = eval_props(rel.properties)
                            rids = alloc(n)
                            if rel.var:
                                row_ids[rel.var] = rids
                            assert prev_ids is not None
                            for i in range(n):
                                a, b = prev_ids[i], ids[i]
                                if a is None or b is None:
                                    continue
                                if rel.direction == ast.Direction.INCOMING:
                                    a, b = b, a
                                rels[rids[i]] = [a, b, rel.rel_types[0],
                                                 rprops[i]]
                            pending_rel = None
                        prev_ids = ids
                    else:
                        pending_rel = el

        # 3. SET items on construct-scope entities
        from caps_tpu.backends.local.expr import evaluate
        for item in self.sets:
            if item.var not in row_ids:
                raise ConstructError(
                    f"SET on unknown construct variable `{item.var}`")
            ids = row_ids[item.var]
            if item.labels:
                for nid in ids:
                    if nid is not None and nid in nodes:
                        nodes[nid][0].update(item.labels)
                continue
            if item.key is None or item.value is None:
                raise ConstructError("SET supports `var.key = expr` and labels")
            resolved = R.resolve_expr(item.value, header)
            col = evaluate(resolved, n, lambda c: table.column_values(c),
                           header, params)
            for i, eid in enumerate(ids):
                if eid is None or col[i] is None:
                    continue
                if eid in nodes:
                    nodes[eid][1][item.key] = col[i]
                elif eid in rels:
                    rels[eid][3][item.key] = col[i]

        built = _tables_from_entities(self.session, nodes, rels)
        graphs = ((tuple(self.on_graphs) if not overlay else ())
                  + (built,))
        if len(graphs) == 1:
            return built
        from caps_tpu.relational.graphs import UnionGraph
        return UnionGraph(self.session, graphs)


def _materialize_graph_into(nodes: Dict[int, Tuple[set, Dict[str, Any]]],
                            rels: Dict[int, List[Any]], graph) -> None:
    """Copy a graph's entities into the host-side build dicts (overlay
    path: ON-graph entities get replaced by SET-modified clones in place).
    First writer wins, matching the clone loops' dedup-by-id."""
    for nt in getattr(graph, "node_tables", ()):
        m = nt.mapping
        ids = nt.table.column_values(m.id_col)
        prop_cols = {k: nt.table.column_values(c)
                     for k, c in m.property_cols.items()}
        for i, nid in enumerate(ids):
            if nid is None or nid in nodes:
                continue
            props = {k: col[i] for k, col in prop_cols.items()
                     if col[i] is not None}
            nodes[nid] = (set(m.labels), props)
    for rt in getattr(graph, "rel_tables", ()):
        m = rt.mapping
        ids = rt.table.column_values(m.id_col)
        srcs = rt.table.column_values(m.source_col)
        tgts = rt.table.column_values(m.target_col)
        prop_cols = {k: rt.table.column_values(c)
                     for k, c in m.property_cols.items()}
        for i, rid in enumerate(ids):
            if rid is None or rid in rels:
                continue
            props = {k: col[i] for k, col in prop_cols.items()
                     if col[i] is not None}
            rels[rid] = [srcs[i], tgts[i], m.rel_type, props]
    for sub in getattr(graph, "graphs", ()):
        _materialize_graph_into(nodes, rels, sub)


def _max_graph_id(graph) -> int:
    out = 0
    try:
        node_tables = getattr(graph, "node_tables", ())
        rel_tables = getattr(graph, "rel_tables", ())
        for nt in node_tables:
            vals = nt.table.column_values(nt.mapping.id_col)
            out = max([out] + [v for v in vals if v is not None])
        for rt in rel_tables:
            vals = rt.table.column_values(rt.mapping.id_col)
            out = max([out] + [v for v in vals if v is not None])
        for sub in getattr(graph, "graphs", ()):
            out = max(out, _max_graph_id(sub))
    except Exception:
        pass
    return out


def _tables_from_entities(session, nodes, rels):
    """Group host-side entity dicts into scan tables (same shape as the
    testing factory's grouping)."""
    from caps_tpu.relational.entity_tables import (
        NodeMapping, NodeTable, RelationshipMapping, RelationshipTable,
    )
    factory = session.table_factory

    by_labels: Dict[Tuple[str, ...], List[Tuple[int, Dict[str, Any]]]] = {}
    for nid, (labels, props) in nodes.items():
        by_labels.setdefault(tuple(sorted(labels)), []).append((nid, props))
    node_tables = []
    for labels, rows in sorted(by_labels.items()):
        keys = sorted({k for _, p in rows for k in p})
        types = {"_id": CTInteger}
        data: Dict[str, List[Any]] = {"_id": [nid for nid, _ in rows]}
        for k in keys:
            vals = [p.get(k) for _, p in rows]
            t = join_all(from_python(v) for v in vals if v is not None)
            if any(v is None for v in vals):
                t = t.nullable
            types[k] = t
            data[k] = vals
        mapping = NodeMapping.on("_id").with_implied_labels(*labels)
        for k in keys:
            mapping = mapping.with_property(k)
        node_tables.append(NodeTable(mapping, factory.from_columns(data, types)))

    by_type: Dict[str, List[Tuple[int, int, int, Dict[str, Any]]]] = {}
    for rid, (src, tgt, rel_type, props) in rels.items():
        by_type.setdefault(rel_type, []).append((rid, src, tgt, props))
    rel_tables = []
    for rel_type, rows in sorted(by_type.items()):
        keys = sorted({k for *_, p in rows for k in p})
        types = {"_id": CTInteger, "_src": CTInteger, "_tgt": CTInteger}
        data = {"_id": [r[0] for r in rows], "_src": [r[1] for r in rows],
                "_tgt": [r[2] for r in rows]}
        for k in keys:
            vals = [r[3].get(k) for r in rows]
            t = join_all(from_python(v) for v in vals if v is not None)
            if any(v is None for v in vals):
                t = t.nullable
            types[k] = t
            data[k] = vals
        mapping = RelationshipMapping.on(rel_type)
        for k in keys:
            mapping = mapping.with_property(k)
        rel_tables.append(
            RelationshipTable(mapping, factory.from_columns(data, types)))
    return session.create_graph(node_tables, rel_tables)


def plan_construct(planner, op):
    """Entry from the relational planner for ConstructGraph / ReturnGraph."""
    from caps_tpu.logical import ops as L
    if isinstance(op, L.ReturnGraph):
        planned = planner.plan_op(op.parent)
        if isinstance(planned, (ConstructOp, GraphResultOp)):
            return planned
        # plain `FROM GRAPH g RETURN GRAPH`
        return GraphResultOp(planner.context, planner.current_graph)
    assert isinstance(op, L.ConstructGraph)
    parent = planner.plan_op(op.parent)
    resolved_on = tuple(planner.graph_resolver(qgn) for qgn in op.on_graphs) \
        if planner.graph_resolver else ()
    session = planner.context.session
    return ConstructOp(planner.context, parent, resolved_on, op.clones,
                       op.news, op.sets, session, planner.current_graph)

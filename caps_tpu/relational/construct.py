"""CONSTRUCT / RETURN GRAPH planning (multiple-graph queries).

Mirrors the reference's ``ConstructGraphPlanner`` (ref:
okapi-relational/.../impl/graph/ConstructGraphPlanner.scala —
reconstructed, mount empty; SURVEY.md §3.4).  Full implementation lands
with the catalog milestone; see tests/test_multiple_graph.py.
"""
from __future__ import annotations


def plan_construct(planner, op):
    raise NotImplementedError(
        "CONSTRUCT/RETURN GRAPH planning not implemented yet")

"""The tensor-path cost model: price plans in padded-bucket device terms.

ROADMAP item 3's optimizer core.  The model spends two substrates the
engine already maintains:

* **ingest-time statistics** (relational/stats.py) — cardinalities,
  degree-distribution sketches, hot-key skew — the prior for a plan
  family with no history;
* **observed actuals** (``session.op_stats``, obs/telemetry.py) — when
  a (family, operator) has execution history under the CURRENT plan
  shape, the observed row mean *calibrates* the estimate (the feedback
  loop: a model estimate that keeps diverging retires its cached plan
  through the quarantine path and the re-plan prices from the refreshed
  statistics prior — the retired plan's history resets with it, because
  operator ids do not transfer across plan shapes).

Costs are NOT abstract row counts: every operator launch on the device
pads its rows up to a shape-bucket boundary (relational/shapes.py), so
an estimate of 1 000 rows that pads to 4 096 pays 4 096 — the
"Premature Dimensional Collapse ..." tensor-path observation (PAPERS.md)
applied to plan pricing.  ``device_cost`` is therefore padded rows ×
row bytes, with a compile-risk surcharge when a step would launch at a
bucket the lattice has never seen (new bucket = new XLA program = the
compile ledger's measured cliff).

Decision surfaces:

* :meth:`CostModel.chain_cost` / :meth:`chain_orientation` — bounded
  join-order enumeration for Expand chains (logical/optimizer.py
  re-roots a chain at its cheaper end);
* :func:`choose_dist_strategy` — radix vs salted vs broadcast for the
  sharded path (okapi/config.py thresholds become model *inputs*;
  skew sketches pre-plan the salting JSPIM motivates);
* :meth:`CostModel.count_pushdown_wins` — SpMV count-pushdown vs the
  binary-join cascade (relational/planner.py consults it);
* :func:`annotate_plan` — stamps ``est_rows`` on every relational
  operator so EXPLAIN renders estimated vs chosen and
  ``opstats.divergences`` measures *model* error, not drift from a
  running mean.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from caps_tpu.ir import exprs as E
from caps_tpu.ir.pattern import Direction
from caps_tpu.okapi.types import _CTNode, _CTRelationship
from caps_tpu.relational.stats import EMPTY_STATS, GraphStatistics

#: modeled bytes one row moves through an operator launch (id + a few
#: payload columns — a deliberate coarse constant: relative costs drive
#: every decision, absolute bytes only scale them)
ROW_BYTES = 24

#: equality-predicate distinct-count fallback when the sketch has none
DEFAULT_EQ_DISTINCT = 10

#: modeled cost of ONE program dispatch, in bytes-equivalent (host
#: orchestration + launch latency ≈ this much HBM traffic; ~10us at
#: v5e bandwidth).  Only priced where the compared structures differ in
#: LAUNCH COUNT — the fused count SpMV is one recorded program, the
#: join cascade pays 1 + 2 x hops operator launches.  Join-order
#: enumeration never includes it: both orientations of a chain launch
#: the same operator count, so the constant cancels.
LAUNCH_OVERHEAD_BYTES = ROW_BYTES * 32_768

#: reversal hysteresis: a chain only re-roots when the other end is at
#: least this much cheaper (plan churn on noisy estimates is worse than
#: a mildly sub-optimal order)
REORDER_MARGIN = 0.7

#: calibration needs at least this many recorded executions before the
#: observed mean overrides the model estimate
_CALIBRATE_MIN_EXECUTIONS = 2

#: modeled bytes per WCOJ frontier row: the multiway join's
#: intermediates are narrow int columns (ids + scan rows), not the
#: cascade's full-width materialized tables (relational/wcoj.py)
WCOJ_ROW_BYTES = 8


def choose_dist_strategy(probe_rows: int, build_rows: int, n_shards: int,
                         config, skew: float = 1.0
                         ) -> Tuple[str, Dict[str, Any]]:
    """Distribution strategy for one sharded join, in modeled wire
    bytes: ``broadcast`` gathers the build side to every device once
    (``build × (n-1)``), ``radix`` exchanges both sides once
    (``probe + build``), ``salted`` is radix with hot-key replication
    when the skew sketch predicts one device would drown.

    ``config.broadcast_join_threshold`` is the model's *prior* (a build
    side at or under it always broadcasts — the Spark
    autoBroadcastJoinThreshold contract callers rely on; <= 0 disables
    broadcasting entirely), and above it the modeled wire costs decide.
    ``config.join_hot_factor`` is the salting trigger: a sketch skew at
    or beyond it plans the salt instead of waiting for the runtime
    hot-key sample to react.  With ``config.use_cost_model`` off, only
    the threshold prior applies — the pre-item-3 fixed heuristic, which
    is also what the runtime dist-join call site must restore (the
    ``bench.py plan`` baseline contract)."""
    probe_rows = max(0, int(probe_rows))
    build_rows = max(0, int(build_rows))
    n = max(2, int(n_shards))
    threshold = int(getattr(config, "broadcast_join_threshold", 0) or 0)
    wire_broadcast = build_rows * (n - 1) * ROW_BYTES
    wire_radix = (probe_rows + build_rows) * ROW_BYTES
    info: Dict[str, Any] = {
        "probe_rows": probe_rows, "build_rows": build_rows,
        "shards": n, "wire_broadcast": wire_broadcast,
        "wire_radix": wire_radix, "skew": round(float(skew), 3),
    }
    if threshold > 0 and build_rows <= threshold:
        info["reason"] = "build<=threshold"
        return "broadcast", info
    if not bool(getattr(config, "use_cost_model", True)):
        # model off: the old threshold-only heuristic, nothing else
        info["reason"] = "exchange"
        return "radix", info
    if threshold > 0 and wire_broadcast * 2 < wire_radix \
            and build_rows <= threshold * 8:
        # decisively cheaper on the wire (2x margin keeps the modeled
        # call conservative where the prior said exchange) — but the
        # threshold stays a MEMORY cap: gathering the build side to
        # every device is bounded at a small multiple of it, never by
        # wire arithmetic alone
        info["reason"] = "wire_model"
        return "broadcast", info
    hot_factor = float(getattr(config, "join_hot_factor", 4.0) or 4.0)
    if skew >= hot_factor:
        info["reason"] = "skew_sketch"
        return "salted", info
    info["reason"] = "exchange"
    return "radix", info


class CostModel:
    """One query's pricing context: graph statistics + shape lattice +
    observed-actuals calibration + the decision log EXPLAIN renders."""

    def __init__(self, stats: Optional[GraphStatistics] = None,
                 lattice=None, op_stats=None, compile_ledger=None,
                 config=None, family: Optional[str] = None,
                 registry=None):
        self.stats = stats if stats is not None else EMPTY_STATS
        self.lattice = lattice
        self.op_stats = op_stats
        self.compile_ledger = compile_ledger
        self.config = config
        self.family = family
        #: decision log — ``render_decisions`` becomes plans["cost"]
        self.decisions: List[Dict[str, Any]] = []
        self._registry = registry
        #: per-op observed means for this family (lazy snapshot)
        self._history: Optional[Dict[str, Dict[str, Any]]] = None

    # -- device pricing -------------------------------------------------

    def padded_rows(self, rows: float) -> int:
        n = max(1, int(rows))
        if self.lattice is not None:
            return int(self.lattice.bucket(n))
        return n

    def device_cost(self, rows: float) -> float:
        """Padded bytes one launch moves, plus the compile-risk
        surcharge for a bucket beyond every boundary the lattice has
        seen (a brand-new bucket is a brand-new XLA program)."""
        padded = self.padded_rows(rows)
        cost = float(padded * ROW_BYTES)
        if self.lattice is not None:
            bounds = self.lattice.boundaries()
            if bounds and padded > bounds[-1]:
                cost *= 2.0  # un-compiled shape: price the cliff in
        return cost

    # -- cardinality estimation ----------------------------------------

    def scan_rows(self, labels: Iterable[str] = ()) -> float:
        return float(max(1, self.stats.node_cardinality(labels)))

    def rel_scan_rows(self, rel_types: Iterable[str] = ()) -> float:
        return float(max(1, self.stats.rel_cardinality(rel_types)))

    def degree(self, rel_types: Iterable[str],
               direction: Direction) -> float:
        out = self.stats.degree_per_node(rel_types, outgoing=True)
        inn = self.stats.degree_per_node(rel_types, outgoing=False)
        if direction == Direction.OUTGOING:
            return out
        if direction == Direction.INCOMING:
            return inn
        return out + inn  # BOTH: either orientation matches

    def predicate_selectivity(self, pred: E.Expr,
                              labels: Iterable[str] = ()) -> float:
        """Coarse selectivity of one predicate over rows of a var with
        ``labels``: equality estimates from the per-property distinct
        sketch, ranges 1/3, labels their population fraction."""
        if isinstance(pred, E.Ands):
            s = 1.0
            for p in pred.exprs:
                s *= self.predicate_selectivity(p, labels)
            return s
        if isinstance(pred, E.HasLabel):
            return self.stats.label_fraction({pred.label})
        if isinstance(pred, E.Equals):
            prop = None
            for side in (pred.lhs, pred.rhs):
                if isinstance(side, E.Property) \
                        and isinstance(side.entity, E.Var):
                    prop = side
            if prop is not None:
                distinct = self.stats.eq_distinct(labels, prop.key)
                if distinct is None:
                    distinct = DEFAULT_EQ_DISTINCT
                return 1.0 / max(1, distinct)
            return 0.1
        if isinstance(pred, (E.LessThan,)) or \
                type(pred).__name__ in ("LessThanOrEqual", "GreaterThan",
                                        "GreaterThanOrEqual"):
            return 1.0 / 3.0
        if isinstance(pred, E.Not):
            return max(0.0, 1.0 - self.predicate_selectivity(pred.expr,
                                                             labels))
        return 0.5

    def selectivity(self, preds: Sequence[E.Expr],
                    labels: Iterable[str] = ()) -> float:
        s = 1.0
        for p in preds:
            s *= self.predicate_selectivity(p, labels)
        return max(s, 1e-9)

    # -- chain costing (join-order enumeration) -------------------------

    def chain_cost(self, seed_labels: Iterable[str], seed_sel: float,
                   hops: Sequence[Tuple[Tuple[str, ...], Direction,
                                        Iterable[str], float]]
                   ) -> Tuple[float, List[float]]:
        """Price one orientation of an Expand chain.  ``hops`` is
        ``(rel_types, direction, target_labels, target_selectivity)``
        per hop; returns (total padded-device cost, per-step estimated
        rows — seed first)."""
        rows = self.scan_rows(seed_labels) * max(seed_sel, 1e-9)
        cost = self.device_cost(rows)
        ests = [rows]
        for rel_types, direction, tgt_labels, tgt_sel in hops:
            rows = (rows * self.degree(rel_types, direction)
                    * self.stats.label_fraction(tgt_labels)
                    * max(tgt_sel, 1e-9))
            # an Expand is two joins (rel scan + target node scan): the
            # launch pays the expanded frontier both times
            cost += 2.0 * self.device_cost(rows)
            ests.append(rows)
        return cost, ests

    def chain_orientation(self, fwd_cost: float,
                          rev_cost: float) -> bool:
        """True = reverse the chain (re-root at the far end)."""
        return rev_cost < fwd_cost * REORDER_MARGIN

    # -- physical choices ----------------------------------------------

    def count_pushdown_wins(self, seed_labels: Iterable[str],
                            seed_sel: float,
                            hops: Sequence[Tuple[Tuple[str, ...],
                                                 Direction,
                                                 Iterable[str],
                                                 float]]) -> bool:
        """SpMV count-pushdown vs the binary-join cascade: the pushdown
        touches EVERY edge of each hop's type once (dense-vector SpMV
        over the adjacency) but is ONE fused program; the cascade
        touches only the (padded) expanded frontier but pays a launch
        per operator.  A highly selective seed on a huge graph can make
        the cascade cheaper — exactly the physical choice ROADMAP
        item 3 asks the model, not a heuristic, to make."""
        cascade_cost, _ests = self.chain_cost(seed_labels, seed_sel, hops)
        cascade_cost += (1 + 2 * len(hops)) * LAUNCH_OVERHEAD_BYTES
        spmv_cost = LAUNCH_OVERHEAD_BYTES \
            + self.device_cost(self.stats.total_nodes or 1)
        for rel_types, _d, _tl, _ts in hops:
            spmv_cost += self.device_cost(self.rel_scan_rows(rel_types))
        # the fused program has no intermediate materialization and no
        # per-op host orchestration; the cascade must be decisively
        # cheaper in modeled bytes (4x) before the model routes around
        # the SpMV
        decision = spmv_cost <= cascade_cost * 4.0
        self.note("count_strategy",
                  chosen="fused-spmv" if decision else "cascade",
                  spmv_cost=round(spmv_cost, 1),
                  cascade_cost=round(cascade_cost, 1))
        return decision

    def algo_pushdown_wins(self, procedure: str,
                           est_iterations: int = 1) -> bool:
        """Device fixed-shape fixpoint vs the host NumPy kernel for one
        ``CALL algo.*`` (caps_tpu/algo/): the device pays one launch
        plus per-iteration padded SpMV traffic over nodes + edges; the
        host streams the same arrays through sequential NumPy at a
        modeled per-byte penalty (no vector lanes, no overlap).  Tiny
        graphs — where the pad-to-bucket waste dwarfs the work — stay on
        the host; anything dense amortizes the launch in one iteration."""
        nodes = float(max(1, self.stats.total_nodes))
        edges = float(max(1, self.stats.total_rels))
        iters = max(1, int(est_iterations))
        device = LAUNCH_OVERHEAD_BYTES + iters * (
            self.device_cost(edges) + self.device_cost(nodes))
        host = iters * (edges + nodes) * ROW_BYTES * 8.0
        decision = device <= host
        self.note("algo_strategy", procedure=procedure,
                  chosen="device-fixpoint" if decision else "host",
                  device_cost=round(device, 1), host_cost=round(host, 1),
                  est_iterations=iters)
        return decision

    def closure_selectivity(self, rel_types: Iterable[str]) -> float:
        """Expected multiplicity of edges of these types between two
        SPECIFIC bound nodes — edge cardinality over the squared node
        population.  Deliberately DIRECTION-FREE: a pair probe hits the
        stored orientation whichever way the pattern arrow was written,
        and the per-direction degree sketches (edge count over distinct
        endpoints) overestimate pair existence badly on hub-skewed
        graphs — exactly where the WCOJ win is largest.  This is the
        semi-filter selectivity a closing edge applies the moment its
        endpoints bind (the early filter the cascade defers)."""
        n = max(1, self.stats.total_nodes)
        return min(1.0, max(self.rel_scan_rows(rel_types), 1.0) / (n * n))

    def wcoj_vs_cascade(self, seed_labels: Iterable[str], seed_sel: float,
                        extends: Sequence[Tuple[Tuple[str, ...], Direction,
                                                Iterable[str], float,
                                                Sequence[Tuple[str, ...]]]],
                        closes: Sequence[Tuple[str, ...]]
                        ) -> Tuple[bool, float, Dict[str, Any]]:
        """The WCOJ-vs-binary-cascade decision surface (ROADMAP item 4),
        priced from the ingest-time degree/skew sketches.

        ``extends`` is one entry per bound vertex beyond the seed:
        ``(anchor_rel_types, anchor_direction, target_labels,
        target_selectivity, checks)`` where ``checks`` lists the
        rel-type tuples of the closing edges that semi-filter that
        vertex's candidates at bind time; ``closes`` the rel-type
        tuples of the pair-multiplicity closings.  The cascade pays the
        full OPEN chain (every frontier materialized at ``ROW_BYTES``
        width, closing joins applied only at the top); the multiway join
        pays the same expansions at ``WCOJ_ROW_BYTES`` narrow width but
        its frontiers shrink by ``closure_selectivity`` the moment a
        closing edge's endpoints bind — on dense cyclic patterns the
        intersection cost tracks the min-degree frontier while the
        cascade's intermediates blow up super-linearly.

        Returns ``(use_wcoj, estimated_output_rows, info)`` and logs the
        decision for EXPLAIN (the ``wcoj_strategy`` line next to the
        existing ``dist`` stamps)."""
        hops = [(a_types, a_dir, t_labels, t_sel)
                for a_types, a_dir, t_labels, t_sel, _checks in extends]
        cascade_cost, ests = self.chain_cost(seed_labels, seed_sel, hops)
        open_rows = ests[-1] if ests else 1.0
        for rel_types in closes:
            # one into-join (probe + pair filter) over the still-open
            # frontier, then the closure selectivity finally applies
            cascade_cost += 2.0 * self.device_cost(open_rows)
            open_rows = max(1.0, open_rows
                            * self.closure_selectivity(rel_types))
        narrow = WCOJ_ROW_BYTES / float(ROW_BYTES)
        rows = self.scan_rows(seed_labels) * max(seed_sel, 1e-9)
        wcoj_cost = narrow * self.device_cost(rows)
        for a_types, a_dir, t_labels, t_sel, checks in extends:
            transient = rows * max(self.degree(a_types, a_dir), 1e-9)
            wcoj_cost += narrow * self.device_cost(transient)
            rows = (transient * self.stats.label_fraction(t_labels)
                    * max(t_sel, 1e-9))
            for c_types in checks:
                rows *= self.closure_selectivity(c_types)
            rows = max(rows, 1.0)
            wcoj_cost += narrow * self.device_cost(rows)
        for _rel_types in closes:
            wcoj_cost += narrow * self.device_cost(rows)
        est_rows = max(1.0, rows)
        wcoj_cost += self.device_cost(est_rows)  # the one full-width gather
        decision = wcoj_cost <= cascade_cost
        info = {"wcoj_cost": round(wcoj_cost, 1),
                "cascade_cost": round(cascade_cost, 1),
                "est_rows": int(round(est_rows))}
        self.note("wcoj_strategy",
                  chosen="wcoj" if decision else "cascade", **info)
        return decision, est_rows, info

    def dist_strategy(self, probe_rows: float, build_rows: float,
                      n_shards: int,
                      rel_types: Iterable[str] = ()
                      ) -> Tuple[str, Dict[str, Any]]:
        """Planned distribution strategy for one sharded join, with the
        skew SKETCH (not a runtime sample) as the salting signal."""
        skew = self.stats.skew(rel_types) if rel_types else 1.0
        return choose_dist_strategy(probe_rows, build_rows, n_shards,
                                    self.config, skew=skew)

    # -- calibration (observed actuals beat the prior) ------------------

    def _family_history(self) -> Dict[str, Dict[str, Any]]:
        if self._history is None:
            hist: Dict[str, Dict[str, Any]] = {}
            if self.op_stats is not None and self.family is not None:
                try:
                    hist = self.op_stats.stats(self.family)
                except Exception:  # pragma: no cover — advisory only
                    hist = {}
            self._history = hist
        return self._history

    def calibrated_rows(self, op_id: int, op_name: str,
                        model_rows: float) -> Tuple[float, str]:
        """(estimate, source): the observed per-op row mean when this
        (family, operator) has enough history, else the model prior."""
        st = self._family_history().get(f"{op_id}:{op_name}")
        if st is not None and \
                st.get("executions", 0) >= _CALIBRATE_MIN_EXECUTIONS:
            return float(st.get("rows_mean") or 0.0), "observed"
        return model_rows, "model"

    # -- decision log ---------------------------------------------------

    def note(self, kind: str, **fields) -> None:
        self.decisions.append({"kind": kind, **fields})

    def render_decisions(self) -> str:
        """The plans["cost"] text EXPLAIN carries: one line per model
        decision (estimated alternatives and the chosen one)."""
        lines = []
        for d in self.decisions:
            extra = ", ".join(f"{k}={v}" for k, v in d.items()
                              if k != "kind")
            lines.append(f"{d['kind']}: {extra}")
        return "\n".join(lines)


# -- plan annotation ---------------------------------------------------------


def _scan_est(model: CostModel, op) -> float:
    m = op.entity_type.material
    if isinstance(m, _CTNode):
        return model.scan_rows(m.labels)
    if isinstance(m, _CTRelationship):
        return model.rel_scan_rows(m.rel_types)
    return 1.0


def _join_est(model: CostModel, op, l_est: float, r_est: float) -> float:
    """Estimate a JoinOp's output: the Expand shapes the planner emits
    (probe × rel scan on an endpoint, then × target node scan) price as
    degree expansion / label-fraction selection; anything else as a
    conservative max."""
    from caps_tpu.relational import ops as R
    rhs = op.children[1]
    if isinstance(rhs, R.ScanOp):
        m = rhs.entity_type.material
        if isinstance(m, _CTRelationship):
            near = op.pairs[0][1] if op.pairs else None
            direction = Direction.OUTGOING \
                if isinstance(near, E.StartNode) else Direction.INCOMING
            est = l_est * model.degree(m.rel_types, direction)
            if len(op.pairs) > 1:  # into-join: both endpoints bound
                est /= max(1, model.stats.total_nodes)
            return est
        if isinstance(m, _CTNode):
            return l_est * model.stats.label_fraction(m.labels)
    return max(l_est, r_est)


def annotate_plan(root, model: CostModel) -> Dict[str, Any]:
    """Stamp ``est_rows`` (and, on sharded joins, ``dist_strategy``)
    onto every relational operator, bottom-up.  The estimates ride into
    each execution's op metrics (relational/ops.py), so the observed-
    statistics store measures *model* error and EXPLAIN renders
    estimated-vs-chosen with zero extra plumbing.  Returns a summary
    for the result metrics."""
    from caps_tpu.algo.op import AlgoProcedureOp
    from caps_tpu.relational import ops as R
    from caps_tpu.relational.count_pattern import CountPatternOp
    from caps_tpu.relational.var_expand import VarExpandOp
    from caps_tpu.relational.wcoj import MultiwayJoinOp

    config = model.config
    n_shards = 0
    if config is not None and getattr(config, "mesh_shape", ()):
        n_shards = 1
        for d in config.mesh_shape:
            n_shards *= int(d)

    seen: Dict[int, float] = {}
    order: List[Any] = []
    stack = [root]
    while stack:  # post-order without recursion (plans can be deep)
        op = stack.pop()
        if id(op) in seen:
            continue
        seen[id(op)] = -1.0
        order.append(op)
        stack.extend(op.children)
    history = model._family_history()
    if history:
        live_keys = {f"{op.op_id}:{type(op).__name__.removesuffix('Op')}"
                     for op in order}
        # a SUBSET of the live ids is the same plan shape with lazily
        # skipped children (a count-pushdown's fallback cascade never
        # executes, so only the CountPattern op ever records) — history
        # is stale only when it names ids the live plan does not have
        if not set(history) <= live_keys:
            # the recorded history describes a DIFFERENT plan shape (a
            # re-plan re-rooted the chain or changed a physical
            # strategy): operator ids do not transfer across shapes, so
            # calibrating against it would alias row means onto
            # unrelated operators.  Drop it — locally and in the store,
            # where continued recording under stale ids would blend two
            # plans' row streams — and let history restart under the
            # live shape.
            model._history = {}
            if model.op_stats is not None and model.family is not None:
                try:
                    model.op_stats.reset_family(model.family)
                except Exception:  # pragma: no cover — advisory only
                    pass
    annotated = 0
    for op in reversed(order):
        kids = [seen.get(id(c), 1.0) for c in op.children]
        l_est = kids[0] if kids else 1.0
        if isinstance(op, R.StartOp):
            est = 1.0
        elif isinstance(op, R.ScanOp):
            est = _scan_est(model, op)
        elif isinstance(op, CountPatternOp):
            est = 1.0
        elif isinstance(op, MultiwayJoinOp):
            # priced at plan time by wcoj_vs_cascade; the cascade child
            # never executes on the healthy path, so its estimates do
            # not flow up
            est = max(1.0, float(op.planned_rows))
        elif isinstance(op, AlgoProcedureOp):
            # one yielded row per snapshot node (BFS/SSSP emit fewer —
            # reachable only — but the full population bounds it)
            est = max(1.0, float(model.stats.total_nodes))
        elif isinstance(op, VarExpandOp):
            est, frontier = 0.0, l_est
            for length in range(1, op.upper + 1):
                frontier *= model.degree(op.rel_types, op.direction)
                if length >= op.lower:
                    est += frontier * model.stats.label_fraction(
                        op.target_labels)
            est = max(est, 1.0)
        elif isinstance(op, R.JoinOp):
            est = _join_est(model, op, l_est, kids[1] if len(kids) > 1
                            else 1.0)
            if n_shards > 1 and config is not None \
                    and getattr(config, "use_dist_join", False):
                rhs = op.children[1]
                rel_types: Tuple[str, ...] = ()
                if isinstance(rhs, R.ScanOp):
                    m = rhs.entity_type.material
                    if isinstance(m, _CTRelationship):
                        rel_types = tuple(m.rel_types)
                strategy, info = model.dist_strategy(
                    l_est, kids[1] if len(kids) > 1 else 1.0,
                    n_shards, rel_types)
                op.dist_strategy = strategy
                model.note("dist", op=f"{op.op_id}:Join",
                           chosen=strategy, **info)
        elif isinstance(op, R.FilterOp):
            labels: Iterable[str] = ()
            vs = {v.name for v in E.vars_in(op.predicate)}
            if len(vs) == 1:
                # resolve the predicate var's labels from the Scan that
                # binds it, so equality selectivity reads the
                # per-property distinct sketch instead of the fallback
                var = next(iter(vs))
                walk = [op]
                while walk:
                    node = walk.pop()
                    if isinstance(node, R.ScanOp) and node.var == var:
                        m = node.entity_type.material
                        if isinstance(m, _CTNode):
                            labels = tuple(m.labels)
                        break
                    walk.extend(node.children)
            est = l_est * model.selectivity([op.predicate], labels)
        elif isinstance(op, R.CrossOp):
            est = l_est * (kids[1] if len(kids) > 1 else 1.0)
        elif isinstance(op, R.UnionAllOp):
            est = sum(kids)
        elif isinstance(op, (R.OptionalJoinOp, R.ExistsJoinOp)):
            est = l_est
        elif isinstance(op, R.AggregateOp):
            est = 1.0 if not op.group else max(1.0, l_est ** 0.5)
        elif isinstance(op, R.DistinctOp):
            est = max(1.0, l_est * 0.9)
        elif isinstance(op, R.UnwindOp):
            est = l_est * 4.0
        else:  # Project/Select/OrderBy/Skip/Limit/RowIndex/...: carry
            est = l_est
        est, source = model.calibrated_rows(
            op.op_id, type(op).__name__.removesuffix("Op"), est)
        op.est_rows = max(0, int(round(est)))
        op.est_source = source
        seen[id(op)] = max(est, 0.0)
        annotated += 1
    if model._registry is not None:
        model._registry.counter("cost.annotated_ops").inc(annotated)
    return {
        "root_est_rows": int(round(seen.get(id(root), 0.0))),
        "annotated_ops": annotated,
        "decisions": list(model.decisions),
    }

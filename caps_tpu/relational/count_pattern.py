"""Aggregate-pushdown lowering of count-only pattern chains to SpMV.

The optimizer rule the round-1 verdict asked for: a query like

    MATCH (a:Person)-[:KNOWS]->(b)-[:KNOWS]->(c)
    WHERE a.name = $seed RETURN count(*)

needs no row materialization at all — per-hop partial-path counts
propagate as a dense node vector, and each Expand hop is one
sparse-matrix/vector product against the HBM-resident adjacency:

    x0[v] = [v matches the seed scan+filters]
    x1[v] = Σ_{edges (u,v)} x0[u]          (segment-sum; psum on a mesh)
    answer = Σ_v x2[v]

(ref analog: the planner owns such rewrites — okapi-logical
LogicalOptimizer / planBoundedVarLengthExpand, reconstructed, mount
empty; SURVEY.md §3.2.  The tensor formulation follows the
dimensional-collapse / TrieJax line in PAPERS.md.)

Correctness scope: openCypher matches with *relationship isomorphism* —
the IR builder emits ``Not(id(r_i) = id(r_j))`` filters between hops —
while SpMV counts walks.  For chains of ≤ 3 hops the difference is a
closed-form correction: 2-hop reuse is r2 == r1, detectable per edge;
3-hop reuse is an inclusion–exclusion over the pairs (see _build_corr3).
The lowering is *exact* there and the matcher refuses longer chains,
leaving them on the join path.

On a device mesh the chain runs sharded: uniform unmasked chains ride
the ppermute ring schedule (parallel/ring.py); general chains use
edge-sharded segment-sums with XLA-inserted collectives.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional as Opt, Sequence, Tuple

import numpy as np

from caps_tpu.ir import exprs as E
from caps_tpu.ir.pattern import Direction
from caps_tpu.logical import ops as L
from caps_tpu.obs.compile import charged as _compile_charged
from caps_tpu.okapi.types import CTInteger
from caps_tpu.relational.header import RecordHeader
from caps_tpu.relational.ops import RelationalOperator
from caps_tpu.relational.var_expand import synth_header

# Node-id domains larger than this refuse the dense-vector form.
_MAX_DOMAIN = 1 << 26

# Sentinel: the length-2 correction has no device path (vs None = the
# correction is provably zero).
_UNSUITABLE_CORR = object()

# Negative fused-closure cache entry: this (graph, plan, params) shape is
# known unfusable — don't re-probe on every execution.
_NO_FUSE = object()

# Per-graph static structures kept at most for this many distinct graphs.
_MAX_STATIC_GRAPHS = 16


@dataclasses.dataclass(frozen=True)
class NodeSpec:
    var: str
    labels: frozenset
    preds: Tuple[E.Expr, ...]

    @property
    def trivial(self) -> bool:
        return not self.labels and not self.preds


@dataclasses.dataclass(frozen=True)
class HopSpec:
    rel: str
    rel_types: Tuple[str, ...]
    direction: Direction
    target: NodeSpec


class _Unsuitable(Exception):
    """Runtime bail-out: compute via the fallback join plan instead."""


def _dense_bool_vec(okps, ends, n: int):
    """Node indicator over the id domain from an id-sorted membership
    mask: int32 cumsum + one boundary gather — the scatter-free
    segment-sum (shared by the fused chain closure and the cycle op's
    per-binding mask args)."""
    import jax.numpy as jnp
    if okps.shape[0] == 0:
        return jnp.zeros((n,), bool)
    c = jnp.cumsum(okps.astype(jnp.int32))
    cum = jnp.where(ends >= 0, c[jnp.clip(ends, 0, None)], 0)
    prev = jnp.concatenate([jnp.zeros(1, jnp.int32), cum[:-1]])
    return (cum - prev) > 0


def _walk_expr(e: E.Expr):
    """Every sub-expression of ``e`` (itself included)."""
    stack = [e]
    while stack:
        x = stack.pop()
        yield x
        stack.extend(c for c in x.children if isinstance(c, E.Expr))


def _split(pred: E.Expr) -> Tuple[E.Expr, ...]:
    if isinstance(pred, E.Ands):
        out: List[E.Expr] = []
        for p in pred.exprs:
            out.extend(_split(p))
        return tuple(out)
    return (pred,)


def _corr_intersection(h1: "HopSpec", h2: "HopSpec"):
    """Edge scan an r2==r1 reuse can live in: the intersection of both
    hops' type constraints (an untyped hop matches every type).  Returns
    the type set, or None when provably disjoint (zero correction)."""
    ta, tb = set(h1.rel_types), set(h2.rel_types)
    if not ta:
        return tb
    if not tb:
        return ta
    inter = ta & tb
    return inter or None


def _corr_roles(h1: "HopSpec", h2: "HopSpec", src, tgt):
    """Per-edge index roles for the length-2 correction, resolved by hop
    directions: (a, b) = hop-1 (from, to), (near2, far2) = hop-2."""
    a, b = (src, tgt) if h1.direction == Direction.OUTGOING else (tgt, src)
    near2, far2 = (src, tgt) if h2.direction == Direction.OUTGOING \
        else (tgt, src)
    return a, b, near2, far2


def _as_uniqueness_pair(pred: E.Expr) -> Opt[Tuple[str, str]]:
    if (isinstance(pred, E.Not) and isinstance(pred.expr, E.Equals)
            and isinstance(pred.expr.lhs, E.Id)
            and isinstance(pred.expr.rhs, E.Id)
            and isinstance(pred.expr.lhs.entity, E.Var)
            and isinstance(pred.expr.rhs.entity, E.Var)):
        return (pred.expr.lhs.entity.name, pred.expr.rhs.entity.name)
    return None


def try_plan_count_pushdown(planner, op: "L.Aggregate", fallback):
    """Match Aggregate(count(*)) over a 1-3 hop Expand chain (or a
    var-length expand with upper <= 3) rooted at one NodeScan, and return
    a CountPatternOp, or None if the shape doesn't qualify."""
    session = planner.context.session
    config = getattr(session, "config", None)
    if not getattr(session, "supports_count_pushdown", False):
        return None
    if config is None or not config.use_count_pushdown:
        return None
    if op.group or len(op.aggregations) != 1:
        return None
    out_name, agg = op.aggregations[0]
    if not isinstance(agg, E.CountStar):
        return None

    hops_rev: List[Tuple[str, Tuple[str, ...], Direction, str, frozenset,
                         str]] = []
    preds_by_var: Dict[str, List[E.Expr]] = {}
    uniq_pairs: List[Tuple[str, str]] = []
    varlen: Opt[L.BoundedVarLengthExpand] = None
    closing: Opt[L.Expand] = None
    pending: List[E.Expr] = []

    cur = op.parent
    seed: Opt[Tuple[str, frozenset]] = None
    while seed is None:
        if isinstance(cur, L.Filter):
            pending.extend(_split(cur.predicate))
            cur = cur.parent
        elif isinstance(cur, L.Expand):
            if cur.direction == Direction.BOTH or varlen:
                return None
            if cur.into:
                # at most one cycle-closing edge (both endpoints bound)
                if closing is not None:
                    return None
                closing = cur
            else:
                hops_rev.append((cur.rel, cur.rel_types, cur.direction,
                                 cur.target, cur.target_labels, cur.source))
            cur = cur.parent
        elif isinstance(cur, L.BoundedVarLengthExpand):
            if (cur.into or cur.direction == Direction.BOTH or hops_rev
                    or varlen or closing or cur.upper is None or cur.upper > 3):
                return None
            varlen = cur
            cur = cur.parent
        elif isinstance(cur, L.NodeScan):
            if not isinstance(cur.parent, L.Start) or cur.parent.qgn is not None:
                return None
            seed = (cur.var, cur.labels)
        else:
            return None

    # The walk collected Expands in plan order; the SpMV/cycle lowerings
    # assume a CHAIN — every hop must expand from the previous hop's
    # target (first hop: from the seed).  A star pattern like
    # (a)->(b), (a)->(c) also type-checks as 2 hops over 3 node vars but
    # is NOT a chain; counting it as one is silently wrong.
    if hops_rev:
        expected_src = seed[0]
        for r, t, d, tv, tl, src in reversed(hops_rev):
            if src != expected_src:
                return None
            expected_src = tv

    if closing is not None and varlen is None:
        return _plan_cycle(planner, op, fallback, seed, hops_rev, closing,
                           pending, out_name)
    if closing is not None:
        return None

    if varlen is not None:
        node_vars = {seed[0], varlen.target}
        rel_vars = {varlen.rel}
        max_len = varlen.upper
        lengths = list(range(varlen.lower, varlen.upper + 1))
    else:
        if not 1 <= len(hops_rev) <= 3:
            return None
        node_vars = {seed[0]} | {h[3] for h in hops_rev}
        rel_vars = {h[0] for h in hops_rev}
        if len(node_vars) != 1 + len(hops_rev) or len(rel_vars) != len(hops_rev):
            return None  # repeated vars: not a simple chain
        max_len = len(hops_rev)
        lengths = [max_len]

    for pred in pending:
        pair = _as_uniqueness_pair(pred)
        if pair is not None:
            if set(pair) <= rel_vars:
                uniq_pairs.append(pair)
                continue
            return None
        vs = {v.name for v in E.vars_in(pred)}
        if len(vs) == 1 and (v := next(iter(vs))) in node_vars:
            preds_by_var.setdefault(v, []).append(pred)
            continue
        return None

    def node_spec(var: str, labels) -> NodeSpec:
        return NodeSpec(var, frozenset(labels),
                        tuple(preds_by_var.get(var, ())))

    seed_spec = node_spec(*seed)
    if varlen is not None:
        # VarExpand joins the target node scan only where a path *ends*;
        # intermediate frontier nodes need no node row (engine semantics —
        # see VarExpandOp).  It always enforces edge isomorphism between
        # every pair of hop positions.
        hop = HopSpec(varlen.rel, tuple(varlen.rel_types), varlen.direction,
                      node_spec(varlen.target, varlen.target_labels))
        hops = [hop] * max_len
        uniq_pos = frozenset((i, j) for i in range(1, max_len + 1)
                             for j in range(i + 1, max_len + 1))
    else:
        # Fixed Expand joins the target node scan at *every* hop, so every
        # hop output is masked by node existence (+labels/preds).  The
        # uniqueness filters the IR emitted map to hop-position pairs.
        hops = [HopSpec(r, tuple(t), d, node_spec(tv, tl))
                for r, t, d, tv, tl, _src in reversed(hops_rev)]
        if uniq_pairs and max_len < 2:
            return None
        pos_of = {h.rel: i + 1 for i, h in enumerate(hops)}
        uniq_pos = frozenset(
            (min(pos_of[x], pos_of[y]), max(pos_of[x], pos_of[y]))
            for x, y in uniq_pairs)

    return CountPatternOp(planner.context, fallback, planner.current_graph,
                          out_name, seed_spec, hops, lengths, uniq_pos,
                          is_varlen=varlen is not None)


def _plan_cycle(planner, op, fallback, seed, hops_rev, closing, pending,
                out_name):
    """Match the cyclic triangle shape: a 2-hop chain a->b->c plus one
    closing edge between a and c (any per-edge orientation), lowered to
    batched 2-path enumeration with a sorted closing-edge key probe
    (benchmark config 4; ref analog: Spark plans this as a 5-way shuffle
    join cascade — reconstructed, mount empty; SURVEY.md §3.2)."""
    if len(hops_rev) != 2:
        return None
    a_var = seed[0]
    hops_fwd = list(reversed(hops_rev))
    b_var, c_var = hops_fwd[0][3], hops_fwd[1][3]
    node_vars = {a_var, b_var, c_var}
    rel_vars = {h[0] for h in hops_fwd} | {closing.rel}
    if len(node_vars) != 3 or len(rel_vars) != 3:
        return None
    if {closing.source, closing.target} != {a_var, c_var}:
        return None
    if closing.target_labels:
        # labels restated on the closing mention must already be implied
        # by the var's own spec (the cycle build masks a/c once)
        existing = seed[1] if closing.target == a_var else hops_fwd[1][4]
        if not frozenset(closing.target_labels) <= frozenset(existing):
            return None

    preds_by_var: Dict[str, List[E.Expr]] = {}
    for pred in pending:
        pair = _as_uniqueness_pair(pred)
        if pair is not None:
            if set(pair) <= rel_vars:
                # relationship-isomorphism filters between the three rels:
                # enforced structurally by CountCycleOp (it refuses graphs
                # with self-loops, the only way two cycle rels can coincide)
                continue
            return None
        vs = {v.name for v in E.vars_in(pred)}
        if len(vs) == 1 and (v := next(iter(vs))) in node_vars:
            preds_by_var.setdefault(v, []).append(pred)
            continue
        return None

    def spec(var: str, labels) -> NodeSpec:
        return NodeSpec(var, frozenset(labels),
                        tuple(preds_by_var.get(var, ())))

    seed_spec = spec(a_var, seed[1])
    hops = [HopSpec(r, tuple(t), d, spec(tv, tl))
            for r, t, d, tv, tl, _src in hops_fwd]
    # orient the closing edge as a->c regardless of how it was written
    closes_forward = (closing.source == a_var) \
        == (closing.direction == Direction.OUTGOING)
    close_hop = HopSpec(closing.rel, tuple(closing.rel_types),
                        Direction.OUTGOING if closes_forward
                        else Direction.INCOMING,
                        spec(c_var, closing.target_labels))
    return CountCycleOp(planner.context, fallback, planner.current_graph,
                        out_name, seed_spec, hops, close_hop)


class CountPatternOp(RelationalOperator):
    """Count pattern matches by dense-vector propagation (see module
    docstring).  Falls back to the embedded join plan when the node-id
    domain is unsuitable."""

    def __init__(self, context, fallback: RelationalOperator, graph,
                 out_name: str, seed: NodeSpec, hops: Sequence[HopSpec],
                 lengths: Sequence[int], uniq_pos: frozenset,
                 is_varlen: bool = False):
        super().__init__(context, [fallback])
        self.graph = graph
        self.out_name = out_name
        self.seed = seed
        self.hops = list(hops)
        self.lengths = list(lengths)
        # hop-position pairs (i, j), i<j, whose relationships must differ
        # (Cypher relationship isomorphism)
        self.uniq_pos = uniq_pos
        self.is_varlen = is_varlen
        self.strategy = "unplanned"

    @property
    def correct_len2(self) -> bool:
        return (1, 2) in self.uniq_pos and 2 in self.lengths

    # -- array extraction --------------------------------------------------

    def _node_ids(self, spec: NodeSpec):
        """(ids, ok) arrays for the nodes matching a NodeSpec."""
        header, t = self.graph.scan_node(spec.var, spec.labels)
        params = self.context.parameters
        for pred in spec.preds:
            from caps_tpu.relational.ops import resolve_expr
            t = t.filter(resolve_expr(pred, header), header, params)
        return self._column_arrays(t, header.column(E.Var(spec.var)))

    def _rel_arrays(self, types: Tuple[str, ...]):
        tmp = "__cnt_rel"
        header, t = self.graph.scan_rel(tmp, types)
        src = self._column_arrays(t, header.column(E.StartNode(E.Var(tmp))))
        tgt = self._column_arrays(t, header.column(E.EndNode(E.Var(tmp))))
        return src, tgt

    def _column_arrays(self, table, col: str):
        """(values, ok) as device arrays, from either a device table or a
        host-fallback one."""
        import jax.numpy as jnp
        from caps_tpu.backends.tpu.table import DeviceTable
        if isinstance(table, DeviceTable) and not table.is_local:
            c = table._cols[col]
            if c.kind not in ("id", "int"):
                raise _Unsuitable(f"non-integer id column {col}")
            return c.data, (c.valid & table.row_ok)
        vals = table.column_values(col)
        arr = np.array([v if v is not None else -1 for v in vals],
                       dtype=np.int64)
        ok = np.array([v is not None for v in vals], dtype=bool)
        return jnp.asarray(arr), jnp.asarray(ok)

    # -- execution ---------------------------------------------------------

    def _compute(self):
        try:
            out = self._compute_pushdown()
        except _Unsuitable:
            self.strategy = "fallback-join"
            out = self.children[0].result
        self._metric_extra = {"strategy": self.strategy}
        if getattr(self, "_fused_bytes", 0):
            self._metric_extra["bytes_in"] = self._fused_bytes
        return out

    # -- fused single-program execution -------------------------------------
    #
    # The whole seed→hops→masks→correction chain compiles to ONE jitted,
    # scatter-free program (the engine's whole-stage-codegen for the count
    # path — ref analog: Spark Tungsten codegen, SparkTable.scala†,
    # SURVEY.md §3.1 invariant "one compiled program per plan").  All
    # data-dependent structure is hoisted out of the steady state:
    #
    #   * per GRAPH (immutable): edge lists sorted by destination, node-scan
    #     ids sorted, and the per-node segment boundary gathers (`ends`)
    #     that turn segment-sum into cumsum + two gathers — no XLA
    #     scatter-add (which serializes on TPU) anywhere;
    #   * per (graph, plan shape, params): node-predicate masks are
    #     evaluated once (they are pure functions of graph data + params)
    #     and the whole chain is traced into one jax.jit closure;
    #   * per ITERATION: one program dispatch, zero host syncs.

    def _value_keyed(self) -> bool:
        """True when the fused closure must key on parameter VALUES.
        No count-family op overrides this anymore (PR 12 converted the
        main chain, PR 14 the cycle op — predicate masks rebuild per
        binding as cheap eager args, the jitted programs never
        recompile, so unseen bindings charge no ``count_fused``
        compiles).  The only remaining value-keyed path is the
        ``_shape_key``-failure fallback in ``_fused_total`` (parameter
        values the shape signature cannot describe)."""
        return False

    def _shape_key(self, backend, params):
        """The value-independent closure-cache key component."""
        from caps_tpu.relational.shapes import param_shape_signature
        session = getattr(self.context, "session", None)
        lattice = getattr(session, "shape_lattice", None)
        try:
            return param_shape_signature(params, lattice)
        except Exception:
            return None

    def _fused_total(self):
        backend = getattr(self.context.factory, "backend", None)
        if backend is None or backend.mesh is not None:
            return None
        if not backend.config.use_fused_count:
            return None
        from caps_tpu.backends.tpu.fused import _graph_key, _params_key
        gk = _graph_key(self.graph)
        params = self.context.parameters
        pk = _params_key(params)
        if gk is None or pk is None:
            return None
        value_keyed = self._value_keyed()
        key_sig = pk if value_keyed else self._shape_key(backend, params)
        if key_sig is None:
            value_keyed, key_sig = True, pk
        # pool length only keys VALUE-keyed entries: a shape-keyed
        # closure's jitted program carries no pooled string data (the
        # predicate masks rebuild per binding against the live pool),
        # and keying on it would turn every new interned string value
        # back into a compile-charging miss
        key = (gk, key_sig, len(backend.pool) if value_keyed else -1,
               self._plan_sig())
        entry = backend.fused_count_fns.get(key)
        if entry is _NO_FUSE:
            return None
        fresh = entry is None
        if fresh:
            # Build outside any record/replay scope: the one-time scan and
            # sort syncs must not leak into a fused-executor recording (a
            # replay would never repeat them).
            saved = backend.count_mode
            backend.count_mode = None
            try:
                built = self._build_fused(backend, gk)
            finally:
                backend.count_mode = saved
            fns = backend.fused_count_fns
            while len(fns) >= max(1, backend.config.compile_cache_size):
                fns.pop(next(iter(fns)))
            # negative results are cached too: repeats of an unfusable
            # query must not pay the build probing (and its host syncs)
            # every execution
            if built is None:
                fns[key] = _NO_FUSE
                return None
            fn, args, valid, make_args = built
            entry = {"run": fn, "valid": valid, "make_args": make_args,
                     "args": args,
                     "token": pk if make_args is not None else None}
            fns[key] = entry
        else:
            fn, valid = entry["run"], entry["valid"]
            args = entry["args"]
            if entry["token"] is not None and entry["token"] != pk:
                # unseen binding, same shape: rebuild ONLY the
                # predicate-mask args (eager device ops — no XLA
                # compile, no count_fused charge; the jitted program
                # reuses its trace because the arg shapes agree)
                args = entry["make_args"](params)
                if args is None:
                    return None
                entry["args"] = args
                entry["token"] = pk
        # roofline numerator: the device arrays the fused program reads
        # per execution — the per-binding args PLUS any closure-captured
        # static arrays the closure self-reports (the cycle op's batch
        # probes re-read its resident edge/key tables every batch)
        import jax
        self._fused_bytes = sum(
            x.nbytes for x in jax.tree_util.tree_leaves(args)
            if hasattr(x, "nbytes")) + getattr(fn, "nbytes_in", 0)
        self.strategy = "fused-spmv"
        if fresh:
            # Compile ledger (obs/compile.py): a fused_count_fns miss is
            # a compile boundary — the closure build plus the FIRST
            # dispatch (where jax traces + XLA-compiles the program).
            # Cache hits (including fresh bindings in a seen shape
            # bucket) charge nothing.
            import hashlib
            sig = hashlib.sha1(
                repr(self._plan_sig()).encode()).hexdigest()[:10]
            with _compile_charged("count_fused", shape=f"g{gk}:{sig}"):
                out = fn(*args)
            return out, valid
        return fn(*args), valid

    def _plan_sig(self):
        def nsig(s: NodeSpec):
            return (tuple(sorted(s.labels)), tuple(repr(p) for p in s.preds))
        return (nsig(self.seed),
                tuple((tuple(sorted(set(h.rel_types))), h.direction,
                       nsig(h.target)) for h in self.hops),
                tuple(self.lengths), self.is_varlen,
                tuple(sorted(self.uniq_pos)))

    def _graph_static(self, backend, gk) -> dict:
        st = backend.fused_count_static.get(gk)
        if st is None:
            # Evict oldest graphs so discarded graphs' device-resident
            # sorted-edge copies don't pin memory for the process lifetime.
            # Their closures must go with them (closures capture the
            # arrays; a stale closure would also serve a reused epoch).
            while len(backend.fused_count_static) >= _MAX_STATIC_GRAPHS:
                old = next(iter(backend.fused_count_static))
                backend.fused_count_static.pop(old)
                for k in [k for k in backend.fused_count_fns if k[0] == old]:
                    backend.fused_count_fns.pop(k)
            st = {"scans": {}, "rels": {}, "edges": {}, "ids": {}}
            backend.fused_count_static[gk] = st
        return st

    def _fused_scan(self, st, labels: frozenset):
        """(header, table, static_ok, host_ids, host_ok) for a node
        scan, pure-device only; cached per graph.  The host copies (one
        read each, one-time) feed the numpy-side static builds below —
        on remote transports a handful of numpy sorts beats a dozen
        round-tripping device programs."""
        key = ("node", labels)
        if key in st["scans"]:
            return st["scans"][key]
        from caps_tpu.backends.tpu.table import DeviceTable
        header, t = self.graph.scan_node("__cnt_n", labels)
        entry = None
        if isinstance(t, DeviceTable) and not t.is_local and t.capacity:
            col = header.column(E.Var("__cnt_n"))
            host = t.host_column(col)
            if host is not None:
                c = t._cols[col]
                static_ok = c.valid & t.row_ok
                entry = (header, t, static_ok, host[0], host[1])
        st["scans"][key] = entry
        return entry

    def _fused_rel(self, st, rk: Tuple[str, ...]):
        """(src, tgt, ok) HOST numpy arrays for a relationship scan;
        cached (the edge structures built from these are device-resident,
        the raw scan itself is only needed host-side)."""
        if rk in st["rels"]:
            return st["rels"][rk]
        from caps_tpu.backends.tpu.table import DeviceTable
        header, t = self.graph.scan_rel("__cnt_r", rk)
        entry = None
        if isinstance(t, DeviceTable) and not t.is_local:
            v = E.Var("__cnt_r")
            s = t.host_column(header.column(E.StartNode(v)))
            g = t.host_column(header.column(E.EndNode(v)))
            if s is not None and g is not None:
                entry = (s[0], g[0], s[1] & g[1])
        st["rels"][rk] = entry
        return entry

    def _fused_edges(self, st, rk, direction, n: int):
        """Edges of one hop sorted by destination + per-node segment
        boundaries: (frm_sorted, ok_sorted, ends, to_clip) device arrays,
        built host-side in numpy and uploaded once."""
        import jax.numpy as jnp
        key = (rk, direction, n)
        if key in st["edges"]:
            return st["edges"][key]
        rel = self._fused_rel(st, rk)
        if rel is None:
            st["edges"][key] = None
            return None
        src, tgt, ok = rel
        frm, to = (src, tgt) if direction == Direction.OUTGOING else (tgt, src)
        to_fold = np.where(ok, to, n).astype(np.int32)
        order = np.argsort(to_fold, kind="stable")
        to_sorted = to_fold[order]
        frm_sorted = np.where(ok, frm, 0).astype(np.int32)[order]
        ok_sorted = ok[order]
        ends = (np.searchsorted(to_sorted, np.arange(n, dtype=np.int32),
                                side="right") - 1).astype(np.int32)
        # clipped destination for edgewise mask gathers on the final hop
        # (invalid edges carry the n sentinel; ok_sorted already excludes
        # them, the clip just keeps the gather in bounds)
        to_clip = np.minimum(to_sorted, n - 1)
        backend = self.context.factory.backend
        # place_rows keeps mesh configs edge-sharded (no-op single-chip)
        entry = (backend.place_rows(jnp.asarray(frm_sorted)),
                 backend.place_rows(jnp.asarray(ok_sorted)),
                 backend.place_rows(jnp.asarray(ends)),
                 backend.place_rows(jnp.asarray(to_clip)))
        st["edges"][key] = entry
        return entry

    def _fused_ids(self, st, labels: frozenset, n: int):
        """Node-scan ids sorted + segment boundaries: (order, ends) —
        order stays host-side (it permutes the predicate mask at build
        time), ends is uploaded for the fused program."""
        import jax.numpy as jnp
        key = (labels, n)
        if key in st["ids"]:
            return st["ids"][key]
        _, _, _ok, host_ids, host_ok = st["scans"][("node", labels)]
        id_fold = np.where(host_ok, host_ids, n).astype(np.int32)
        order = np.argsort(id_fold, kind="stable")
        ids_sorted = id_fold[order]
        ends = (np.searchsorted(ids_sorted, np.arange(n, dtype=np.int32),
                                side="right") - 1).astype(np.int32)
        backend = self.context.factory.backend
        entry = (order, backend.place_rows(jnp.asarray(ends)))
        st["ids"][key] = entry
        return entry

    def _fused_okpred(self, scan, spec: NodeSpec, order, params=None):
        """Predicate mask over a node scan, evaluated at closure-build
        time — or re-evaluated per unseen binding when the closure is
        shape-keyed (pure function of graph data + ``params``) —
        permuted into id order.  Returns None if a predicate has no
        device path."""
        from caps_tpu.backends.tpu.expr import (
            DeviceExprCompiler, UnsupportedOnDevice,
        )
        from caps_tpu.relational.ops import resolve_expr
        import jax.numpy as jnp
        header, t, static_ok, _hids, host_ok = scan
        backend = self.context.factory.backend
        if params is None:
            params = self.context.parameters
        if not spec.preds:
            # no device work: permute the static mask host-side, upload
            # once (a numpy arg would re-transfer on every call)
            return backend.place_rows(jnp.asarray(host_ok[order]))
        compiler = DeviceExprCompiler(t._cols, t.capacity, header,
                                      params,
                                      backend.pool, t.row_ok)

        def rename(e: E.Expr) -> E.Expr:
            # the cached scan binds "__cnt_n", not the query's var name
            if isinstance(e, E.Var) and e.name == spec.var:
                return E.Var("__cnt_n")
            return e

        okpred = static_ok
        try:
            for pred in spec.preds:
                renamed = pred.transform_up(rename)
                col = compiler.compile(resolve_expr(renamed, header))
                if col.kind != "bool":
                    return None
                okpred = okpred & col.data & col.valid
        except (UnsupportedOnDevice, KeyError):
            return None
        return backend.place_rows(okpred[order])

    def _build_fused(self, backend, gk):
        import jax
        import jax.numpy as jnp
        st = self._graph_static(backend, gk)

        seed_scan = self._fused_scan(st, self.seed.labels)
        if seed_scan is None:
            return None
        if self.is_varlen:
            mask_specs = [self.hops[0].target]
        else:
            mask_specs = [h.target for h in self.hops]
        mask_scans = [self._fused_scan(st, s.labels) for s in mask_specs]
        if any(m is None for m in mask_scans):
            return None
        relkeys = [tuple(sorted(set(h.rel_types))) for h in self.hops]
        rels = {rk: self._fused_rel(st, rk) for rk in relkeys}
        if any(r is None for r in rels.values()):
            return None

        # id domain over everything this chain touches (host-side — the
        # scan host copies were read once when cached)
        mx = -1
        for _, _, _ok, host_ids, host_ok in [seed_scan] + mask_scans:
            if host_ids.shape[0] and host_ok.any():
                mx = max(mx, int(host_ids[host_ok].max()))
        for src, tgt, ok in rels.values():
            if src.shape[0] and ok.any():
                mx = max(mx, int(src[ok].max()), int(tgt[ok].max()))
        n = mx + 1
        if n <= 0:
            n = 1
        if n > _MAX_DOMAIN:
            return None  # let the eager path raise _Unsuitable

        seed_order, seed_ends = self._fused_ids(st, self.seed.labels, n)
        # Hops often share a target spec (e.g. two unlabeled nodes): build
        # each distinct mask once and index into it, so the program carries
        # no duplicate dense-vector subgraphs.  The distinct-mask ORDER is
        # structural (labels + pred shapes), so the per-binding args
        # builder below reproduces it exactly for every parameter value.
        uniq_masks: List[tuple] = []  # (spec, scan) per distinct mask
        mask_index: List[int] = []
        uniq: Dict[tuple, int] = {}
        for spec, scan in zip(mask_specs, mask_scans):
            k = (spec.labels, tuple(repr(p) for p in spec.preds))
            if k not in uniq:
                uniq[k] = len(uniq_masks)
                uniq_masks.append((spec, scan))
            mask_index.append(uniq[k])
        mask_index = tuple(mask_index)
        hop_edges = [self._fused_edges(st, rk, h.direction, n)
                     for rk, h in zip(relkeys, self.hops)]
        if any(e is None for e in hop_edges):
            return None

        lengths = tuple(self.lengths)
        max_len = max(lengths)
        is_varlen = self.is_varlen
        cap1 = backend.bucket(1)

        corr = None
        if self.correct_len2:
            corr = self._fused_corr(st, n)
            if corr is _UNSUITABLE_CORR:
                return None
            if corr is not None:
                corr = self._compact_cond(backend, n, *corr)

        corr3, coef_t = None, 0
        if max_len == 3 and 3 in lengths and self.uniq_pos:
            built = self._build_corr3(backend, st, n)
            if built is _UNSUITABLE_CORR:
                return None
            if built is not None:
                corr3, coef_t = built

        # Dtype schedule (gathers dominate the program on TPU — random
        # gather cost scales with element width, so every gather is as
        # narrow as correctness allows): node indicators are BOOL; the
        # frontier after hop 1 is int32 (values bounded by in-degree < 2^31
        # since edges are int32-indexed); hop 2+ frontiers are int64 (path
        # counts compose multiplicatively).  The final hop never builds a
        # dense frontier at all — it reduces edgewise with a bool mask
        # gather at the destination.

        def dense_bool(okps, ends):
            """Node indicator from id-sorted membership (module-level
            :func:`_dense_bool_vec` — shared with the cycle op's
            per-binding mask args)."""
            return _dense_bool_vec(okps, ends, n)

        def hop_dense(x, frm, ok, ends, out_dtype):
            """One SpMV hop to a dense frontier of ``out_dtype``."""
            if frm.shape[0] == 0:
                return jnp.zeros((n,), out_dtype)
            gx = x[frm]
            if gx.dtype == jnp.bool_:
                contrib = (ok & gx).astype(out_dtype)
            else:
                contrib = jnp.where(ok, gx, 0).astype(out_dtype)
            c = jnp.cumsum(contrib)
            cum = jnp.where(ends >= 0, c[jnp.clip(ends, 0, None)], 0)
            prev = jnp.concatenate([jnp.zeros(1, c.dtype), cum[:-1]])
            return cum - prev

        def hop_edgewise(x, frm, ok, to_clip, emask):
            """Final hop: Σ_e x[frm]·mask[to] — no dense rebuild."""
            if frm.shape[0] == 0:
                return jnp.int64(0)
            keep = ok & emask[to_clip]
            gx = x[frm]
            if gx.dtype == jnp.bool_:
                return (keep & gx).sum(dtype=jnp.int64)
            return jnp.where(keep, gx, 0).sum(dtype=jnp.int64)

        @jax.jit
        def run(seed_okps, seed_ends, masks, hops, corr, corr3):
            x0 = dense_bool(seed_okps, seed_ends)
            uniq_vecs = [dense_bool(mo, me) for mo, me in masks]
            mask_vecs = [uniq_vecs[i] for i in mask_index]
            end_mask = mask_vecs[0] if is_varlen else mask_vecs[-1]
            total = jnp.int64(0)
            x = x0
            x1_saved = None
            for length in range(0, max_len + 1):
                if length in lengths and length < max_len:
                    xl = x.astype(jnp.int64)
                    if is_varlen:
                        xl = jnp.where(end_mask, xl, 0)
                    total = total + xl.sum()
                if length < max_len:
                    frm, ok, ends, to_clip = hops[length]
                    if length == max_len - 1 and max_len in lengths:
                        emask = end_mask if is_varlen \
                            else mask_vecs[length]
                        total = total + hop_edgewise(x, frm, ok, to_clip,
                                                     emask)
                    else:
                        dt = jnp.int32 if length == 0 else jnp.int64
                        x = hop_dense(x, frm, ok, ends, dt)
                        if not is_varlen:
                            x = jnp.where(mask_vecs[length], x, 0)
                        if length == 0:
                            x1_saved = x
            if corr is not None:
                cvalid, a, b, f = corr
                hit = cvalid & x0[a]
                if not is_varlen:
                    hit = hit & mask_vecs[0][b]
                hit = hit & (end_mask if is_varlen else mask_vecs[1])[f]
                total = total - hit.sum(dtype=jnp.int64)
            if corr3 is not None:
                # 3-hop inclusion–exclusion over the enforced uniqueness
                # pairs P: bad = ΣA_p − coef_t·T (every pairwise
                # intersection of the A_p equals the triple T).
                c12, c23, i13, c123, d3, pair2 = corr3
                m1 = None if is_varlen else mask_vecs[0]
                m2 = None if is_varlen else mask_vecs[1]
                m3 = end_mask if is_varlen else mask_vecs[2]
                sub = jnp.int64(0)
                if c12 is not None:
                    # A12: e2=e1 at positions (a,b,c); hop 3 continues
                    # freely — D3[v] = Σ_{e3 from v} m3[far3]
                    frm3, ok3, ends3, _t3 = d3
                    D3 = hop_dense(m3, frm3, ok3, ends3, jnp.int32)
                    cv, a, b, c = c12
                    keep = cv & x0[a]
                    if m1 is not None:
                        keep = keep & m1[b]
                    if m2 is not None:
                        keep = keep & m2[c]
                    sub = sub + jnp.where(keep, D3[c], 0
                                          ).sum(dtype=jnp.int64)
                if c23 is not None:
                    # A23: e3=e2 at positions (b,c,d); weight by the
                    # number of length-1 walks from the seed into b
                    cv, b, c, d = c23
                    keep = cv & m3[d]
                    if m2 is not None:
                        keep = keep & m2[c]
                    sub = sub + jnp.where(keep, x1_saved[b], 0
                                          ).sum(dtype=jnp.int64)
                if i13 is not None:
                    # A13: e3=e1 with e2 free — count hop-2 edges between
                    # far1(e) and near3(e) via the sorted pair-key table
                    cv, a, b, c, d = i13
                    q = b.astype(jnp.int64) * n + c.astype(jnp.int64)
                    lo = jnp.searchsorted(pair2, q, side="left")
                    hi = jnp.searchsorted(pair2, q, side="right")
                    cnt2 = (hi - lo).astype(jnp.int32)
                    keep = cv & x0[a] & m3[d]
                    if m1 is not None:
                        keep = keep & m1[b]
                    if m2 is not None:
                        keep = keep & m2[c]
                    sub = sub + jnp.where(keep, cnt2, 0
                                          ).sum(dtype=jnp.int64)
                if c123 is not None and coef_t:
                    cv, a, b, c, d = c123
                    keep = cv & x0[a] & m3[d]
                    if m1 is not None:
                        keep = keep & m1[b]
                    if m2 is not None:
                        keep = keep & m2[c]
                    sub = sub - coef_t * keep.sum(dtype=jnp.int64)
                total = total - sub
            return jnp.zeros((cap1,), jnp.int64).at[0].set(total)

        def build_args(params):
            """The parameter-dependent half of the closure: predicate
            masks evaluated for ONE binding (eager device ops, no XLA
            compile).  Everything else — edges, segment boundaries,
            corrections — is graph-static and captured above."""
            seed_okps = self._fused_okpred(seed_scan, self.seed,
                                           seed_order, params)
            if seed_okps is None:
                return None
            masks: List[tuple] = []
            for spec, scan in uniq_masks:
                order, ends = self._fused_ids(st, spec.labels, n)
                okps = self._fused_okpred(scan, spec, order, params)
                if okps is None:
                    return None
                masks.append((okps, ends))
            return (seed_okps, seed_ends, tuple(masks),
                    tuple(hop_edges), corr, corr3)

        args = build_args(self.context.parameters)
        if args is None:
            return None
        # Host-side validity: the count row is always valid, and a numpy
        # mask lets result materialization skip one device round trip.
        valid = np.ones((cap1,), bool)
        all_preds = list(self.seed.preds) + [p for s, _sc in uniq_masks
                                             for p in s.preds]
        has_param_preds = any(
            isinstance(x, E.Param)
            for p in all_preds for x in _walk_expr(p))
        return (run, args, valid, build_args if has_param_preds else None)

    def _build_corr3(self, backend, st, n: int):
        """Static data for the 3-hop isomorphism correction.

        For a 3-hop chain the excluded walks are the union of A12 (e2=e1),
        A23 (e3=e2), A13 (e3=e1) over the enforced pairs P; every pairwise
        intersection of these events is the triple T (all edges equal), so
        |∪| = ΣA_p − coef·T with coef = max(0, |P|−1).  Each A-term is a
        per-edge sum over the hops' type-intersection scan (generalizing
        the 2-hop closed form at _fused_corr / _len2_correction; ref
        analog: planBoundedVarLengthExpand's rel-uniqueness filters†,
        SURVEY.md §3.2).  Returns ((c12, c23, i13, c123, d3, pair2),
        coef) of device arrays, None for a provably-zero correction, or
        _UNSUITABLE_CORR."""
        import jax.numpy as jnp
        h1, h2, h3 = self.hops
        P = self.uniq_pos
        if not P:
            return None

        def role(h, src, tgt):
            return (src, tgt) if h.direction == Direction.OUTGOING \
                else (tgt, src)

        def compact(cond, *arrs):
            return self._compact_cond(backend, n, cond, *arrs)

        def pair_rel(ha, hb):
            inter = _corr_intersection(ha, hb)
            if inter is None:
                return None
            rel = self._fused_rel(st, tuple(sorted(inter)))
            if rel is None:
                return _UNSUITABLE_CORR
            return rel

        c12 = c23 = i13 = c123 = d3 = pair2 = None
        if (1, 2) in P:
            rel = pair_rel(h1, h2)
            if rel is _UNSUITABLE_CORR:
                return _UNSUITABLE_CORR
            if rel is not None and rel[0].shape[0]:
                src, tgt, ok = rel
                n1, f1 = role(h1, src, tgt)
                n2, f2 = role(h2, src, tgt)
                c12 = compact(ok & (f1 == n2), n1, f1, f2)
            if c12 is not None:
                opp = Direction.INCOMING \
                    if h3.direction == Direction.OUTGOING \
                    else Direction.OUTGOING
                d3 = self._fused_edges(
                    st, tuple(sorted(set(h3.rel_types))), opp, n)
                if d3 is None:
                    return _UNSUITABLE_CORR
        if (2, 3) in P:
            rel = pair_rel(h2, h3)
            if rel is _UNSUITABLE_CORR:
                return _UNSUITABLE_CORR
            if rel is not None and rel[0].shape[0]:
                src, tgt, ok = rel
                n2, f2 = role(h2, src, tgt)
                n3, f3 = role(h3, src, tgt)
                c23 = compact(ok & (f2 == n3), n2, f2, f3)
        if (1, 3) in P:
            rel = pair_rel(h1, h3)
            if rel is _UNSUITABLE_CORR:
                return _UNSUITABLE_CORR
            if rel is not None and rel[0].shape[0]:
                src, tgt, ok = rel
                n1, f1 = role(h1, src, tgt)
                n3, f3 = role(h3, src, tgt)
                i13 = compact(ok, n1, f1, n3, f3)
            if i13 is not None:
                rel2 = self._fused_rel(
                    st, tuple(sorted(set(h2.rel_types))))
                if rel2 is None:
                    return _UNSUITABLE_CORR
                s2, t2, ok2 = rel2
                if s2.shape[0] == 0:
                    i13 = None  # no hop-2 edges: A13 walks cannot exist
                else:
                    n2v, f2v = role(h2, s2, t2)
                    keys = np.where(ok2, n2v.astype(np.int64) * n + f2v,
                                    np.int64(2) ** 62)
                    pair2 = backend.place_rows(jnp.asarray(np.sort(keys)))
        coef_t = max(0, len(P) - 1)
        if coef_t:
            i12t = _corr_intersection(h1, h2)
            inter3 = None
            if i12t is not None:
                t3 = set(h3.rel_types)
                if not t3:
                    inter3 = i12t
                elif not i12t:
                    inter3 = t3
                else:
                    inter3 = (i12t & t3) or None
            if inter3 is not None:
                rel = self._fused_rel(st, tuple(sorted(inter3)))
                if rel is None:
                    return _UNSUITABLE_CORR
                src, tgt, ok = rel
                if src.shape[0]:
                    n1, f1 = role(h1, src, tgt)
                    n2, f2 = role(h2, src, tgt)
                    n3, f3 = role(h3, src, tgt)
                    c123 = compact(ok & (f1 == n2) & (f2 == n3),
                                   n1, f1, f2, f3)
        if c12 is None and c23 is None and i13 is None and c123 is None:
            return None
        return ((c12, c23, i13, c123, d3, pair2), coef_t)

    def _compact_cond(self, backend, n: int, cond, *arrs):
        """Compact per-edge correction data to the (usually tiny) subset
        where ``cond`` holds — a static property of the graph — clipping
        indices into [0, n) and padding to a bucket.  Returns (cvalid,
        *clipped) device arrays, or None when no edge qualifies."""
        import jax.numpy as jnp
        (idx,) = np.nonzero(cond)
        nc = len(idx)
        if nc == 0:
            return None
        cap_c = backend.bucket(nc)
        idx = np.concatenate([idx, np.zeros(cap_c - nc, idx.dtype)])
        cvalid = np.arange(cap_c) < nc
        out = [backend.place_rows(jnp.asarray(cvalid))]
        out += [backend.place_rows(jnp.asarray(
            np.clip(a, 0, n - 1).astype(np.int32)[idx])) for a in arrs]
        return tuple(out)

    def _fused_corr(self, st, n: int):
        """Static per-edge data for the length-2 isomorphism correction:
        (cond, a, b, far2) with indices pre-clipped.  None = zero
        correction; _UNSUITABLE_CORR = no device path."""
        h1, h2 = self.hops[0], self.hops[1]
        inter = _corr_intersection(h1, h2)
        if inter is None:
            return None
        rel = self._fused_rel(st, tuple(sorted(inter)))
        if rel is None:
            return _UNSUITABLE_CORR
        src, tgt, ok = rel
        if src.shape[0] == 0:
            return None
        a, b, near2, far2 = _corr_roles(h1, h2, src, tgt)
        cond = ok & (near2 == b)
        safe = lambda v: np.clip(np.where(cond, v, 0), 0, n - 1
                                 ).astype(np.int32)
        return (cond, safe(a), safe(b), safe(far2))

    def _domain(self, parts) -> int:
        """Smallest N covering every id seen (consume_count so fused
        replay serves it sync-free)."""
        import jax.numpy as jnp
        backend = getattr(self.context.factory, "backend", None)
        mx = jnp.int64(-1)
        for vals, ok in parts:
            if vals.shape[0]:
                mx = jnp.maximum(mx, jnp.max(jnp.where(
                    ok, vals.astype(jnp.int64), -1)))
        n = (backend.consume_count(mx, relation="cap")
             if backend is not None else int(mx)) + 1
        if n <= 0:
            n = 1
        if n > _MAX_DOMAIN:
            raise _Unsuitable(f"node-id domain {n} too large")
        return n

    def _indicator(self, ids, ok, n: int, dtype):
        import jax
        import jax.numpy as jnp
        safe = jnp.where(ok, ids, n).astype(jnp.int32)
        vec = jax.ops.segment_sum(ok.astype(dtype), safe,
                                  num_segments=n + 1)[:n]
        return jnp.minimum(vec, 1)

    def _compute_pushdown(self):
        import jax
        import jax.numpy as jnp

        fused = self._fused_total()
        if fused is not None:
            return self._emit_fused(*fused)

        if max(self.lengths) >= 3 and self.uniq_pos:
            # the 3-hop inclusion–exclusion correction only exists on the
            # fused path; walks-only 3-hop chains may continue below
            raise _Unsuitable("3-hop isomorphism correction is fused-only")

        seed_ids, seed_ok = self._node_ids(self.seed)
        rel_cache: Dict[Tuple[str, ...], tuple] = {}
        for h in self.hops:
            key = tuple(sorted(set(h.rel_types)))
            if key not in rel_cache:
                rel_cache[key] = self._rel_arrays(h.rel_types)
        # Mask regimes (engine join semantics):
        #   fixed chain — Expand joins the target node scan at EVERY hop:
        #     mask_vecs[i] (node existence + labels + preds) multiplies the
        #     frontier after hop i;
        #   var-length — VarExpand joins the target only where a path
        #     ends: one end_mask applied at counting lengths, frontier
        #     flows unmasked through intermediate (possibly node-less)
        #     endpoints.
        if self.is_varlen:
            mask_ids = [self._node_ids(self.hops[0].target)]
        else:
            mask_ids = [self._node_ids(h.target) for h in self.hops]

        domain_parts = [(seed_ids, seed_ok)]
        for (src, tgt) in rel_cache.values():
            domain_parts += [src, tgt]
        domain_parts += mask_ids
        n = self._domain(domain_parts)

        seed_vec = self._indicator(seed_ids, seed_ok, n, jnp.int64)
        mask_vecs = [self._indicator(m[0], m[1], n, jnp.int64)
                     for m in mask_ids]
        end_mask = mask_vecs[0] if self.is_varlen else mask_vecs[-1]

        def hop_arrays(h: HopSpec):
            (src, src_ok), (tgt, tgt_ok) = rel_cache[
                tuple(sorted(set(h.rel_types)))]
            ok = src_ok & tgt_ok
            frm, to = (src, tgt) if h.direction == Direction.OUTGOING \
                else (tgt, src)
            return frm, to, ok

        backend = getattr(self.context.factory, "backend", None)
        mesh = getattr(backend, "mesh", None)
        total = jnp.int64(0)
        ring_total = self._try_ring(mesh, n, seed_vec, mask_vecs, hop_arrays)
        if ring_total is not None:
            total = ring_total
        else:
            self.strategy = "spmv-sharded" if mesh is not None else "spmv"
            x = seed_vec
            for length in range(0, max(self.lengths) + 1):
                if length in self.lengths:
                    # fixed chains are already fully masked; var-length
                    # paths are masked only where they end
                    xl = x * end_mask if self.is_varlen else x
                    total = total + xl.sum()
                if length < max(self.lengths):
                    h = self.hops[length]
                    frm, to, ok = hop_arrays(h)
                    safe_frm = jnp.where(ok, frm, 0).astype(jnp.int32)
                    safe_to = jnp.where(ok, to, n).astype(jnp.int32)
                    contrib = jnp.where(ok, x[safe_frm], 0)
                    x = jax.ops.segment_sum(contrib, safe_to,
                                            num_segments=n + 1)[:n]
                    if not self.is_varlen:
                        x = x * mask_vecs[length]

        if self.correct_len2:
            if self.is_varlen:
                corr_masks = (None, end_mask)
            else:
                corr_masks = (mask_vecs[0], mask_vecs[1])
            total = total - self._len2_correction(
                n, seed_vec, corr_masks, hop_arrays, jnp)

        return self._emit(total)

    def _try_ring(self, mesh, n, seed_vec, mask_vecs, hop_arrays):
        """Uniform unmasked chains on a mesh ride the ppermute ring
        schedule (parallel/ring.py).  Returns the total or None."""
        import jax
        import jax.numpy as jnp
        backend = getattr(self.context.factory, "backend", None)
        if mesh is None or backend is None:
            return None
        if mesh.devices.ndim != 1:
            # the hand-scheduled ring is a 1-D-mesh optimization; 2-D
            # (DCN x ICI) meshes take the GSPMD spmv-sharded path
            return None
        if not getattr(backend.config, "use_ring", True):
            return None
        if len(self.lengths) != 1 or self.lengths[0] < 1:
            return None
        k = self.lengths[0]
        specs = {(h.rel_types, h.direction) for h in self.hops}
        if len(specs) != 1:
            return None
        if not self.is_varlen:
            # fixed chains mask every hop; the ring applies ONE mask per
            # hop, so all hop target specs must coincide
            if len({(h.target.labels, h.target.preds)
                    for h in self.hops}) != 1:
                return None
        from caps_tpu.parallel.ring import ring_khop_cached
        from jax.sharding import NamedSharding, PartitionSpec as P
        s = int(mesh.devices.size)
        n_pad = ((n + s - 1) // s) * s
        frm, to, ok = hop_arrays(self.hops[0])
        e_pad = ((int(frm.shape[0]) + s - 1) // s) * s
        def pad_edges(a, fill):
            return jnp.concatenate(
                [a, jnp.full((e_pad - a.shape[0],), fill, a.dtype)])
        seed_p = jnp.concatenate(
            [seed_vec, jnp.zeros((n_pad - n,), seed_vec.dtype)])
        frm_p = pad_edges(jnp.where(ok, frm, 0).astype(jnp.int32), 0)
        to_p = pad_edges(jnp.where(ok, to, 0).astype(jnp.int32), 0)
        ok_p = pad_edges(ok, False)
        shard = NamedSharding(mesh, P(backend.axis))
        seed_p = jax.device_put(seed_p, shard)
        frm_p = jax.device_put(frm_p, shard)
        to_p = jax.device_put(to_p, shard)
        ok_p = jax.device_put(ok_p, shard)
        def pad_mask(vec):
            m = jnp.concatenate([vec, jnp.zeros((n_pad - n,), vec.dtype)])
            return jax.device_put(m, shard)
        if self.is_varlen:
            # intermediate endpoints unmasked; end mask applied on the
            # final block-sharded frontier
            khop = ring_khop_cached(mesh, n_pad, k, axis=backend.axis)
            total, blk = khop(seed_p, frm_p, to_p, ok_p)
            total = (blk.astype(jnp.int64) * pad_mask(mask_vecs[0])).sum()
        else:
            khop = ring_khop_cached(mesh, n_pad, k, axis=backend.axis,
                                    masked=True)
            total, blk = khop(seed_p, frm_p, to_p, ok_p,
                              pad_mask(mask_vecs[0]))
        self.strategy = "ring"
        return total

    def _len2_correction(self, n, seed_vec, corr_masks, hop_arrays, jnp):
        """Walks of length 2 reusing their edge (r2 == r1): an edge can be
        reused only if it satisfies BOTH hops' type constraints, i.e. it
        lies in the *intersection* scan (an untyped hop matches every
        type).  For each such edge the reuse is expressible per edge —
        subtract seed[a]·mask_b[b]·mask_c[c] where the hop directions
        determine (a, b, c) — making the lowering exact under
        relationship isomorphism for every type combination."""
        h1, h2 = self.hops[0], self.hops[1]
        inter = _corr_intersection(h1, h2)
        if inter is None:
            return jnp.int64(0)  # disjoint scans: an edge can't repeat
        (src, src_ok), (tgt, tgt_ok) = self._rel_arrays(
            tuple(sorted(inter)))
        ok = src_ok & tgt_ok
        a, b, near2, far2 = _corr_roles(h1, h2, src, tgt)
        cond = ok & (near2 == b)
        def mask_at(vec, ids):
            if vec is None:
                return 1
            safe = jnp.clip(ids, 0, n - 1).astype(jnp.int32)
            return vec[safe]
        safe_a = jnp.where(cond, a, 0).astype(jnp.int32)
        contrib = jnp.where(
            cond,
            seed_vec[jnp.clip(safe_a, 0, n - 1)]
            * mask_at(corr_masks[0], b) * mask_at(corr_masks[1], far2),
            0)
        return contrib.sum()

    def _emit_fused(self, data, valid):
        """Wrap the fused program's already-padded output column (no extra
        device dispatches on the steady path)."""
        header = RecordHeader([(E.Var(self.out_name), self.out_name,
                                CTInteger)])
        from caps_tpu.backends.tpu.table import Column, DeviceTable
        col = Column("int", data, valid, CTInteger)
        return header, DeviceTable(self.context.factory.backend,
                                   {self.out_name: col}, 1)

    def _emit(self, total):
        import jax.numpy as jnp
        header = RecordHeader([(E.Var(self.out_name), self.out_name,
                                CTInteger)])
        factory = self.context.factory
        from caps_tpu.backends.tpu.table import (
            Column, DeviceTable, DeviceTableFactory,
        )
        if isinstance(factory, DeviceTableFactory):
            cap = factory.backend.bucket(1)
            data = jnp.zeros((cap,), jnp.int64).at[0].set(total)
            col = Column("int", data, jnp.ones((cap,), bool), CTInteger)
            return header, DeviceTable(factory.backend,
                                       {self.out_name: col}, 1)
        return header, factory.from_columns(
            {self.out_name: [int(total)]}, {self.out_name: CTInteger})

    def _pretty_args(self):
        hops = "".join(
            f"-[:{'|'.join(h.rel_types)}]{'>' if h.direction == Direction.OUTGOING else '<'}"
            for h in self.hops)
        return (f"{self.out_name}=count(*), ({self.seed.var}){hops}, "
                f"lengths={self.lengths}, strategy={self.strategy}")


class CountCycleOp(CountPatternOp):
    """Count directed-triangle matches — a 2-hop chain a->b->c plus a
    closing edge between a and c — WITHOUT the join cascade.

    The lowering enumerates the chain's 2-paths in fixed-shape device
    batches and probes a sorted closing-edge key table:

        W[j]   = out-degree (hop 2) of hop-1 edge j's endpoint b
        P      = sum W — the number of 2-paths
        path p = (edge j, k-th hop-2 neighbour of b), recovered with one
                 searchsorted over cumsum(W)
        count += multiplicity of key a*n + c in the closing edge set

    ONE jitted program of batch size B serves every batch and every graph
    scale — compile cost is O(1) in the graph, intermediates are bounded
    by B, and parallel closing edges are counted exactly (the probe
    returns multiplicity).  Relationship isomorphism is enforced
    structurally: with no self-loop edges in any participating scan, the
    three matched rel instances are necessarily pairwise distinct (any
    coincidence forces a self-loop); graphs with self-loops fall back to
    the join plan — which for a cyclic pattern is now itself the
    worst-case-optimal MultiwayJoinOp (relational/wcoj.py), not the raw
    cascade.  (Ref analog: Spark executes this query as a 5-way
    shuffle-join cascade — reconstructed, mount empty; BASELINE.md
    config 4.)

    This op is the AGGREGATE-ONLY specialization of the WCOJ path: the
    closing probe is ``ops/wcoj.py``'s sorted pair-key multiplicity
    (the close step with the enumeration skipped — multiplicities sum
    instead of expanding), and since PR 14 the closure is SHAPE-keyed
    like the main count path: node-predicate masks rebuild per unseen
    binding as eager device args (``_cycle_mask_dev``), so cyclic count
    families stop charging per-value ``count_fused`` compiles.
    """

    #: per-dispatch 2-path batch; one compile serves all batches
    _BATCH = 1 << 20

    def __init__(self, context, fallback, graph, out_name, seed: NodeSpec,
                 hops: Sequence[HopSpec], close_hop: HopSpec):
        super().__init__(context, fallback, graph, out_name, seed, hops,
                         lengths=[2], uniq_pos=frozenset())
        self.close_hop = close_hop

    def _plan_sig(self):
        ch = self.close_hop
        return (super()._plan_sig(), "cycle",
                tuple(sorted(set(ch.rel_types))), ch.direction)

    def _compute_pushdown(self):
        fused = self._fused_total()
        if fused is None:
            raise _Unsuitable("cycle count needs the fused device path")
        self.strategy = "cycle-probe"
        return self._emit_fused(*fused)

    def _cycle_mask_dev(self, st, spec: NodeSpec, n: int, params):
        """Dense DEVICE bool mask over the id domain for one node var
        (existence + labels + predicates) — a pure function of graph
        data + ``params``, rebuilt per unseen binding as cheap eager
        device ops so the cycle closure stays SHAPE-keyed (the PR 10
        cold-process residual, closed for the cycle family too)."""
        scan = self._fused_scan(st, spec.labels)
        if scan is None:
            return None
        order, ends = self._fused_ids(st, spec.labels, n)
        okps = self._fused_okpred(scan, spec, order, params)
        if okps is None:
            return None
        return _dense_bool_vec(okps, ends, n)

    def _build_fused(self, backend, gk):
        import jax
        import jax.numpy as jnp
        from caps_tpu.ops import wcoj as WC
        st = self._graph_static(backend, gk)

        h1, h2, ch = self.hops[0], self.hops[1], self.close_hop
        relkeys = [tuple(sorted(set(h.rel_types))) for h in (h1, h2, ch)]
        rels = [self._fused_rel(st, rk) for rk in relkeys]
        if any(r is None for r in rels):
            return None
        # no self-loops anywhere rels participate: the structural
        # guarantee that the three cycle rels are pairwise distinct
        for src, tgt, ok in rels:
            if src.shape[0] and bool(np.any((src == tgt) & ok)):
                return None

        seed_scan = self._fused_scan(st, self.seed.labels)
        if seed_scan is None or \
                self._fused_scan(st, h1.target.labels) is None or \
                self._fused_scan(st, h2.target.labels) is None:
            return None

        mx = -1
        for labels in (self.seed.labels, h1.target.labels, h2.target.labels):
            _h, _t, _ok, host_ids, host_ok = st["scans"][("node", labels)]
            if host_ids.shape[0] and host_ok.any():
                mx = max(mx, int(host_ids[host_ok].max()))
        for src, tgt, ok in rels:
            if src.shape[0] and ok.any():
                mx = max(mx, int(src[ok].max()), int(tgt[ok].max()))
        n = mx + 1
        if n <= 0:
            n = 1
        if n > _MAX_DOMAIN:
            return None

        def oriented(rel, direction):
            src, tgt, ok = rel
            return (src, tgt, ok) if direction == Direction.OUTGOING \
                else (tgt, src, ok)

        # STATIC structures: validity-compacted only — node masks are
        # per-BINDING arguments now, applied on the fly (a/b gate the
        # 2-path weights, c gates inside the batch), so one compiled
        # closure serves every parameter value of the shape.
        f1, t1, ok1 = oriented(rels[0], h1.direction)
        e1f = np.clip(f1[ok1], 0, n - 1).astype(np.int32)
        e1t = np.clip(t1[ok1], 0, n - 1).astype(np.int32)

        # hop 2 CSR b->c (validity only; c-mask applied in the batch)
        f2, t2, ok2 = oriented(rels[1], h2.direction)
        f2c = f2[ok2].astype(np.int64)
        t2c = np.clip(t2[ok2], 0, n - 1).astype(np.int32)
        order2 = np.argsort(f2c, kind="stable")
        adj2 = t2c[order2]
        starts2 = np.searchsorted(f2c[order2], np.arange(n + 1, dtype=np.int64),
                                  side="left").astype(np.int64)

        # closing edge key table a*n + c (multiplicity-preserving)
        f3, t3, ok3 = oriented(rels[2], ch.direction)
        keys = (f3[ok3].astype(np.int64) * n + t3[ok3].astype(np.int64))
        keys = np.sort(keys)

        cap1 = backend.bucket(1)
        valid = np.ones((cap1,), bool)
        if e1f.shape[0] == 0 or keys.shape[0] == 0:
            zero = jnp.zeros((cap1,), jnp.int64)
            return ((lambda *a: zero), (), valid, None)

        B = self._BATCH
        d_e1f = backend.place_rows(jnp.asarray(e1f))
        d_e1t = backend.place_rows(jnp.asarray(e1t))
        d_starts2 = backend.place_rows(jnp.asarray(starts2))
        d_adj2 = backend.place_rows(jnp.asarray(adj2)) if adj2.shape[0] \
            else jnp.zeros((1,), jnp.int32)
        d_keys = backend.place_rows(jnp.asarray(keys))
        n_i64 = jnp.int64(n)
        # host loop extent for the current binding (set by build_args;
        # not traced — the jitted batch program is P-generic)
        cell = {"n_batches": 0, "P": 0}

        @jax.jit
        def batch(p0, p_lim, m_c, cum_w):
            p = p0 + jnp.arange(B, dtype=jnp.int64)
            live = p < p_lim
            ps = jnp.where(live, p, 0)
            j = jnp.searchsorted(cum_w, ps, side="right")
            j = jnp.minimum(j, cum_w.shape[0] - 1)
            prev = jnp.where(j > 0, cum_w[jnp.maximum(j - 1, 0)], 0)
            k = ps - prev
            a = d_e1f[j].astype(jnp.int64)
            b = d_e1t[j].astype(jnp.int64)
            idx = jnp.minimum(d_starts2[b] + k, d_adj2.shape[0] - 1)
            c = d_adj2[idx]
            live = live & m_c[c]
            key = a * n_i64 + c.astype(jnp.int64)
            # sorted-pair multiplicity probe: the aggregate-only
            # specialization of the WCOJ close step (ops/wcoj.py)
            cnt = WC.multiplicity(d_keys, key)
            return jnp.where(live, cnt, 0).sum()

        def run(m_c, cum_w):
            n_batches = cell["n_batches"]
            if n_batches == 0:
                return jnp.zeros((cap1,), jnp.int64)
            p_lim = jnp.int64(cell["P"])
            parts = [batch(jnp.int64(i * B), p_lim, m_c, cum_w)
                     for i in range(n_batches)]
            total = parts[0]
            for x in parts[1:]:
                total = total + x
            return jnp.zeros((cap1,), jnp.int64).at[0].set(total)

        static_nbytes = sum(int(x.nbytes) for x in (d_e1f, d_e1t, d_starts2,
                                                    d_adj2, d_keys))

        def build_args(params):
            """The parameter-dependent half: dense node masks + the
            masked 2-path weight prefix sum, eager device ops (no XLA
            compile, no count_fused charge).  One host scalar read (P)
            sizes the batch loop — and re-stamps the roofline numerator
            (``run.nbytes_in``: bytes every batch probes from the
            resident static arrays, ADDED to the args accounting by
            ``_fused_total``), so later bindings with different path
            counts report honest per-execution bytes."""
            m_a = self._cycle_mask_dev(st, self.seed, n, params)
            m_b = self._cycle_mask_dev(st, h1.target, n, params)
            m_c = self._cycle_mask_dev(st, h2.target, n, params)
            if m_a is None or m_b is None or m_c is None:
                return None
            deg2 = d_starts2[d_e1t + 1] - d_starts2[d_e1t]
            w = jnp.where(m_a[d_e1f] & m_b[d_e1t], deg2, 0)
            cum_w = jnp.cumsum(w)
            p_total = int(cum_w[-1])
            cell["P"] = p_total
            cell["n_batches"] = (p_total + B - 1) // B
            run.nbytes_in = cell["n_batches"] * static_nbytes
            return (m_c, cum_w)

        args = build_args(self.context.parameters)
        if args is None:
            return None
        self.strategy = "cycle-probe"
        all_preds = (list(self.seed.preds) + list(h1.target.preds)
                     + list(h2.target.preds) + list(ch.target.preds))
        has_param_preds = any(
            isinstance(x, E.Param)
            for p in all_preds for x in _walk_expr(p))
        return (run, args, valid, build_args if has_param_preds else None)

    def _pretty_args(self):
        ch = self.close_hop
        arrow = ">" if ch.direction == Direction.OUTGOING else "<"
        return (f"{self.out_name}=count(*), triangle ({self.seed.var})"
                f"->({self.hops[0].target.var})->({self.hops[1].target.var})"
                f" closed by [:{'|'.join(ch.rel_types)}]{arrow}, "
                f"strategy={self.strategy}")

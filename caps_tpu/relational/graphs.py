"""Relational property graphs over scan tables.

Mirrors the reference's ``ScanGraph`` (per-entity-type scans; scans align
and union entity tables), ``UnionGraph`` and ``EmptyGraph`` (ref:
okapi-relational/.../impl/graph/ — reconstructed, mount empty; SURVEY.md
§2 "Relational graphs", §3.3).
"""
from __future__ import annotations

import itertools
from typing import Any, FrozenSet, Iterable, List, Mapping, Optional, Tuple

from caps_tpu.ir import exprs as E
from caps_tpu.okapi.graph import PropertyGraph
from caps_tpu.okapi.schema import Schema
from caps_tpu.okapi.types import (
    CTBoolean, CTString, CypherType,
)
from caps_tpu.relational.entity_tables import NodeTable, RelationshipTable
from caps_tpu.relational.header import RecordHeader
from caps_tpu.relational.table import Table, TableFactory


class RelationalCypherGraph(PropertyGraph):
    """Backend-generic graph: can produce aligned scan tables."""

    def __init__(self, session):
        self._session = session

    @property
    def session(self):
        return self._session

    @property
    def factory(self) -> TableFactory:
        return self._session.table_factory

    # -- scans ---------------------------------------------------------------

    def scan_node(self, var: str, labels: Iterable[str] = ()
                  ) -> Tuple[RecordHeader, Table]:
        raise NotImplementedError

    def scan_rel(self, var: str, rel_types: Iterable[str] = ()
                 ) -> Tuple[RecordHeader, Table]:
        raise NotImplementedError

    # -- PropertyGraph API ---------------------------------------------------

    def cypher(self, query: str, parameters: Optional[Mapping[str, Any]] = None):
        return self._session.cypher_on_graph(self, query, parameters)

    def prepare(self, query: str):
        """Prepared statement bound to this graph: parse once, then
        ``.run(params)`` serves the plan from the session plan cache."""
        return self._session.prepare(query, graph=self)

    def nodes(self, var: str = "n", labels: Iterable[str] = ()):
        header, table = self.scan_node(var, labels)
        return self._session.records_from(header, table, (var,))

    def relationships(self, var: str = "r", rel_types: Iterable[str] = ()):
        header, table = self.scan_rel(var, rel_types)
        return self._session.records_from(header, table, (var,))

    def union_all(self, *others: "RelationalCypherGraph") -> "UnionGraph":
        graphs: List[RelationalCypherGraph] = [self]
        for o in others:
            graphs.extend(o.graphs if isinstance(o, UnionGraph) else [o])
        return UnionGraph(self._session, tuple(graphs))

    def rel_lookup(self):
        """Host-side map rel-id -> (src, tgt, type, props), used to
        materialize variable-length relationship lists."""
        return {}

    def node_lookup(self):
        """Host-side map node-id -> (labels, props), used to materialize
        path values and node lists."""
        return {}

    def statistics(self):
        """Ingest-time statistics sketch (relational/stats.py) — the
        cost model's prior.  Graphs without scan tables report the
        empty sketch; ScanGraph computes lazily and caches."""
        from caps_tpu.relational.stats import EMPTY_STATS
        return EMPTY_STATS


def _align_node_scan(nt: NodeTable, header: RecordHeader, var: str,
                     all_labels: Iterable[str]) -> Table:
    """Rename/extend one node table to the target scan header layout."""
    t = nt.table
    m = nt.mapping
    keep = [m.id_col] + list(m.property_cols.values())
    t = t.select(keep)
    rename = {m.id_col: f"{var}__id"}
    for key, col in m.property_cols.items():
        rename[col] = f"{var}__prop_{key}"
    t = t.rename(rename)
    for lbl in all_labels:
        t = t.with_literal_column(f"{var}__label_{lbl}", lbl in nt.labels,
                                  CTBoolean)
    for e in header.exprs:
        col = header.column(e)
        if col not in t.columns:
            t = t.with_literal_column(col, None, header.type_of(e))
    return t.select(list(header.columns))


def _align_rel_scan(rt: RelationshipTable, header: RecordHeader, var: str) -> Table:
    t = rt.table
    m = rt.mapping
    keep = [m.id_col, m.source_col, m.target_col] + list(m.property_cols.values())
    t = t.select(keep)
    rename = {m.id_col: f"{var}__id", m.source_col: f"{var}__src",
              m.target_col: f"{var}__tgt"}
    for key, col in m.property_cols.items():
        rename[col] = f"{var}__prop_{key}"
    t = t.rename(rename)
    t = t.with_literal_column(f"{var}__type", rt.rel_type, CTString)
    for e in header.exprs:
        col = header.column(e)
        if col not in t.columns:
            t = t.with_literal_column(col, None, header.type_of(e))
    return t.select(list(header.columns))


def align_scan(header: RecordHeader, t: Table) -> Table:
    """Align a sub-scan to a wider union header: missing label columns
    become False (the label is not possible in that part), other missing
    columns null — the UnionGraph technique, shared with the versioned
    snapshot overlay (relational/updates.py)."""
    for e in header.exprs:
        col = header.column(e)
        if col not in t.columns:
            default = False if isinstance(e, E.HasLabel) else None
            t = t.with_literal_column(col, default, header.type_of(e))
    return t.select(list(header.columns))


class ScanGraph(RelationalCypherGraph):
    """A graph stored as one table per label-combination / relationship type."""

    _version_counter = itertools.count(1)

    def __init__(self, session, node_tables: Iterable[NodeTable] = (),
                 rel_tables: Iterable[RelationshipTable] = ()):
        super().__init__(session)
        # Monotone graph identity for plan/size-memo caches (fused executor)
        self.version = next(ScanGraph._version_counter)
        self.node_tables: Tuple[NodeTable, ...] = tuple(node_tables)
        self.rel_tables: Tuple[RelationshipTable, ...] = tuple(rel_tables)
        for rt in self.rel_tables:
            # ingest-time physical layout (CSR adjacency on device backends)
            self.factory.prepare_rel_table(rt)
        schema = Schema.empty()
        for nt in self.node_tables:
            schema = schema.union(nt.schema())
        for rt in self.rel_tables:
            schema = schema.union(rt.schema())
        self._schema = schema
        self._rel_lookup_cache = None
        self._node_lookup_cache = None
        self._statistics_cache = None

    @property
    def schema(self) -> Schema:
        return self._schema

    def statistics(self):
        """Lazily computed, cached ingest-time sketch: per-label
        cardinalities, degree distributions, hot-key skew
        (relational/stats.py) — the cost model's prior.  One host pass
        at first use; a ``stats.computed`` counter records it."""
        if self._statistics_cache is None:
            from caps_tpu.relational.stats import compute_graph_statistics
            self._statistics_cache = compute_graph_statistics(self)
            registry = getattr(self._session, "metrics_registry", None)
            if registry is not None:
                registry.counter("stats.computed").inc()
        return self._statistics_cache

    def seed_statistics(self, payload) -> bool:
        """Adopt a persisted statistics sketch (plan_store.py payload)
        as this graph's prior — the load half of the store's
        ``stats`` field: a cold process prices its first plans from
        the PREVIOUS process's observed graph shape without paying the
        host recompute.  Only lands when nothing has been computed yet
        (a live sketch always wins), and stays advisory by the stats
        contract: a stale seed mis-prices a plan at worst, and
        calibration from ``op_stats`` actuals plus the divergence →
        re-plan loop correct exactly that case."""
        if self._statistics_cache is not None:
            return False
        from caps_tpu.relational.stats import GraphStatistics
        try:
            stats = GraphStatistics.from_payload(payload)
        except Exception:  # malformed store field — hint, not authority
            return False
        if stats is None or not stats.total_nodes:
            return False
        self._statistics_cache = stats
        registry = getattr(self._session, "metrics_registry", None)
        if registry is not None:
            registry.counter("stats.seeded").inc()
        return True

    def node_lookup(self):
        if self._node_lookup_cache is None:
            out = {}
            for nt in self.node_tables:
                m = nt.mapping
                t = nt.table
                ids = t.column_values(m.id_col)
                props = {key: t.column_values(col)
                         for key, col in m.property_cols.items()}
                labels = tuple(sorted(nt.labels))
                for i, nid in enumerate(ids):
                    p = {k: v[i] for k, v in props.items() if v[i] is not None}
                    out[nid] = (labels, p)
            self._node_lookup_cache = out
        return self._node_lookup_cache

    def rel_lookup(self):
        if self._rel_lookup_cache is None:
            out = {}
            for rt in self.rel_tables:
                m = rt.mapping
                t = rt.table
                ids = t.column_values(m.id_col)
                srcs = t.column_values(m.source_col)
                tgts = t.column_values(m.target_col)
                props = {key: t.column_values(col)
                         for key, col in m.property_cols.items()}
                for i, rid in enumerate(ids):
                    p = {k: v[i] for k, v in props.items() if v[i] is not None}
                    out[rid] = (srcs[i], tgts[i], rt.rel_type, p)
            self._rel_lookup_cache = out
        return self._rel_lookup_cache

    def scan_node(self, var: str, labels: Iterable[str] = ()
                  ) -> Tuple[RecordHeader, Table]:
        labels = frozenset(labels)
        header = RecordHeader.for_node(var, self._schema, labels)
        combos = set(self._schema.combinations_for(labels))
        all_labels = sorted({lbl for c in combos for lbl in c})
        parts = [
            _align_node_scan(nt, header, var, all_labels)
            for nt in self.node_tables if nt.labels in combos
        ]
        if not parts:
            return header, self.factory.empty(
                header.columns,
                {header.column(e): header.type_of(e) for e in header.exprs})
        out = parts[0]
        for p in parts[1:]:
            out = out.union_all(p)
        return header, out

    def scan_rel(self, var: str, rel_types: Iterable[str] = ()
                 ) -> Tuple[RecordHeader, Table]:
        rel_types = frozenset(rel_types)
        header = RecordHeader.for_relationship(var, self._schema, rel_types)
        wanted = rel_types or self._schema.relationship_types
        parts = [
            _align_rel_scan(rt, header, var)
            for rt in self.rel_tables if rt.rel_type in wanted
        ]
        if not parts:
            return header, self.factory.empty(
                header.columns,
                {header.column(e): header.type_of(e) for e in header.exprs})
        out = parts[0]
        for p in parts[1:]:
            out = out.union_all(p)
        return header, out


class EmptyGraph(RelationalCypherGraph):
    @property
    def schema(self) -> Schema:
        return Schema.empty()

    def scan_node(self, var, labels=()):
        header = RecordHeader.for_node(var, Schema.empty(), frozenset(labels))
        return header, self.factory.empty(header.columns, {})

    def scan_rel(self, var, rel_types=()):
        header = RecordHeader.for_relationship(var, Schema.empty(),
                                               frozenset(rel_types))
        cols = {header.column(e): header.type_of(e) for e in header.exprs}
        return header, self.factory.empty(header.columns, cols)


class UnionGraph(RelationalCypherGraph):
    """The union of several graphs (the reference's ``UnionGraph``).  Node
    and relationship ids must come from disjoint id spaces (the construct
    planner guarantees this by retagging)."""

    def __init__(self, session, graphs: Tuple[RelationalCypherGraph, ...]):
        super().__init__(session)
        self.graphs = graphs
        schema = Schema.empty()
        for g in graphs:
            schema = schema.union(g.schema)
        self._schema = schema

    @property
    def schema(self) -> Schema:
        return self._schema

    def rel_lookup(self):
        out = {}
        for g in self.graphs:
            out.update(g.rel_lookup())
        return out

    def node_lookup(self):
        out = {}
        for g in self.graphs:
            out.update(g.node_lookup())
        return out

    def _union_scans(self, header: RecordHeader,
                     scans: List[Tuple[RecordHeader, Table]]) -> Table:
        parts = [align_scan(header, t) for _sub_header, t in scans]
        out = parts[0]
        for p in parts[1:]:
            out = out.union_all(p)
        return out

    def scan_node(self, var: str, labels: Iterable[str] = ()):
        header = RecordHeader.for_node(var, self._schema, frozenset(labels))
        scans = [g.scan_node(var, labels) for g in self.graphs]
        return header, self._union_scans(header, scans)

    def scan_rel(self, var: str, rel_types: Iterable[str] = ()):
        header = RecordHeader.for_relationship(var, self._schema,
                                               frozenset(rel_types))
        scans = [g.scan_rel(var, rel_types) for g in self.graphs]
        return header, self._union_scans(header, scans)

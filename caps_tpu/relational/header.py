"""RecordHeader: the bidirectional map between expressions and physical
columns.

Mirrors the reference's central data structure (ref:
okapi-relational/.../impl/table/RecordHeader.scala — reconstructed, mount
empty; SURVEY.md §2 "RecordHeader"): a node var owns an id column, one
boolean column per possible label, and one column per property; a rel var
owns id, source, target, type and property columns; value vars own a single
column.

Column naming is deterministic:

    Var(n)/Id(Var(n))        -> "n__id"        (entities)
    Var(x)                   -> "x"            (values)
    HasLabel(Var(n), "L")    -> "n__label_L"
    StartNode(Var(r))        -> "r__src"
    EndNode(Var(r))          -> "r__tgt"
    Type(Var(r))             -> "r__type"
    Property(Var(n), "k")    -> "n__prop_k"
    var-length rel hop i     -> "r__hop{i}"
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from caps_tpu.ir import exprs as E
from caps_tpu.okapi.schema import Schema
from caps_tpu.okapi.types import (
    CTBoolean, CTInteger, CTList, CTNode, CTRelationship, CTString,
    CypherType, _CTNode, _CTRelationship,
)


class HeaderError(Exception):
    pass


def column_name_for(expr: E.Expr, entity_vars: Iterable[str]) -> str:
    """Deterministic column name for a mappable expression."""
    entity_vars = set(entity_vars)
    if isinstance(expr, E.Var):
        return f"{expr.name}__id" if expr.name in entity_vars else expr.name
    if isinstance(expr, E.Id) and isinstance(expr.entity, E.Var):
        return f"{expr.entity.name}__id"
    if isinstance(expr, E.HasLabel) and isinstance(expr.node, E.Var):
        return f"{expr.node.name}__label_{expr.label}"
    if isinstance(expr, E.StartNode) and isinstance(expr.rel, E.Var):
        return f"{expr.rel.name}__src"
    if isinstance(expr, E.EndNode) and isinstance(expr.rel, E.Var):
        return f"{expr.rel.name}__tgt"
    if isinstance(expr, E.Type) and isinstance(expr.rel, E.Var):
        return f"{expr.rel.name}__type"
    if isinstance(expr, E.Property) and isinstance(expr.entity, E.Var):
        return f"{expr.entity.name}__prop_{expr.key}"
    if isinstance(expr, E.PathSeg) and isinstance(expr.path, E.Var):
        return f"{expr.path.name}__seg{expr.index}"
    if isinstance(expr, E.PathNode) and isinstance(expr.path, E.Var):
        return f"{expr.path.name}__node{expr.index}"
    raise HeaderError(f"no canonical column name for {expr!r}")


class RecordHeader:
    """Immutable ordered mapping Expr -> (column, CypherType)."""

    def __init__(self, entries: Iterable[Tuple[E.Expr, str, CypherType]] = ()):
        self._entries: Tuple[Tuple[E.Expr, str, CypherType], ...] = tuple(entries)
        self._by_expr: Dict[E.Expr, Tuple[str, CypherType]] = {
            e: (c, t) for e, c, t in self._entries}
        cols: Dict[str, CypherType] = {}
        for _, c, t in self._entries:
            if c in cols:
                continue
            cols[c] = t
        self._col_types = cols
        if len(self._by_expr) != len(self._entries):
            raise HeaderError("duplicate expression in header")

    # -- queries ------------------------------------------------------------

    @property
    def exprs(self) -> Tuple[E.Expr, ...]:
        return tuple(e for e, _, _ in self._entries)

    @property
    def columns(self) -> Tuple[str, ...]:
        # unique, insertion order
        return tuple(self._col_types.keys())

    def has(self, expr: E.Expr) -> bool:
        return expr in self._by_expr

    def column(self, expr: E.Expr) -> str:
        if expr not in self._by_expr:
            raise HeaderError(f"expression {expr!r} not in header "
                              f"(has: {[str(e) for e in self.exprs]})")
        return self._by_expr[expr][0]

    def type_of(self, expr: E.Expr) -> CypherType:
        if expr not in self._by_expr:
            raise HeaderError(f"expression {expr!r} not in header")
        return self._by_expr[expr][1]

    def column_type(self, col: str) -> CypherType:
        return self._col_types[col]

    @property
    def entity_vars(self) -> Tuple[str, ...]:
        out = []
        for e, _, t in self._entries:
            if isinstance(e, E.Var) and isinstance(
                    t.material, (_CTNode, _CTRelationship)):
                out.append(e.name)
        return tuple(out)

    @property
    def composite_vars(self) -> Tuple[str, ...]:
        """Vars owning multiple columns: entity vars plus path vars."""
        from caps_tpu.okapi.types import _CTPath
        out = list(self.entity_vars)
        for e, _, t in self._entries:
            if isinstance(e, E.Var) and isinstance(t.material, _CTPath):
                out.append(e.name)
        return tuple(out)

    @property
    def vars(self) -> Tuple[str, ...]:
        return tuple(e.name for e, _, _ in self._entries if isinstance(e, E.Var))

    def var_type(self, name: str) -> CypherType:
        return self.type_of(E.Var(name))

    def exprs_for(self, var: str) -> Tuple[E.Expr, ...]:
        """All expressions owned by ``var`` (the reference's
        ``expressionsFor``/``ownedBy``)."""
        out = []
        v = E.Var(var)
        for e, _, _ in self._entries:
            if e == v or any(c == v for c in e.walk()):
                out.append(e)
        return out and tuple(out) or ()

    # -- construction -------------------------------------------------------

    @staticmethod
    def empty() -> "RecordHeader":
        return RecordHeader()

    def with_expr(self, expr: E.Expr, cypher_type: CypherType,
                  column: Optional[str] = None) -> "RecordHeader":
        if expr in self._by_expr:
            return self
        if column is None:
            column = column_name_for(expr, self.entity_vars_guess(expr))
        return RecordHeader(self._entries + ((expr, column, cypher_type),))

    def entity_vars_guess(self, expr: E.Expr) -> Tuple[str, ...]:
        """Entity vars for naming purposes: current entities plus the var in
        ``expr`` if the expression itself declares entity structure."""
        names = set(self.entity_vars)
        if isinstance(expr, (E.Id, E.HasLabel, E.StartNode, E.EndNode, E.Type,
                             E.Property)):
            child = expr.children[0]
            if isinstance(child, E.Var):
                names.add(child.name)
        return tuple(names)

    def concat(self, other: "RecordHeader") -> "RecordHeader":
        """Disjoint union of two headers (the reference's ``++``)."""
        overlap = set(self._by_expr) & set(other._by_expr)
        if overlap:
            raise HeaderError(f"headers overlap on {overlap}")
        col_overlap = set(self.columns) & set(other.columns)
        if col_overlap:
            raise HeaderError(f"headers share columns {col_overlap}")
        return RecordHeader(self._entries + other._entries)

    def select(self, exprs: Iterable[E.Expr]) -> "RecordHeader":
        keep = []
        for e in exprs:
            if e not in self._by_expr:
                raise HeaderError(f"cannot select {e!r}: not in header")
            c, t = self._by_expr[e]
            keep.append((e, c, t))
        return RecordHeader(keep)

    def select_vars(self, names: Iterable[str]) -> "RecordHeader":
        """Keep every expression owned by the given vars, in header order."""
        names = set(names)
        keep = []
        for e, c, t in self._entries:
            evs = {v.name for v in E.vars_in(e)}
            if evs and evs <= names:
                keep.append((e, c, t))
        return RecordHeader(keep)

    def rename_var(self, old: str, new: str,
                   new_type: Optional[CypherType] = None) -> "RecordHeader":
        """Alias an entity/value var: rewrite owned expressions and rename
        their columns with the new prefix."""
        entries = []
        ov = E.Var(old)
        for e, c, t in self._entries:
            if ov in e.walk() or e == ov:
                ne = e.transform_down(lambda n: E.Var(new) if n == ov else n)
                if c == old:
                    nc = new
                elif c.startswith(f"{old}__"):
                    nc = f"{new}__" + c[len(old) + 2:]
                else:
                    nc = c
                nt = new_type if new_type is not None and e == ov else t
                entries.append((ne, nc, nt))
            else:
                entries.append((e, c, t))
        return RecordHeader(entries)

    # -- entity header builders --------------------------------------------

    @staticmethod
    def for_node(var: str, schema: Schema, labels: Iterable[str] = (),
                 nullable: bool = False) -> "RecordHeader":
        labels = frozenset(labels)
        combos = schema.combinations_for(labels)
        all_labels = sorted(set().union(*combos) if combos else labels)
        props = schema.node_property_keys(labels)
        v = E.Var(var)
        node_t: CypherType = CTNode(labels)
        if nullable:
            node_t = node_t.nullable
        entries: List[Tuple[E.Expr, str, CypherType]] = [
            (v, f"{var}__id", node_t)]
        for lbl in all_labels:
            entries.append((E.HasLabel(v, lbl), f"{var}__label_{lbl}",
                            CTBoolean.nullable if nullable else CTBoolean))
        for key in sorted(props):
            t = props[key].nullable if nullable else props[key]
            entries.append((E.Property(v, key), f"{var}__prop_{key}", t))
        return RecordHeader(entries)

    @staticmethod
    def for_relationship(var: str, schema: Schema,
                         rel_types: Iterable[str] = (),
                         nullable: bool = False) -> "RecordHeader":
        rel_types = frozenset(rel_types)
        effective = rel_types or schema.relationship_types
        props = schema.relationship_property_keys(rel_types)
        v = E.Var(var)
        rel_t: CypherType = CTRelationship(effective)
        int_t: CypherType = CTInteger
        str_t: CypherType = CTString
        if nullable:
            rel_t, int_t, str_t = rel_t.nullable, CTInteger.nullable, CTString.nullable
        entries: List[Tuple[E.Expr, str, CypherType]] = [
            (v, f"{var}__id", rel_t),
            (E.StartNode(v), f"{var}__src", int_t),
            (E.EndNode(v), f"{var}__tgt", int_t),
            (E.Type(v), f"{var}__type", str_t),
        ]
        for key in sorted(props):
            t = props[key].nullable if nullable else props[key]
            entries.append((E.Property(v, key), f"{var}__prop_{key}", t))
        return RecordHeader(entries)

    @staticmethod
    def for_value(var: str, cypher_type: CypherType) -> "RecordHeader":
        return RecordHeader([(E.Var(var), var, cypher_type)])

    # -- alignment (for unions) --------------------------------------------

    def union_target(self, other: "RecordHeader") -> "RecordHeader":
        """Header covering both inputs: union of expressions; types join;
        expressions present on one side only become nullable."""
        entries: List[Tuple[E.Expr, str, CypherType]] = []
        seen = set()
        for e, c, t in self._entries:
            if e in other._by_expr:
                _, t2 = other._by_expr[e]
                entries.append((e, c, t.join(t2)))
            else:
                entries.append((e, c, t.nullable))
            seen.add(e)
        for e, c, t in other._entries:
            if e in seen:
                continue
            entries.append((e, c, t.nullable))
        return RecordHeader(entries)

    # -- dunder -------------------------------------------------------------

    def __eq__(self, other):
        return isinstance(other, RecordHeader) and self._entries == other._entries

    def __hash__(self):
        return hash(self._entries)

    def __repr__(self):
        inner = ", ".join(f"{e.cypher_repr()}->{c}" for e, c, _ in self._entries)
        return f"RecordHeader({inner})"

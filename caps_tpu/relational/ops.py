"""Relational operators: each lazily defines ``header`` + ``table``.

Mirrors the reference's ``RelationalOperator[T]`` family — Start, Scan,
Filter, Select, Project/Add, Aggregate, Join, Distinct, OrderBy, Skip,
Limit, TabularUnionAll — where every operator defines a lazy ``header:
RecordHeader`` and ``table: T`` evaluated through the Table SPI (ref:
okapi-relational/.../relational/impl/operators/ — reconstructed, mount
empty; SURVEY.md §2 "Relational planner", §3.1).
"""
from __future__ import annotations

import abc
import dataclasses
import itertools
from contextlib import nullcontext
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from caps_tpu.obs import clock

try:  # profiling is optional — this layer stays backend-agnostic
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:  # pragma: no cover
    _TraceAnnotation = None

from caps_tpu.ir import exprs as E
from caps_tpu.okapi.types import (
    CTBoolean, CTInteger, CTList, CTNode, CTRelationship, CypherType,
    _CTNode, _CTRelationship,
)
from caps_tpu.relational.header import HeaderError, RecordHeader
from caps_tpu.relational.table import AggSpec, Table
from caps_tpu.serve.deadline import checkpoint as _cancel_checkpoint
from caps_tpu.serve.errors import CancellationError as _CancellationError


ENTITY_CTX_PARAM = "__entity_ctx__"
"""Reserved parameter key carrying the :class:`EntityContext` to the
expression evaluators (popped before query-parameter lookup, excluded
from fused-executor cache keys)."""


class EntityContext:
    """Host-side entity rehydration for expression evaluation: property /
    label access on entity values flowing through list expressions, and
    node-sequence reconstruction for var-length named paths.  One context
    per planned graph — operators snapshot the context current at THEIR
    planning time, so multi-graph queries (FROM GRAPH / UNION branches)
    rehydrate against the graph they actually matched.  Lookups build
    lazily so queries that never touch entity values pay nothing."""

    def __init__(self, graph):
        self._graph = graph
        self._nodes: Optional[Dict] = None
        self._rels: Optional[Dict] = None

    def node(self, nid) -> Optional[Tuple[Tuple[str, ...], Dict[str, Any]]]:
        if self._nodes is None:
            g = self._graph
            self._nodes = g.node_lookup() if g is not None else {}
        return self._nodes.get(nid)

    def rel(self, rid) -> Optional[Tuple[int, int, str, Dict[str, Any]]]:
        if self._rels is None:
            g = self._graph
            self._rels = g.rel_lookup() if g is not None else {}
        return self._rels.get(rid)


class RelationalRuntimeContext:
    """Per-query context: parameters, session, catalog view (ref:
    ``RelationalRuntimeContext`` — SURVEY.md §2).

    Parameter VALUES are late-bound: every operator reads
    ``context.parameters`` inside ``_compute`` (filters, projections,
    SKIP/LIMIT counts, percentile args), never at plan-construction time.
    That contract is what lets the session plan cache
    (relational/plan_cache.py) re-execute one planned operator tree for
    every binding of the same parameter signature — new plan-time value
    reads must go through the PlanParams view instead so they are
    recorded in the cache key."""

    def __init__(self, session, parameters: Optional[Mapping[str, Any]] = None):
        self.session = session
        self.parameters: Dict[str, Any] = dict(parameters or {})
        # per-operator wall-clock + row counts, filled as ops evaluate
        # (SURVEY.md §5.1 — the structured analog of the Spark UI stage view)
        self.op_metrics: List[Dict[str, Any]] = []
        # the session tracer, cached so the per-operator hot path pays
        # one attribute read (None for bare/mock sessions in tests)
        self.tracer = getattr(session, "tracer", None)
        # plan-node id sequence: operators draw a stable id at
        # CONSTRUCTION (planner order is deterministic per query), so
        # the observed-statistics store (obs/telemetry.py OpStatsStore)
        # can key measurements by (plan family, operator id) across
        # executions, replans, and fused replays
        self.op_seq = itertools.count()

    def rebind(self, parameters: Mapping[str, Any]) -> None:
        """Swap in fresh parameter bindings for a cached-plan
        re-execution: operators hold a reference to THIS context, so an
        in-place update reaches every ``_compute``; per-run operator
        metrics start fresh (the previous run's list stays owned by the
        result that captured it)."""
        self.parameters.clear()
        self.parameters.update(parameters)
        self.op_metrics = []

    @property
    def factory(self):
        return self.session.table_factory


def resolve_expr(expr: E.Expr, header: RecordHeader) -> E.Expr:
    """Normalize an expression against a header so backends only ever see
    resolvable expressions:

      * ``HasLabel`` on a var whose header lacks that label column → false
        (the label cannot occur there);
      * ``HasType(r, T)`` → ``Type(r) = 'T'``;
      * ``Property`` on an entity var whose header lacks the column → null.

    The walk is scope-aware: a comprehension / quantifier / reduce variable
    that shadows a header entity var must NOT have its property reads
    rewritten against the outer header."""
    entity_vars = set(header.entity_vars)

    def rw(n: E.Expr, bound: frozenset) -> E.Expr:
        if isinstance(n, E.ListComprehension):
            inner = bound | {n.var}
            return dataclasses.replace(
                n, list_expr=rw(n.list_expr, bound),
                predicate=(rw(n.predicate, inner)
                           if n.predicate is not None else None),
                projection=(rw(n.projection, inner)
                            if n.projection is not None else None))
        if isinstance(n, E.QuantifiedPredicate):
            return dataclasses.replace(
                n, list_expr=rw(n.list_expr, bound),
                predicate=rw(n.predicate, bound | {n.var}))
        if isinstance(n, E.Reduce):
            return dataclasses.replace(
                n, init=rw(n.init, bound),
                list_expr=rw(n.list_expr, bound),
                expr=rw(n.expr, bound | {n.acc, n.var}))
        n = n.map_children(lambda c: rw(c, bound))
        if isinstance(n, E.HasLabel) and isinstance(n.node, E.Var) \
                and n.node.name not in bound \
                and n.node.name in entity_vars and not header.has(n):
            return E.Lit(False)
        if isinstance(n, E.HasType) and isinstance(n.rel, E.Var) \
                and n.rel.name not in bound:
            return E.Equals(E.Type(n.rel), E.Lit(n.rel_type))
        if isinstance(n, E.Property) and isinstance(n.entity, E.Var) \
                and n.entity.name not in bound \
                and n.entity.name in entity_vars and not header.has(n):
            return E.Lit(None)
        return n

    return rw(expr, frozenset())


def host_eval(expr: E.Expr, parameters: Mapping[str, Any]) -> Any:
    """Evaluate a driver-side expression (SKIP/LIMIT counts etc.)."""
    if isinstance(expr, E.Lit):
        return expr.value
    if isinstance(expr, E.Param):
        if expr.name not in parameters:
            raise KeyError(f"missing parameter ${expr.name}")
        return parameters[expr.name]
    if isinstance(expr, E.Negate):
        return -host_eval(expr.expr, parameters)
    raise ValueError(f"expression {expr!r} must be a literal or parameter")


class RelationalOperator(abc.ABC):
    """Base: caches the computed (header, table) pair."""

    def __init__(self, context: RelationalRuntimeContext,
                 children: Sequence["RelationalOperator"] = ()):
        self.context = context
        self.children = tuple(children)
        self._result: Optional[Tuple[RecordHeader, Table]] = None
        # snapshot of the planner's graph-scoped entity context at THIS
        # op's planning time (multi-graph correctness — see EntityContext)
        self.entity_ctx: Optional[EntityContext] = getattr(
            context, "entity_ctx", None)
        # stable per-plan node id (observed-statistics key; -1 under bare
        # mock contexts in unit tests)
        seq = getattr(context, "op_seq", None)
        self.op_id: int = next(seq) if seq is not None else -1

    @property
    def parameters(self) -> Dict[str, Any]:
        """Query parameters plus this op's entity-context snapshot under
        the reserved key (backends pop it before parameter lookup)."""
        if self.entity_ctx is None:
            return self.context.parameters
        p = dict(self.context.parameters)
        p[ENTITY_CTX_PARAM] = self.entity_ctx
        return p

    @abc.abstractmethod
    def _compute(self) -> Tuple[RecordHeader, Table]:
        ...

    @property
    def result(self) -> Tuple[RecordHeader, Table]:
        if self._result is None:
            # Cooperative cancel/deadline boundary (serve/deadline.py):
            # a served request with an expired budget stops HERE, before
            # the next operator computes — one thread-local read when no
            # scope is installed.
            _cancel_checkpoint("execute")
            name = type(self).__name__.removesuffix("Op")
            tracer = self.context.tracer
            tr_span = (tracer.span(f"op.{name}", kind="operator")
                       if tracer is not None and tracer.enabled
                       else nullcontext())
            t0 = clock.now()
            device_s: Optional[float] = None
            with tr_span as sp:
                xla_span = (_TraceAnnotation(f"caps_tpu.{name}")
                            if _TraceAnnotation is not None else nullcontext())
                with xla_span:
                    try:
                        self._result = self._compute()
                    except _CancellationError:
                        raise  # budget expiry, not an operator failure
                    except Exception as ex:
                        # only the op that ACTUALLY failed reports; the
                        # ancestors it unwinds through (parents evaluate
                        # children lazily inside their own _compute)
                        # must not re-count it
                        if getattr(ex, "caps_failed_op", None) is None:
                            self._propagate_error(ex, name, tracer)
                        raise
                if tracer is not None and tracer.enabled \
                        and tracer.sync_device:
                    # PROFILE per-op device mode: wait for the dispatched
                    # work so this span's wall time is the real
                    # post-block_until_ready delta, then record the
                    # device-inclusive duration explicitly
                    self._result[1].device_sync()
                    device_s = clock.now() - t0
            try:  # bytes pulled through memory by this operator: the
                # roofline numerator (SURVEY.md §5.5).  Only children the
                # op actually evaluated count — summing `c.table` blindly
                # would FORCE lazy children (e.g. the count-pushdown's
                # fallback join plan) just for accounting.
                evaluated = [c for c in self.children
                             if c._result is not None]
                if evaluated:
                    bytes_in = sum(c.table.nbytes for c in evaluated)
                elif self.children:
                    bytes_in = 0  # pushdown path: children never ran
                else:
                    bytes_in = self._result[1].nbytes
            except Exception:  # pragma: no cover — accounting must not fail
                bytes_in = 0
            if device_s is not None:
                # PROFILE per-op mode: exact cardinality, not a served
                # bound (free in eager/exact-replay mode; one counted
                # sync per op under generic replay — a diagnostic run
                # may pay for honest numbers, never report wrong ones)
                try:
                    rows = self._result[1].exact_size()
                except Exception:
                    rows = self._result[1].size
            else:
                rows = self._result[1].size
            entry = {
                "op": name,
                "op_id": self.op_id,
                "seconds": clock.now() - t0,
                "rows": rows,
                "bytes_in": bytes_in,
                **getattr(self, "_metric_extra", {}),
            }
            if device_s is not None:
                entry["device_s"] = device_s
            # cost-model estimate (relational/cost.py annotate_plan):
            # ride the entry so the observed-statistics store measures
            # MODEL error, not drift from its own running mean
            est = getattr(self, "est_rows", None)
            if est is not None:
                entry["est_rows"] = int(est)
            self.context.op_metrics.append(entry)
            # run-stamped measurement for PROFILE (obs/profile.py): the
            # op_metrics LIST identity tags which run the entry belongs
            # to — rebind() swaps in a fresh list, so stale stamps from
            # an earlier cached-plan execution are detectable.
            self._last_metrics = (self.context.op_metrics, entry)
            if sp is not None:  # nullcontext (tracing disabled) yields None
                sp.annotate(rows=entry["rows"], bytes=bytes_in,
                            device_s=device_s)
        return self._result

    def _propagate_error(self, ex: Exception, name: str, tracer) -> None:
        """Failure-containment telemetry for one operator failure
        (caps_tpu/serve/failure.py consumes it): an ``op.error`` trace
        event, an ``ops.errors`` counter tick, and the failing operator
        stamped on the exception.  The caller gates on the stamp being
        absent, so the whole report fires exactly once per failure —
        at the operator that raised, not at every ancestor it unwound
        through (and a badly-written injector sharing one exception
        across requests keeps its first, accurate stamp)."""
        try:
            if tracer is not None and tracer.enabled:
                tracer.event("op.error", kind="event", op=name,
                             error=type(ex).__name__)
            session = getattr(self.context, "session", None)
            registry = getattr(session, "metrics_registry", None)
            if registry is not None:
                registry.counter("ops.errors").inc()
            if getattr(ex, "caps_failed_op", None) is None:
                ex.caps_failed_op = name
        except Exception:  # pragma: no cover — telemetry must not mask
            pass

    @property
    def header(self) -> RecordHeader:
        return self.result[0]

    @property
    def table(self) -> Table:
        return self.result[1]

    def pretty(self, depth: int = 0) -> str:
        label = type(self).__name__.removesuffix("Op")
        extra = self._pretty_args()
        est = getattr(self, "est_rows", None)
        suffix = ""
        if est is not None:
            # estimated-vs-chosen in EXPLAIN: the cost model's row
            # estimate (src: model prior or observed calibration) and,
            # on sharded joins, the planned distribution strategy
            src = getattr(self, "est_source", "model")
            suffix = f"  ~rows={est} ({src})"
            dist = getattr(self, "dist_strategy", None)
            if dist is not None:
                suffix += f" dist={dist}"
        lines = [("    " * depth) + ("└─" if depth else "") + label
                 + (f"({extra})" if extra else "") + suffix]
        for c in self.children:
            lines.append(c.pretty(depth + 1))
        return "\n".join(lines)

    def _pretty_args(self) -> str:
        return ""


class StartOp(RelationalOperator):
    """A single empty driving row (or an externally supplied driving table)."""

    def __init__(self, context, header: Optional[RecordHeader] = None,
                 table: Optional[Table] = None):
        super().__init__(context)
        self._start_header = header or RecordHeader.empty()
        self._start_table = table

    def _compute(self):
        t = self._start_table if self._start_table is not None \
            else self.context.factory.unit()
        return self._start_header, t


class ScanOp(RelationalOperator):
    """Aligned union of entity tables for one var (ref: ``scanOperator``)."""

    def __init__(self, context, graph, var: str, entity_type: CypherType):
        super().__init__(context)
        self.graph = graph
        self.var = var
        self.entity_type = entity_type

    def _compute(self):
        # delta-aware scan: against a versioned snapshot
        # (relational/updates.py) the scan is (base minus tombstone
        # mask) ∪ delta — surface the overlay size in this op's metrics
        # so PROFILE and the op log attribute the extra work honestly
        state = getattr(self.graph, "state", None)
        if state is not None and getattr(state, "delta_rows", 0):
            self._metric_extra = {
                "delta_rows": state.delta_rows,
                "snapshot_version": self.graph.snapshot_version}
        m = self.entity_type.material
        if isinstance(m, _CTNode):
            return self.graph.scan_node(self.var, m.labels)
        if isinstance(m, _CTRelationship):
            return self.graph.scan_rel(self.var, m.rel_types)
        raise TypeError(f"cannot scan entity type {self.entity_type!r}")

    def _pretty_args(self):
        return f"{self.var}: {self.entity_type!r}"


class FilterOp(RelationalOperator):
    def __init__(self, context, parent: RelationalOperator, predicate: E.Expr):
        super().__init__(context, [parent])
        self.predicate = predicate

    def _compute(self):
        header, table = self.children[0].result
        pred = resolve_expr(self.predicate, header)
        return header, table.filter(pred, header, self.parameters)

    def _pretty_args(self):
        return self.predicate.cypher_repr()


class SelectOp(RelationalOperator):
    """Narrow to the expressions owned by the given vars."""

    def __init__(self, context, parent: RelationalOperator,
                 names: Sequence[str]):
        super().__init__(context, [parent])
        self.names = tuple(names)

    def _compute(self):
        header, table = self.children[0].result
        out_header = header.select_vars(self.names)
        return out_header, table.select(list(out_header.columns))

    def _pretty_args(self):
        return ", ".join(self.names)


class ProjectOp(RelationalOperator):
    """Add computed/aliased columns; overwriting an existing var drops its
    old columns first (computed via temporaries to avoid clobbering inputs
    still referenced by later items)."""

    def __init__(self, context, parent: RelationalOperator,
                 items: Sequence[Tuple[str, E.Expr, CypherType]]):
        super().__init__(context, [parent])
        self.items = tuple(items)

    def _compute(self):
        header, table = self.children[0].result
        params = self.parameters
        overwritten = [name for name, expr, _ in self.items
                       if name in set(header.vars) and expr != E.Var(name)]
        pending_renames: Dict[str, str] = {}
        new_entries: List[Tuple[E.Expr, str, CypherType]] = []

        for name, expr, ctype in self.items:
            target = name
            tmp_prefix = f"__new__{name}" if name in overwritten else name
            if isinstance(expr, E.Var) and expr.name in header.composite_vars:
                # entity/path alias: copy all owned columns under the new
                # prefix (paths own __start/__seg*/__node* columns)
                src = expr.name
                sub = header.select_vars([src])
                copied = set()
                for e in sub.exprs:
                    old_col = sub.column(e)
                    suffix = old_col[len(src):]  # '__id', '__prop_x', ...
                    new_col = f"{tmp_prefix}{suffix}"
                    if old_col not in copied:
                        table = table.copy_column(old_col, new_col)
                        copied.add(old_col)
                    ne = e.transform_down(
                        lambda n: E.Var(target) if n == E.Var(src) else n)
                    final_col = f"{target}{suffix}"
                    if new_col != final_col:
                        pending_renames[new_col] = final_col
                    t = ctype if e == E.Var(src) else sub.type_of(e)
                    new_entries.append((ne, final_col, t))
            elif isinstance(expr, E.PathExpr):
                # reify a named path: path-owned copies of the constituent
                # id columns — start node id + one column per hop (rel id,
                # or rel-id list for var-length segments); fixed-length
                # paths also pin per-position node ids for nodes(p)
                pv = E.Var(target)
                fixed = not any(expr.varlen)

                def path_col(src_expr, suffix, entry_expr, etype):
                    nonlocal table
                    tmp_col = f"{tmp_prefix}{suffix}"
                    table = table.copy_column(header.column(src_expr), tmp_col)
                    final_col = f"{target}{suffix}"
                    if tmp_col != final_col:
                        pending_renames[tmp_col] = final_col
                    new_entries.append((entry_expr, final_col, etype))
                    return final_col

                start_col = path_col(expr.nodes[0], "__start", pv, ctype)
                if fixed:
                    new_entries.append((E.PathNode(pv, 0), start_col,
                                        header.type_of(expr.nodes[0])))
                for i, (rexpr, vl) in enumerate(zip(expr.rels, expr.varlen)):
                    path_col(rexpr, f"__seg{i}", E.PathSeg(pv, i, vl),
                             header.type_of(rexpr))
                if fixed:
                    for i, nexpr in enumerate(expr.nodes[1:], start=1):
                        path_col(nexpr, f"__node{i}", E.PathNode(pv, i),
                                 header.type_of(nexpr))
            else:
                resolved = resolve_expr(expr, header)
                if isinstance(resolved, E.Var) and resolved.name in header.vars:
                    table = table.copy_column(header.column(resolved), tmp_prefix)
                else:
                    table = table.with_column(tmp_prefix, resolved, header,
                                              params, ctype)
                if tmp_prefix != target:
                    pending_renames[tmp_prefix] = target
                new_entries.append((E.Var(target), target, ctype))

        if overwritten:
            drop_cols = set()
            keep_entries = []
            for e, c, t in zip(header.exprs, (header.column(x) for x in header.exprs),
                               (header.type_of(x) for x in header.exprs)):
                owners = {v.name for v in E.vars_in(e)}
                if owners & set(overwritten):
                    drop_cols.add(c)
                else:
                    keep_entries.append((e, c, t))
            keep_cols = [c for c in table.columns
                         if c not in drop_cols]
            table = table.select(keep_cols)
            if pending_renames:
                table = table.rename(pending_renames)
            base_entries = keep_entries
        else:
            base_entries = [(e, header.column(e), header.type_of(e))
                            for e in header.exprs]
        out_entries = base_entries + [
            (e, c, t) for e, c, t in new_entries
            if all(e != be[0] for be in base_entries)]
        return RecordHeader(out_entries), table

    def _pretty_args(self):
        return ", ".join(f"{e.cypher_repr()} AS {n}" for n, e, _ in self.items)


class JoinOp(RelationalOperator):
    def __init__(self, context, lhs: RelationalOperator, rhs: RelationalOperator,
                 pairs: Sequence[Tuple[E.Expr, E.Expr]], how: str = "inner"):
        super().__init__(context, [lhs, rhs])
        self.pairs = tuple(pairs)
        self.how = how

    def _compute(self):
        lh, lt = self.children[0].result
        rh, rt = self.children[1].result
        col_pairs = [(lh.column(le), rh.column(re)) for le, re in self.pairs]
        out_header = lh.concat(rh)
        return out_header, lt.join(rt, self.how, col_pairs)

    def _pretty_args(self):
        conds = ", ".join(f"{l.cypher_repr()}={r.cypher_repr()}"
                          for l, r in self.pairs)
        return f"{self.how}: {conds}"


class CrossOp(RelationalOperator):
    def __init__(self, context, lhs, rhs):
        super().__init__(context, [lhs, rhs])

    def _compute(self):
        lh, lt = self.children[0].result
        rh, rt = self.children[1].result
        return lh.concat(rh), lt.join(rt, "cross", [])


class UnionAllOp(RelationalOperator):
    def __init__(self, context, lhs, rhs):
        super().__init__(context, [lhs, rhs])

    def _compute(self):
        lh, lt = self.children[0].result
        rh, rt = self.children[1].result
        target = lh.union_target(rh)

        def align(h: RecordHeader, t: Table) -> Table:
            for e in target.exprs:
                col = target.column(e)
                if col not in t.columns:
                    default = False if isinstance(e, E.HasLabel) else None
                    t = t.with_literal_column(col, default, target.type_of(e))
            return t.select(list(target.columns))

        return target, align(lh, lt).union_all(align(rh, rt))


class ExistsJoinOp(RelationalOperator):
    """Row-id semi-join implementing EXISTS subqueries: lhs (tagged with a
    row index) keeps every row exactly once; the nullable boolean
    ``marker`` var is true where the subquery side produced at least one
    row for that row id, null otherwise (ref: okapi-relational planning of
    ExistsSubQuery — reconstructed; SURVEY.md §2)."""

    def __init__(self, context, lhs_tagged: RelationalOperator,
                 rhs: RelationalOperator, rid_col: str, marker: str):
        super().__init__(context, [lhs_tagged, rhs])
        self.rid_col = rid_col
        self.marker = marker

    def _compute(self):
        lh, lt = self.children[0].result
        rh, rt = self.children[1].result
        mcol = rh.column(E.Var(self.marker))
        rid_right = f"__ex_{self.rid_col}"
        rsel = rt.select([self.rid_col, mcol]).distinct() \
            .rename({self.rid_col: rid_right})
        joined = lt.join(rsel, "left", [(self.rid_col, rid_right)])
        out_entries = [(e, lh.column(e), lh.type_of(e)) for e in lh.exprs
                       if e != E.Var(self.rid_col)] \
            + [(E.Var(self.marker), mcol, CTBoolean.nullable)]
        out_header = RecordHeader(out_entries)
        return out_header, joined.select(list(out_header.columns))

    def _pretty_args(self):
        return self.marker


class DistinctOp(RelationalOperator):
    def __init__(self, context, parent):
        super().__init__(context, [parent])

    def _compute(self):
        header, table = self.children[0].result
        return header, table.distinct()


class AggregateOp(RelationalOperator):
    _KINDS = {
        E.Count: "count", E.Sum: "sum", E.Avg: "avg", E.Min: "min",
        E.Max: "max", E.Collect: "collect", E.StDev: "stdev",
        E.PercentileCont: "percentile_cont", E.PercentileDisc: "percentile_disc",
    }

    def __init__(self, context, parent,
                 group: Sequence[Tuple[str, E.Expr, CypherType]],
                 aggregations: Sequence[Tuple[str, E.Aggregator, CypherType]]):
        super().__init__(context, [parent])
        self.group = tuple(group)
        self.aggregations = tuple(aggregations)

    def _compute(self):
        header, table = self.children[0].result
        params = self.parameters

        by_cols: List[str] = []
        out_entries: List[Tuple[E.Expr, str, CypherType]] = []
        first_specs: List[AggSpec] = []
        renames: Dict[str, str] = {}

        for name, expr, ctype in self.group:
            if isinstance(expr, E.Var) and expr.name in header.entity_vars:
                src = expr.name
                sub = header.select_vars([src])
                id_col = sub.column(E.Var(src))
                by_cols.append(id_col)
                for e in sub.exprs:
                    old_col = sub.column(e)
                    suffix = old_col[len(src):]
                    new_col = f"{name}{suffix}"
                    ne = e.transform_down(
                        lambda n: E.Var(name) if n == E.Var(src) else n)
                    t = ctype if e == E.Var(src) else sub.type_of(e)
                    if old_col == id_col:
                        renames[old_col] = new_col
                    else:
                        first_specs.append(AggSpec(new_col, "first", old_col,
                                                   result_type=t))
                    out_entries.append((ne, new_col, t))
            elif isinstance(expr, E.Var) and expr.name in header.composite_vars:
                # path var: path identity = the full column tuple (start id
                # + every hop id column), so group by all of them
                src = expr.name
                sub = header.select_vars([src])
                for e in sub.exprs:
                    old_col = sub.column(e)
                    suffix = old_col[len(src):]
                    new_col = f"{name}{suffix}"
                    ne = e.transform_down(
                        lambda n: E.Var(name) if n == E.Var(src) else n)
                    t = ctype if e == E.Var(src) else sub.type_of(e)
                    if old_col not in by_cols:
                        by_cols.append(old_col)
                        renames[old_col] = new_col
                    out_entries.append((ne, new_col, t))
            else:
                resolved = resolve_expr(expr, header)
                col = f"__group__{name}"
                table = table.with_column(col, resolved, header, params, ctype)
                by_cols.append(col)
                renames[col] = name
                out_entries.append((E.Var(name), name, ctype))

        agg_specs: List[AggSpec] = []
        for name, agg, ctype in self.aggregations:
            if isinstance(agg, E.CountStar):
                agg_specs.append(AggSpec(name, "count_star", result_type=ctype))
                out_entries.append((E.Var(name), name, ctype))
                continue
            inner = resolve_expr(agg.expr, header)
            in_col = f"__agg_in__{name}"
            in_type = header.type_of(inner) if header.has(inner) else ctype
            table = table.with_column(in_col, inner, header, params, in_type)
            kind = self._KINDS[type(agg)]
            distinct = bool(getattr(agg, "distinct", False))
            pct = None
            if isinstance(agg, (E.PercentileCont, E.PercentileDisc)):
                pct = host_eval(agg.percentile, params)
            agg_specs.append(AggSpec(name, kind, in_col, distinct, pct, ctype))
            out_entries.append((E.Var(name), name, ctype))

        grouped = table.group(by_cols, tuple(first_specs) + tuple(agg_specs))
        if renames:
            grouped = grouped.rename(renames)
        out_header = RecordHeader(out_entries)
        return out_header, grouped.select(list(out_header.columns))

    def _pretty_args(self):
        g = ", ".join(n for n, _, _ in self.group)
        a = ", ".join(f"{agg.cypher_repr()} AS {n}" for n, agg, _ in self.aggregations)
        return f"group=[{g}] aggs=[{a}]"


class OrderByOp(RelationalOperator):
    def __init__(self, context, parent, items: Sequence[Tuple[E.Expr, bool]]):
        super().__init__(context, [parent])
        self.items = tuple(items)

    def _compute(self):
        header, table = self.children[0].result
        params = self.parameters
        sort_cols: List[Tuple[str, bool]] = []
        temp_cols: List[str] = []
        for i, (expr, asc) in enumerate(self.items):
            resolved = resolve_expr(expr, header)
            if header.has(resolved):
                sort_cols.append((header.column(resolved), asc))
            else:
                col = f"__sort__{i}"
                from caps_tpu.okapi.types import CTAny
                table = table.with_column(col, resolved, header, params, CTAny)
                temp_cols.append(col)
                sort_cols.append((col, asc))
        table = table.order_by(sort_cols)
        if temp_cols:
            table = table.select([c for c in table.columns if c not in temp_cols])
        return header, table


def _slice_count(expr: E.Expr, parameters, what: str) -> int:
    """SKIP/LIMIT operand: openCypher requires a non-negative integer
    (negative literals are a SyntaxError upstream; parameters make it a
    runtime check here)."""
    n = int(host_eval(expr, parameters))
    if n < 0:
        raise ValueError(f"{what} must be a non-negative integer, got {n}")
    return n


class SkipOp(RelationalOperator):
    def __init__(self, context, parent, expr: E.Expr):
        super().__init__(context, [parent])
        self.expr = expr

    def _compute(self):
        header, table = self.children[0].result
        return header, table.skip(
            _slice_count(self.expr, self.context.parameters, "SKIP"))


class LimitOp(RelationalOperator):
    def __init__(self, context, parent, expr: E.Expr):
        super().__init__(context, [parent])
        self.expr = expr

    def _compute(self):
        header, table = self.children[0].result
        return header, table.limit(
            _slice_count(self.expr, self.context.parameters, "LIMIT"))


class UnwindOp(RelationalOperator):
    def __init__(self, context, parent, list_expr: E.Expr, var: str,
                 inner_type: CypherType):
        super().__init__(context, [parent])
        self.list_expr = list_expr
        self.var = var
        self.inner_type = inner_type

    def _compute(self):
        header, table = self.children[0].result
        params = self.parameters
        resolved = resolve_expr(self.list_expr, header)
        tmp = f"__unwind__{self.var}"
        from caps_tpu.okapi.types import CTAny, CTList
        table = table.with_column(tmp, resolved, header, params,
                                  CTList(self.inner_type))
        table = table.explode(tmp, self.var, self.inner_type)
        out_header = header.with_expr(E.Var(self.var), self.inner_type,
                                      column=self.var)
        return out_header, table.select(list(out_header.columns))


class RowIndexOp(RelationalOperator):
    def __init__(self, context, parent, col: str):
        super().__init__(context, [parent])
        self.col = col

    def _compute(self):
        header, table = self.children[0].result
        out = header.with_expr(E.Var(self.col), CTInteger, column=self.col)
        return out, table.with_row_index(self.col)


class OptionalJoinOp(RelationalOperator):
    """Left outer join of lhs (tagged with a row index) against the planned
    optional side, implementing OPTIONAL MATCH."""

    def __init__(self, context, lhs_tagged: RelationalOperator,
                 rhs: RelationalOperator, rid_col: str):
        super().__init__(context, [lhs_tagged, rhs])
        self.rid_col = rid_col

    def _compute(self):
        lh, lt = self.children[0].result
        rh, rt = self.children[1].result
        lhs_cols = set(lt.columns)
        # Right side: row id + columns new in rhs.
        new_entries = [(e, rh.column(e), rh.type_of(e).nullable)
                       for e in rh.exprs
                       if not lh.has(e) and e != E.Var(self.rid_col)]
        if self.rid_col not in rt.columns:
            # The optional pattern shares no variable with the lhs (e.g. a
            # leading OPTIONAL MATCH over the unit driving row), so it
            # never consumed the tagged rows: OPTIONAL MATCH then pairs
            # every lhs row with every rhs row, or null-pads when the
            # pattern found nothing (openCypher).
            out_header = RecordHeader(
                [(e, lh.column(e), lh.type_of(e)) for e in lh.exprs
                 if e != E.Var(self.rid_col)] + new_entries)
            new_cols = [c for _, c, _ in new_entries if c not in lhs_cols]
            if rt.branch_empty():
                out = lt
                for e, c, t in new_entries:
                    if c not in lhs_cols:
                        out = out.with_literal_column(c, None, t)
            else:
                out = lt.join(rt.select(list(dict.fromkeys(new_cols))),
                              "cross", [])
            keep = [c for c in out.columns if c != self.rid_col]
            return out_header, out.select(keep).select(
                list(out_header.columns))
        rid_right = f"__opt_{self.rid_col}"
        sel_cols = [self.rid_col] + [c for _, c, _ in new_entries
                                     if c not in lhs_cols]
        rsel = rt.select(list(dict.fromkeys(sel_cols)))
        rsel = rsel.rename({self.rid_col: rid_right})
        joined = lt.join(rsel, "left", [(self.rid_col, rid_right)])
        # Drop the row-id bookkeeping columns.
        keep = [c for c in joined.columns if c not in (self.rid_col, rid_right)]
        out_entries = [(e, lh.column(e), lh.type_of(e)) for e in lh.exprs
                       if e != E.Var(self.rid_col)] + new_entries
        out_header = RecordHeader(out_entries)
        return out_header, joined.select(keep).select(list(out_header.columns))

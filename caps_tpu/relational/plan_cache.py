"""Prepared statements and the session-level LRU plan cache.

Every ``RelationalCypherSession.cypher()`` call used to re-run the whole
scalar frontend (parse → IRBuilder → LogicalPlanner → LogicalOptimizer →
RelationalPlanner) even for identical query text.  Execution is
tensorized and compile-cached (jitted kernels, the fused size-replay
executor), so for the canonical serving shape — the SAME parameterized
query with rotating bindings — planning was the last un-amortized hot
path (the path-selection cost "Premature Dimensional Collapse ..."
identifies for tensorized execution; PAPERS.md).

This module caches the *planned relational operator tree* and re-executes
it with fresh parameter bindings:

* the cache key is value-independent: (normalized query text, graph plan
  token, parameter *signature* — names + coarse types, never values);
  catalog consistency rides on per-plan dependency tokens, revalidated
  at lookup (scoped invalidation instead of a global fingerprint);
* parameter VALUES are late-bound: relational operators read
  ``context.parameters`` inside ``_compute`` (SKIP/LIMIT counts,
  predicate params, percentile args all evaluate at execution time), so
  one cached plan serves every binding;
* where planning genuinely DID read a value (:class:`PlanParams` records
  every such read — e.g. the key set of a map parameter used as pattern
  properties), the cached entry is additionally keyed by that value
  aspect, so specialized plans are re-planned rather than served stale;
* ``CATALOG CREATE/DROP GRAPH`` (and any catalog mutation) bumps the
  mutated NAME's dep token — its dependents can never be served (the
  lookup revalidation drops them), the session's catalog subscription
  evicts them eagerly, and every unrelated graph's plans survive.

Executing a cached plan = clear each operator's memoized ``(header,
table)`` pair, swap the shared runtime context's parameter dict, and pull
``root.result`` again.  Operator trees hold no per-run state beyond that
memo (results are captured by the returned records object), so between
executions a cached plan retains no tables or device buffers.

Concurrency (the serving tier, ``caps_tpu/serve/``): the cache's LRU
dict is guarded by one lock, and each :class:`CachedPlan` carries its
own ``exec_lock`` — two threads that hit the SAME entry take turns
re-binding/executing its shared operator tree, while different entries
execute independently.
"""
from __future__ import annotations

import dataclasses
import itertools
import threading
from collections import OrderedDict
from typing import Any, Dict, Iterator, List, Mapping, Optional, Tuple

from caps_tpu.obs.lockgraph import make_lock, make_rlock
from caps_tpu.okapi.types import from_python

_plan_tokens = itertools.count(1)
_plan_token_lock = make_lock("plan_cache._plan_token_lock")


def graph_plan_token(graph) -> Optional[int]:
    """A stable identity for a graph object, stamped on first use
    (``id()`` alone can be reused after gc — same technique as the fused
    executor's graph epoch).  None = this graph cannot anchor a cache
    entry.  The first-use stamp is locked: concurrent serving threads
    submitting against a fresh graph must agree on ONE token, or their
    cache keys (and micro-batch keys) silently diverge.

    A ``plan_token_unstable`` marker (the VersionedGraph handle —
    relational/updates.py) refuses a token outright: the object's DATA
    changes across commits, so a stable token would serve stale plans.
    Readers anchor on the immutable per-version snapshots instead."""
    if getattr(graph, "plan_token_unstable", False):
        return None
    tok = getattr(graph, "_plan_token", None)
    if tok is None:
        with _plan_token_lock:
            tok = getattr(graph, "_plan_token", None)
            if tok is not None:
                return tok
            tok = next(_plan_tokens)
            try:
                graph._plan_token = tok
            except Exception:
                return None
    return tok


def _coarse_type_token(value: Any) -> str:
    """Names + coarse types form the parameter signature: the planner
    only ever consumes a parameter's *type* (SchemaTyper), so plans are
    shared across values of the same shape."""
    try:
        return repr(from_python(value))
    except Exception:
        return f"?{type(value).__name__}"


def param_signature(params: Mapping[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, _coarse_type_token(v)) for k, v in params.items()))


def _value_token(v: Any) -> Optional[str]:
    """A token that fully identifies a parameter VALUE, or None when no
    faithful token exists.  Only plain primitives and containers of them
    qualify: an arbitrary type's ``repr`` may be content-free or
    truncated (numpy arrays elide elements past a threshold), and a
    collided token would serve a stale value-specialized plan — refuse
    caching instead."""
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return repr(v)
    if isinstance(v, (list, tuple)):
        parts = [_value_token(x) for x in v]
        if any(p is None for p in parts):
            return None
        return f"[{','.join(parts)}]"
    if isinstance(v, (set, frozenset)):
        parts = [_value_token(x) for x in v]
        if any(p is None for p in parts):
            return None
        return f"{{{','.join(sorted(parts))}}}"
    if isinstance(v, dict):
        items = []
        for k, x in v.items():
            kt, xt = _value_token(k), _value_token(x)
            if kt is None or xt is None:
                return None
            items.append(f"{kt}:{xt}")
        return f"{{{','.join(sorted(items))}}}"
    return None


class PlanParams(Mapping):
    """The parameter view handed to the PLANNING phases (IRBuilder /
    LogicalPlanner / SchemaTyper).  It records every read that makes the
    resulting plan depend on a parameter *value* — such reads become
    extra cache-key components (specializations) so a value-specialized
    plan is never served for a different value.

    Reads that only consume the coarse type (:meth:`coarse_type`) record
    nothing: the type is already part of the cache key's parameter
    signature.  :meth:`map_keys` records only the KEY SET of a map
    parameter (pattern-property expansion depends on the keys, not the
    values).  Any other value access (``get``/``[]``/iteration) records
    the full value — sound for any future plan-time read, at the cost of
    value-keying that plan."""

    def __init__(self, params: Mapping[str, Any]):
        self._params = dict(params)
        # ordered, deduped (kind, name) -> token
        self.specializations: "OrderedDict[Tuple[str, str], Any]" = \
            OrderedDict()
        self.cacheable = True

    # -- plan-time accessors -------------------------------------------

    def coarse_type(self, name: str):
        """The parameter's coarse Cypher type (None when unbound).  Not a
        specialization: the signature already keys on it."""
        if name not in self._params:
            return None
        return from_python(self._params[name])

    def map_keys(self, name: str) -> Optional[Tuple[str, ...]]:
        """Sorted key tuple of a map-valued parameter (None otherwise).
        Records a key-set specialization: two bindings with different
        keys plan differently, same keys with different values share the
        plan."""
        v = self._params.get(name)
        keys = tuple(sorted(v)) if isinstance(v, dict) else None
        self._record("mapkeys", name, keys)
        return keys

    def _record(self, kind: str, name: str, token: Any) -> None:
        try:
            hash(token)
        except TypeError:
            token = repr(token)
        self.specializations[(kind, name)] = token

    # -- Mapping protocol (full-value reads record specializations) ----

    def __getitem__(self, name: str) -> Any:
        v = self._params[name]
        tok = _value_token(v)
        if tok is None:
            # no faithful content token: this plan must not be cached at
            # all (a collided token would serve it for a different value)
            self.cacheable = False
            tok = object()  # unmatchable placeholder
        self._record("value", name, tok)
        return v

    def get(self, name: str, default: Any = None) -> Any:
        if name not in self._params:
            return default
        return self[name]

    def __contains__(self, name) -> bool:
        return name in self._params

    def __iter__(self) -> Iterator[str]:
        return iter(self._params)

    def __len__(self) -> int:
        return len(self._params)

    # -- key material --------------------------------------------------

    def spec_key(self) -> Tuple:
        return tuple((kind, name, tok) for (kind, name), tok
                     in self.specializations.items())

    @staticmethod
    def recompute_spec_key(spec_key: Tuple,
                           params: Mapping[str, Any]) -> Optional[Tuple]:
        """Re-derive a stored entry's specialization tokens from NEW
        parameter bindings (None = not derivable, treat as mismatch)."""
        out = []
        for kind, name, _ in spec_key:
            if kind == "mapkeys":
                v = params.get(name)
                tok: Any = tuple(sorted(v)) if isinstance(v, dict) else None
            else:  # full value
                if name not in params:
                    return None
                tok = _value_token(params[name])
                if tok is None:
                    return None
            out.append((kind, name, tok))
        return tuple(out)


@dataclasses.dataclass
class CachedPlan:
    """One planned query, ready for re-execution with fresh bindings."""
    root: Any                       # R.RelationalOperator
    result_fields: Tuple[str, ...]
    plans: Dict[str, str]           # pretty ir/logical/relational text
    records_graph: Any              # graph for entity materialization
    context: Any                    # the shared RelationalRuntimeContext
    spec_key: Tuple                 # value specializations (see PlanParams)
    cold_phase_s: float             # parse+ir+plan+relational of the cold run
    nbytes: int                     # rough host-side footprint estimate
    #: catalog graphs this plan resolved at planning time, with the
    #: per-name dep token observed then: ((qgn, token), ...).  Lookup
    #: revalidates against the live catalog, so a mutation of graph X
    #: invalidates exactly X's dependents — never the whole cache.
    catalog_deps: Tuple = ()
    #: the raw query text this plan answered — divergence-triggered
    #: retirement (``evict_family``) needs it to ALSO forget the fused
    #: executor's recorded program for (graph, query): a re-planned
    #: tree replaying the old plan's size stream would mis-gather
    query_text: str = ""
    # Serializes executions of THIS plan: the operator tree and its
    # runtime context are shared mutable state (parameter dict, per-op
    # result memos), so concurrent serving threads that hit the same
    # entry take turns — per-plan, not cache-wide (see session._run_cached).
    exec_lock: threading.Lock = dataclasses.field(
        default_factory=lambda: make_lock("plan_cache.CachedPlan"
                                          ".exec_lock"),
        repr=False, compare=False)


def reset_plan(root) -> None:
    """Clear every operator's memoized (header, table) pair so the tree
    re-executes (idempotent; handles shared subtrees)."""
    seen = set()
    stack = [root]
    while stack:
        op = stack.pop()
        if id(op) in seen:
            continue
        seen.add(id(op))
        op._result = None
        stack.extend(op.children)


def _plan_nbytes(plan: Dict[str, str], root, context=None,
                 catalog_deps=()) -> int:
    """Approximate host bytes a cached plan entry keeps resident: the
    pretty plan texts, a per-operator object estimate, the runtime
    context's retained parameter bindings (rebind swaps them but the
    LAST run's values stay referenced between executions), and the
    catalog-dependency tuples.  The input to ``plan_cache.stats()
    ["bytes"]`` and the memory ledger's ``mem.plan_cache_bytes`` gauge
    (obs/ledger.py)."""
    n_ops, seen, stack = 0, set(), [root]
    while stack:
        op = stack.pop()
        if id(op) in seen:
            continue
        seen.add(id(op))
        n_ops += 1
        stack.extend(op.children)
    n = sum(len(s) for s in plan.values()) + 512 * n_ops
    if context is not None:
        try:
            n += sum(len(str(k)) + len(repr(v))
                     for k, v in context.parameters.items())
        except Exception:  # pragma: no cover — accounting must not fail
            pass
    n += 128 * len(catalog_deps)
    return n


class PlanCache:
    """Session-level LRU cache of :class:`CachedPlan` entries.

    Keyed by (normalized query text, graph plan token, parameter
    signature); each key holds the (usually one) plans that differ only
    in recorded value specializations.  Catalog consistency is per-plan,
    not per-key: each plan carries the dep tokens of the catalog graphs
    it resolved (``catalog_deps``), revalidated on lookup — so a catalog
    mutation invalidates exactly its dependents instead of fingerprinting
    every key in the session (the old evict-everything fanout).  LRU
    order and the size cap count individual plans.

    Counters live in a :class:`caps_tpu.obs.metrics.MetricsRegistry`
    (the session passes its own), so ``plan_cache.*`` shows up in
    ``session.metrics_snapshot()`` alongside every other stat and
    consumers (bench.py) diff snapshots instead of hand-rolling
    before/after counters.  ``stats()`` and the attribute accessors
    (``.hits`` etc.) read the same counters — one source of truth."""

    def __init__(self, max_size: int = 256, enabled: bool = True,
                 registry=None):
        from caps_tpu.obs.metrics import MetricsRegistry
        self.max_size = max(1, int(max_size))
        self.enabled = enabled
        self._entries: "OrderedDict[Tuple, List[CachedPlan]]" = OrderedDict()
        self._count = 0
        # Guards _entries/_count: lookup's LRU move_to_end, store's
        # append+evict, and the catalog-subscription eviction all mutate
        # the OrderedDict and may run on different serving threads.
        self._lock = make_rlock("plan_cache.PlanCache._lock")
        self.metrics = registry if registry is not None else MetricsRegistry()
        self._hits = self.metrics.counter("plan_cache.hits")
        self._misses = self.metrics.counter("plan_cache.misses")
        self._evictions = self.metrics.counter("plan_cache.evictions")
        # catalog-fingerprint evictions (CATALOG CREATE/DROP etc.)
        self._invalidations = self.metrics.counter("plan_cache.invalidations")
        # failure-driven evictions (serve/ circuit breaker: an entry
        # whose executions keep failing is quarantined — see quarantine())
        self._quarantined = self.metrics.counter("plan_cache.quarantined")
        # cold-phase seconds skipped by hits
        self._saved_s = self.metrics.counter("plan_cache.saved_s")
        self.metrics.gauge("plan_cache.entries", fn=lambda: self._count)

    # attribute-style reads kept for existing callers/tests
    @property
    def hits(self) -> int:
        return self._hits.value

    @property
    def misses(self) -> int:
        return self._misses.value

    @property
    def evictions(self) -> int:
        return self._evictions.value

    @property
    def invalidations(self) -> int:
        return self._invalidations.value

    @property
    def saved_s(self) -> float:
        return self._saved_s.value

    def lookup(self, key: Tuple, params: Mapping[str, Any],
               catalog=None) -> Optional[CachedPlan]:
        with self._lock:
            plans = self._entries.get(key)
            if plans:
                for plan in list(plans):
                    if plan.catalog_deps and catalog is not None \
                            and any(catalog.dep_token(q) != tok
                                    for q, tok in plan.catalog_deps):
                        # a referenced catalog graph changed since this
                        # plan was made: scoped invalidation — drop just
                        # this plan, the caller replans
                        plans.remove(plan)
                        self._count -= 1
                        self._invalidations.inc()
                        continue
                    if not plan.spec_key:
                        match = True
                    else:
                        match = PlanParams.recompute_spec_key(
                            plan.spec_key, params) == plan.spec_key
                    if match:
                        self._entries.move_to_end(key)
                        self._hits.inc()
                        self._saved_s.inc(plan.cold_phase_s)
                        return plan
                if not plans:
                    del self._entries[key]
        self._misses.inc()
        return None

    def store(self, key: Tuple, plan: CachedPlan) -> None:
        with self._lock:
            plans = self._entries.setdefault(key, [])
            # replace an entry with the same specialization tokens (e.g. a
            # re-plan after the fused executor re-recorded)
            for i, p in enumerate(plans):
                if p.spec_key == plan.spec_key:
                    plans[i] = plan
                    self._entries.move_to_end(key)
                    return
            plans.append(plan)
            self._count += 1
            self._entries.move_to_end(key)
            while self._count > self.max_size and self._entries:
                _, dropped = self._entries.popitem(last=False)
                self._count -= len(dropped)
                self._evictions.inc(len(dropped))

    def quarantine(self, key: Tuple) -> int:
        """Failure containment (caps_tpu/serve/): evict every plan under
        ``key`` because executions of it keep failing — a poisoned entry
        (stale memo, corrupted operator state) would otherwise fail every
        future hit on its key forever.  Returns the number of plans
        dropped; the next execution of the query re-plans from scratch."""
        with self._lock:
            plans = self._entries.pop(key, None)
            if not plans:
                return 0
            self._count -= len(plans)
            self._quarantined.inc(len(plans))
            return len(plans)

    @property
    def quarantined(self) -> int:
        return self._quarantined.value

    def evict_family(self, family: str) -> List[CachedPlan]:
        """Divergence-triggered retirement (relational/session.py
        ``_maybe_replan``): quarantine every cached plan whose key's
        normalized-query-text component matches ``family`` — the same
        eviction path the serving tier's failure containment uses, so
        the next execution re-plans from scratch with fresh (calibrated)
        statistics.  Returns the dropped plans so the caller can ALSO
        retire their fused recordings (a re-planned tree must never
        replay the retired plan's size stream)."""
        with self._lock:
            stale = [k for k in self._entries if k[0] == family]
        dropped: List[CachedPlan] = []
        for k in stale:
            with self._lock:
                plans = self._entries.pop(k, None)
                if not plans:
                    continue
                self._count -= len(plans)
                self._quarantined.inc(len(plans))
                dropped.extend(plans)
        return dropped

    def evict_dependents(self, qgn=None) -> int:
        """Scoped catalog eviction (the session's catalog subscription):
        drop exactly the plans that resolved the mutated graph ``qgn``
        at planning time.  ``qgn=None`` (a namespace-level change —
        register/deregister) drops every plan with ANY catalog
        dependency.  Plans that never touched the catalog — the vast
        majority of serving traffic — survive untouched."""
        dropped = 0
        with self._lock:
            for k in list(self._entries):
                plans = self._entries[k]
                for plan in list(plans):
                    deps = plan.catalog_deps
                    if deps and (qgn is None
                                 or any(q == qgn for q, _tok in deps)):
                        plans.remove(plan)
                        self._count -= 1
                        self._invalidations.inc()
                        dropped += 1
                if not plans:
                    del self._entries[k]
        return dropped

    def evict_graph(self, graph_token) -> int:
        """Scoped per-graph eviction: drop every plan anchored on this
        graph plan token (key position 1).  The versioned write path
        uses it to free a superseded snapshot's plans the moment the
        next version publishes — no other graph's entries are
        touched."""
        with self._lock:
            stale = [k for k in self._entries if k[1] == graph_token]
            n = 0
            for k in stale:
                n += len(self._entries.pop(k))
            self._count -= n
            if n:
                self._invalidations.inc(n)
            return n

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._count = 0

    @property
    def size(self) -> int:
        return self._count

    def stats(self) -> Dict[str, Any]:
        total = self.hits + self.misses
        with self._lock:
            entries = self._count
            nbytes = sum(p.nbytes for plans in self._entries.values()
                         for p in plans)
        return {
            "entries": entries,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "invalidations": self.invalidations,
            "quarantined": self.quarantined,
            "hit_rate": (self.hits / total) if total else 0.0,
            "bytes": nbytes,
            "saved_s": self.saved_s,
        }


class PreparedQuery:
    """A pre-parsed query bound to a session (and optionally a graph):
    the explicit prepared-statement handle for serving workloads.

    ``prepare()`` pays parse once (populating the session-wide parse
    memo) and validates syntax eagerly; every :meth:`run` goes through
    the session plan cache, so after the first execution per parameter
    *signature* the whole frontend is skipped."""

    def __init__(self, session, query: str, graph=None):
        from caps_tpu.frontend.parser import parse_query
        self._session = session
        self._graph = graph
        self.query = query
        parse_query(query)  # eager syntax validation + parse-memo warm

    def run(self, parameters: Optional[Mapping[str, Any]] = None):
        graph = self._graph if self._graph is not None \
            else self._session._ambient
        return self._session.cypher_on_graph(graph, self.query, parameters)

    def __repr__(self):
        return f"PreparedQuery({self.query!r})"

"""Persistent on-disk compile/plan store: the cross-process warm path.

Cold starts pay two cliffs: the XLA executable compiles (35-40s in
BENCH_extra_r05 — on TPU the JAX compilation cache already persists
those, wired by ``backends/tpu/table.py``) and the engine-level warm
state a process accumulates — which plan families are hot, a
shape-faithful parameter binding per family, the fused executor's
recorded size streams, and the observed shape-bucket boundaries.  This
module persists THAT state as a versioned JSON index so a fresh process
can warm itself through ``serve/warmup.py`` instead of re-learning it
from live traffic.

Honesty contract (the store is a hint, never an authority):

* the payload is fingerprinted by store format, package version, JAX
  backend, and device kind — a mismatch is **rejected** (counter
  ``planstore.rejected`` + a structured ``planstore.rejected`` event)
  and the process degrades to cold compile, exactly like a corrupt,
  truncated, or unwritable file;
* nothing executable is stored (plain JSON, no pickle): seeded fused
  size streams are re-verified at execution time by the generic-replay
  relation checks (``backends/tpu/table.py``) — a wrong stream
  re-records, it can never shape results;
* a missing store is a normal first boot, not an error.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

STORE_FORMAT = 1

#: per-family cap on persisted size-stream entries — a runaway stream
#: must not balloon the index file
_MAX_STREAM_ENTRIES = 4096


def store_fingerprint() -> Dict[str, Any]:
    """What a payload must match to be trusted by THIS process."""
    import caps_tpu
    backend = device_kind = "unknown"
    try:
        import jax
        backend = jax.default_backend()
        device_kind = getattr(jax.devices()[0], "device_kind", "unknown")
    except Exception:  # pragma: no cover — jax-less / deviceless install
        pass
    return {"format": STORE_FORMAT,
            "package": getattr(caps_tpu, "__version__", "0"),
            "backend": backend, "device_kind": str(device_kind)}


def _serialize_stream(entries) -> Optional[List[List[Any]]]:
    """JSON form of a fused size stream, or None when it cannot
    round-trip faithfully (``__obj__`` entries hold live host objects)."""
    out: List[List[Any]] = []
    if len(entries) > _MAX_STREAM_ENTRIES:
        return None
    for e in entries:
        if not isinstance(e, tuple) or not e:
            return None
        if e[0] == "rows" and len(e) == 2 and isinstance(e[1], int):
            out.append(["rows", e[1]])
        elif e[0] == "size" and len(e) == 3 and isinstance(e[1], int) \
                and isinstance(e[2], str):
            out.append(["size", e[1], e[2]])
        else:  # __obj__ or an unknown tag: not persistable
            return None
    return out


def deserialize_stream(raw) -> Optional[List[tuple]]:
    """The inverse of :func:`_serialize_stream`, validating every entry
    — a damaged stream is dropped (None), never partially trusted."""
    if not isinstance(raw, list) or len(raw) > _MAX_STREAM_ENTRIES:
        return None
    out: List[tuple] = []
    for e in raw:
        if not isinstance(e, list) or not e:
            return None
        if e[0] == "rows" and len(e) == 2 and isinstance(e[1], int):
            out.append(("rows", e[1]))
        elif e[0] == "size" and len(e) == 3 and isinstance(e[1], int) \
                and isinstance(e[2], str):
            out.append(("size", e[1], e[2]))
        else:
            return None
    return out


def collect_warm_state(session, graph=None,
                       families: Optional[List[str]] = None
                       ) -> Dict[str, Any]:
    """Snapshot a session's warm state into a store payload: per hot
    family the original query text, the last JSON-able parameter
    binding (``session.warmup_bindings()``), the fused executor's
    param-generic size stream for ``graph`` (when the backend has one),
    and the observed max row count (the lattice seed)."""
    bindings = session.warmup_bindings()
    if families is not None:
        keep = set(families)
        bindings = [b for b in bindings if b["family"] in keep]
    streams: Dict[str, Dict[str, Any]] = {}
    fused = getattr(session, "fused", None)
    g = graph
    if g is not None and getattr(g, "graph_is_versioned", False):
        g = g.current()
    if fused is not None and g is not None:
        for query, rec in fused.export_streams(g).items():
            ser = _serialize_stream(rec["entries"])
            if ser is not None:
                streams[query] = {"pool_len": rec["pool_len"],
                                  "entries": ser}
    rows_max: Dict[str, int] = {}
    try:
        for fam, ops in session.op_stats.stats().items():
            rows_max[fam] = max((int(st.get("rows_max") or 0)
                                 for st in ops.values()), default=0)
    except Exception:  # pragma: no cover — stats shape drift
        rows_max = {}
    out_families = []
    for b in bindings:
        out_families.append({
            "family": b["family"],
            "query": b["query"],
            "params": b["params"],
            # every retained binding crossed a compile boundary (a
            # per-value compile cache's rotation) — warmup replays all
            "bindings": b.get("bindings") or [b["params"]],
            "stream": streams.get(b["query"]),
            "rows_max": rows_max.get(b["family"], 0),
        })
    stats_payload = None
    if graph is not None:
        # persist the ingest-time statistics sketch alongside the warm
        # state (relational/stats.py): a fresh process's cost model can
        # price its first plans from the PREVIOUS process's observed
        # graph shape instead of an empty prior
        try:
            stats = g.statistics() if g is not None else None
            if stats is not None and stats.total_nodes:
                stats_payload = stats.to_payload()
        except Exception:  # pragma: no cover — the store is a hint
            stats_payload = None
    return {
        "fingerprint": store_fingerprint(),
        "lattice": list(session.shape_lattice.boundaries()),
        "families": out_families,
        "stats": stats_payload,
    }


class PlanStore:
    """One JSON index file of warm-path state, loaded with suspicion.

    ``load()`` returns the validated payload or None; ``save(payload)``
    writes atomically (tmp + rename) and returns success.  EVERY way a
    store can be bad — unreadable, corrupt JSON, truncated, wrong
    fingerprint, malformed families, unwritable directory — lands in
    ``planstore.rejected`` (counter + structured event via
    ``event_log``) and degrades to a cold start; serving never sees an
    exception from here."""

    def __init__(self, path: str, registry=None, event_log=None):
        self.path = str(path)
        self._event_log = event_log
        self._rejected_c = (registry.counter("planstore.rejected")
                           if registry is not None else None)
        self._loaded_c = (registry.counter("planstore.loaded")
                         if registry is not None else None)
        self._saved_c = (registry.counter("planstore.saved")
                        if registry is not None else None)
        #: last rejection reason (None = never rejected) — the stats /
        #: warmup report surface
        self.last_rejection: Optional[str] = None

    def _reject(self, reason: str) -> None:
        self.last_rejection = reason
        if self._rejected_c is not None:
            self._rejected_c.inc()
        if self._event_log is not None:
            self._event_log.emit("planstore.rejected", request_id=None,
                                 family=None, path=self.path,
                                 reason=reason[:200])

    def load(self) -> Optional[Dict[str, Any]]:
        if not os.path.exists(self.path):
            return None  # first boot: nothing persisted yet, not an error
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                raw = f.read()
        except OSError as ex:
            self._reject(f"unreadable: {type(ex).__name__}: {ex}")
            return None
        try:
            payload = json.loads(raw)
        except ValueError as ex:
            self._reject(f"corrupt: {ex}")
            return None
        if not isinstance(payload, dict):
            self._reject("corrupt: top-level value is not an object")
            return None
        want = store_fingerprint()
        have = payload.get("fingerprint")
        if have != want:
            self._reject(f"fingerprint mismatch: stored {have!r}, "
                         f"this process {want!r}")
            return None
        fams = payload.get("families")
        if not isinstance(fams, list) or not all(
                isinstance(f, dict) and isinstance(f.get("query"), str)
                and isinstance(f.get("params"), dict)
                and (f.get("bindings") is None
                     or (isinstance(f["bindings"], list)
                         and all(isinstance(b, dict)
                                 for b in f["bindings"])))
                for f in fams):
            self._reject("malformed families section")
            return None
        if self._loaded_c is not None:
            self._loaded_c.inc()
        return payload

    def save(self, payload: Dict[str, Any]) -> bool:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, sort_keys=True)
            os.replace(tmp, self.path)
        except (OSError, TypeError, ValueError) as ex:
            self._reject(f"unwritable: {type(ex).__name__}: {ex}")
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        if self._saved_c is not None:
            self._saved_c.inc()
        return True

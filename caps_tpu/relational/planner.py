"""Logical plan → relational operator tree.

Mirrors the reference's ``RelationalPlanner`` — each LogicalOperator maps to
RelationalOperators parameterized by the backend Table; Expand becomes
Join(Join(rows, rel-scan), node-scan) on id columns (ref:
okapi-relational/.../impl/RelationalPlanner.scala — reconstructed, mount
empty; SURVEY.md §2, §3.2 "planExpand").
"""
from __future__ import annotations

from typing import Callable, Dict, Optional as Opt, Tuple

from caps_tpu.ir import exprs as E
from caps_tpu.ir.pattern import Direction
from caps_tpu.logical import ops as L
from caps_tpu.okapi.graph import QualifiedGraphName
from caps_tpu.okapi.types import CTNode, CTRelationship
from caps_tpu.relational import ops as R
from caps_tpu.relational.graphs import RelationalCypherGraph
from caps_tpu.relational.var_expand import VarExpandOp


class RelationalPlanningError(Exception):
    pass


GraphResolver = Callable[[QualifiedGraphName], RelationalCypherGraph]


class RelationalPlanner:
    def __init__(self, context: R.RelationalRuntimeContext,
                 ambient_graph: RelationalCypherGraph,
                 graph_resolver: Opt[GraphResolver] = None,
                 cost_model=None):
        self.context = context
        self.ambient_graph = ambient_graph
        self.graph_resolver = graph_resolver
        #: relational/cost.py CostModel — physical-strategy choices
        #: (count-pushdown vs cascade here; distribution strategy via
        #: cost.annotate_plan) consult it when present
        self.cost_model = cost_model
        self._entity_ctx_cache: Dict[int, R.EntityContext] = {}
        self.current_graph = ambient_graph
        self._memo: Dict[L.LogicalOperator, R.RelationalOperator] = {}
        self._fresh = 0
        # Names referenced anywhere in the plan (None = unknown, assume
        # everything is used); lets VarExpand prove its rel var dead and
        # take the ring-matrix path (var_expand.py module docstring).
        self._used_names: Opt[frozenset] = None
        # Names whose only reads are size()/length() — a var-length rel
        # list read that way is served by a PATH-LENGTH column instead,
        # keeping the query on the matrix path (e.g. LDBC IC13/IC14's
        # min(size(r))).  _fix() rewrites those reads in consumers.
        self._size_only_ok: frozenset = frozenset()
        self._len_names: Dict[str, str] = {}
        # single-hop rel var -> its pattern endpoints (for the
        # startNode()/endNode() property rewrite in _fix)
        self._rel_endpoints: Dict[str, Tuple[str, str]] = {}

    @property
    def current_graph(self) -> RelationalCypherGraph:
        return self._current_graph

    @current_graph.setter
    def current_graph(self, g: RelationalCypherGraph) -> None:
        # keep one EntityContext per graph so ops planned while this graph
        # is current share lookup caches (and multi-graph queries rehydrate
        # against the right graph — RelationalOperator snapshots this)
        self._current_graph = g
        ctx = self._entity_ctx_cache.get(id(g))
        if ctx is None:
            ctx = R.EntityContext(g)
            self._entity_ctx_cache[id(g)] = ctx
        self.context.entity_ctx = ctx

    def fresh(self, prefix: str) -> str:
        self._fresh += 1
        return f"__{prefix}_{self._fresh}"

    def _fix(self, e: E.Expr, scope: Opt[L.LogicalOperator] = None
             ) -> E.Expr:
        """Expression rewrites that need plan context:

        * size(rel)/length(rel) of a size-only var-length rel variable
          → its path-length column (see _len_names);
        * startNode(rel).k / endNode(rel).k where the MATCH bound the
          endpoints → CASE WHEN startNode(rel) = id(x) THEN x.k ELSE
          y.k — correct for every match direction, because startNode/
          endNode follow the STORED orientation and the comparison is
          against the actual stored id (previously these silently
          evaluated the property of a bare node id: null).  Applied only
          when ``scope`` (the consumer's input subtree) still carries
          the pattern's endpoint bindings unobscured — see
          _endpoints_reach."""
        if not self._len_names and not self._rel_endpoints:
            return e

        def repl(x):
            if (isinstance(x, E.FunctionExpr)
                    and x.name.lower() in ("size", "length")
                    and len(x.args) == 1 and isinstance(x.args[0], E.Var)
                    and x.args[0].name in self._len_names):
                return E.Var(self._len_names[x.args[0].name])
            if (isinstance(x, E.Property)
                    and isinstance(x.entity, (E.StartNode, E.EndNode))
                    and isinstance(x.entity.rel, E.Var)
                    and x.entity.rel.name in self._rel_endpoints
                    and scope is not None):
                a, b = self._rel_endpoints[x.entity.rel.name]
                if self._endpoints_reach(scope, x.entity.rel.name, a, b):
                    return E.CaseExpr(
                        (E.Equals(x.entity, E.Id(E.Var(a))),),
                        (E.Property(E.Var(a), x.key),),
                        E.Property(E.Var(b), x.key))
            return x

        return e.transform_up(repl)

    def _endpoints_reach(self, op, rel: str, a: str, b: str) -> bool:
        """True when, walking down the consumer's input subtree, the
        Expand binding ``rel`` is reached with its endpoint names
        ``a``/``b`` neither dropped by a Select nor rebound by a
        Project/Aggregate/Unwind/var-length bind along the way."""
        while op is not None:
            if isinstance(op, L.Select):
                if not {a, b} <= set(op.names):
                    return False
                op = op.parent
            elif isinstance(op, L.Project):
                if {rel, a, b} & {n for n, _ in op.items}:
                    return False  # rel or endpoint rebound here
                op = op.parent
            elif isinstance(op, L.Aggregate):
                return False  # only grouped aliases survive
            elif isinstance(op, L.Unwind):
                if op.var in (rel, a, b):
                    return False
                op = op.parent
            elif isinstance(op, L.Expand):
                if op.rel == rel:
                    return {op.source, op.target} == {a, b}
                op = op.parent
            elif isinstance(op, L.BoundedVarLengthExpand):
                if op.rel == rel or op.target in (a, b) \
                        or op.rel in (a, b):
                    return False
                op = op.parent
            elif isinstance(op, (L.Filter, L.Distinct, L.OrderBy, L.Skip,
                                 L.Limit, L.NodeScan, L.FromGraph)):
                op = getattr(op, "parent", None)
            elif isinstance(op, (L.Optional, L.ExistsSemiJoin)):
                return (self._endpoints_reach(op.rhs, rel, a, b)
                        or self._endpoints_reach(op.lhs, rel, a, b))
            elif isinstance(op, (L.CartesianProduct, L.ValueJoin)):
                return (self._endpoints_reach(op.lhs, rel, a, b)
                        or self._endpoints_reach(op.rhs, rel, a, b))
            elif isinstance(op, L.TabularUnionAll):
                # rows come from either branch: both must satisfy
                return (self._endpoints_reach(op.lhs, rel, a, b)
                        and self._endpoints_reach(op.rhs, rel, a, b))
            else:
                return False  # unknown operator: conservative
        return False

    def process(self, plan: L.LogicalPlan) -> R.RelationalOperator:
        self._used_names, self._size_only_ok, self._rel_endpoints = \
            self._collect_used_names(plan.root)
        return self.plan_op(plan.root)

    @staticmethod
    def _op_exprs(op):
        """The expression trees one logical operator carries."""
        if isinstance(op, L.Filter):
            return (op.predicate,)
        if isinstance(op, L.Project):
            return tuple(e for _, e in op.items)
        if isinstance(op, L.Aggregate):
            return (tuple(e for _, e in op.group)
                    + tuple(a for _, a in op.aggregations))
        if isinstance(op, L.OrderBy):
            return tuple(e for e, _ in op.items)
        if isinstance(op, (L.Skip, L.Limit)):
            return (op.expr,)
        if isinstance(op, L.Unwind):
            return (op.list_expr,)
        if isinstance(op, L.ValueJoin):
            return tuple(op.predicates)
        return ()

    @staticmethod
    def _collect_used_names(root: L.LogicalOperator):
        """(used, size_only): every name read by an expression or
        selection in the plan, and the subset whose EVERY read is
        ``size(name)``/``length(name)`` (those reads can be served by a
        path-length column instead of the materialized value).  used is
        None (= treat all names as used) when the plan contains
        operators whose name flow this walk doesn't model (CONSTRUCT
        patterns carry var references outside the Expr tree)."""
        used = set()
        selected = set()
        total: dict = {}
        wrapped: dict = {}
        varlen_binds: dict = {}
        other_binds = set()
        rel_endpoints: dict = {}
        shadowed = set()
        conservative = False
        has_exists = False

        def count_expr(e):
            nonlocal has_exists
            if isinstance(e, E.Var):
                total[e.name] = total.get(e.name, 0) + 1
            if isinstance(e, E.ExistsSubQuery):
                # the subquery pattern introduces its own scope this
                # name-level analysis does not model
                has_exists = True
            if (isinstance(e, E.FunctionExpr)
                    and e.name.lower() in ("size", "length")
                    and len(e.args) == 1 and isinstance(e.args[0], E.Var)):
                n = e.args[0].name
                wrapped[n] = wrapped.get(n, 0) + 1
            for c in e.children:
                if isinstance(c, E.Expr):
                    count_expr(c)

        seen_ops = set()

        def walk(op):
            nonlocal conservative
            # shared subtrees (Optional/ExistsSemiJoin rhs embeds lhs)
            # must count once, or a single Expand looks rebound
            if id(op) in seen_ops:
                return
            seen_ops.add(id(op))
            if isinstance(op, (L.ConstructGraph, L.ReturnGraph)):
                conservative = True
            if isinstance(op, L.Select):
                used.update(op.names)
                selected.update(op.names)
            # binding sites: a size-only rewrite is sound only when the
            # name has exactly ONE binding in the whole plan and it is a
            # var-length rel — same-named bindings in sibling scopes
            # (UNION branches, UNWIND) would otherwise be rewritten to a
            # length column their branch does not have
            if isinstance(op, L.BoundedVarLengthExpand):
                varlen_binds[op.rel] = varlen_binds.get(op.rel, 0) + 1
                other_binds.add(op.target)
            elif isinstance(op, (L.NodeScan, L.RelScan)):
                other_binds.add(op.var)
            elif isinstance(op, L.Expand):
                other_binds.update((op.rel, op.target))
                if op.rel in rel_endpoints and \
                        rel_endpoints[op.rel] != (op.source, op.target):
                    shadowed.add(op.rel)  # rebound: ambiguous endpoints
                rel_endpoints[op.rel] = (op.source, op.target)
            elif isinstance(op, L.Unwind):
                other_binds.add(op.var)
            elif isinstance(op, L.Project):
                other_binds.update(n for n, _ in op.items)
            elif isinstance(op, L.Aggregate):
                other_binds.update(n for n, _ in op.group)
                other_binds.update(n for n, _ in op.aggregations)
            for e in RelationalPlanner._op_exprs(op):
                used.update(v.name for v in E.vars_in(e))
                count_expr(e)
            for c in op.children:
                if isinstance(c, L.LogicalOperator):
                    walk(c)

        walk(root)
        for n in shadowed:
            rel_endpoints.pop(n, None)

        if conservative:
            return None, frozenset(), {}
        if has_exists:
            return frozenset(used), frozenset(), rel_endpoints
        size_only = frozenset(
            n for n, t in total.items()
            if wrapped.get(n, 0) == t and n not in selected
            and varlen_binds.get(n, 0) == 1 and n not in other_binds)
        return frozenset(used), size_only, rel_endpoints

    # ------------------------------------------------------------------

    def plan_op(self, op: L.LogicalOperator) -> R.RelationalOperator:  # noqa: C901
        # Memo keys are the logical ops themselves (frozen dataclasses, so
        # structural): shared or structurally-identical subtrees plan to one
        # relational operator, which Optional planning depends on.
        if op in self._memo:
            return self._memo[op]
        out = self._plan_op(op)
        self._memo[op] = out
        return out

    def _plan_op(self, op: L.LogicalOperator) -> R.RelationalOperator:  # noqa: C901
        ctx = self.context
        if isinstance(op, L.Start):
            if op.qgn is not None and self.graph_resolver is not None:
                self.current_graph = self.graph_resolver(op.qgn)
            return R.StartOp(ctx)
        if isinstance(op, L.NodeScan):
            self.plan_op(op.parent)  # graph-context side effects (FromGraph)
            return R.ScanOp(ctx, self.current_graph, op.var, CTNode(op.labels))
        if isinstance(op, L.RelScan):
            self.plan_op(op.parent)
            return R.ScanOp(ctx, self.current_graph, op.var,
                            CTRelationship(op.rel_types))
        if isinstance(op, L.Expand):
            return self._plan_expand(op)
        if isinstance(op, L.BoundedVarLengthExpand):
            parent = self.plan_op(op.parent)
            rel_needed = (self._used_names is None
                          or op.rel in self._used_names)
            emit_len = None
            if rel_needed and op.rel in self._size_only_ok:
                # every read is size(rel)/length(rel): emit a path-length
                # column and rewrite those reads to it — the rel list
                # itself need not materialize
                emit_len = f"__{op.rel}_len"
                self._len_names[op.rel] = emit_len
                rel_needed = False
            return VarExpandOp(
                ctx, parent, self.current_graph, op.source, op.rel,
                op.rel_types, op.target, op.target_labels, op.direction,
                op.lower, op.upper, op.into, rel_needed=rel_needed,
                emit_len=emit_len)
        if isinstance(op, L.Filter):
            parent = self.plan_op(op.parent)
            return R.FilterOp(ctx, parent,
                               self._fix(op.predicate, op.parent))
        if isinstance(op, L.Project):
            parent = self.plan_op(op.parent)
            env = dict(op.fields)
            items = [(name, self._fix(expr, op.parent), env[name])
                     for name, expr in op.items]
            return R.ProjectOp(ctx, parent, items)
        if isinstance(op, L.Select):
            return R.SelectOp(ctx, self.plan_op(op.parent), op.names)
        if isinstance(op, L.Distinct):
            return R.DistinctOp(ctx, self.plan_op(op.parent))
        if isinstance(op, L.Aggregate):
            parent = self.plan_op(op.parent)
            env = dict(op.fields)
            group = [(n, self._fix(e, op.parent), env[n])
                     for n, e in op.group]
            aggs = [(n, self._fix(a, op.parent), env[n])
                    for n, a in op.aggregations]
            default = R.AggregateOp(ctx, parent, group, aggs)
            from caps_tpu.relational.count_pattern import (
                CountCycleOp, try_plan_count_pushdown,
            )
            pushed = try_plan_count_pushdown(self, op, default)
            if pushed is not None and self.cost_model is not None \
                    and not isinstance(pushed, CountCycleOp) \
                    and not self._pushdown_wins(pushed):
                # count-pushdown vs cascade is a MODEL choice now: a
                # hyper-selective seed on a huge graph keeps the join
                # cascade (tiny padded frontiers beat a full-graph SpMV)
                pushed = None
            return pushed if pushed is not None else default
        if isinstance(op, L.OrderBy):
            parent = self.plan_op(op.parent)
            items = tuple((self._fix(e, op.parent), asc)
                          for e, asc in op.items)
            return R.OrderByOp(ctx, parent, items)
        if isinstance(op, L.Skip):
            parent = self.plan_op(op.parent)
            return R.SkipOp(ctx, parent, self._fix(op.expr, op.parent))
        if isinstance(op, L.Limit):
            parent = self.plan_op(op.parent)
            return R.LimitOp(ctx, parent, self._fix(op.expr, op.parent))
        if isinstance(op, L.Unwind):
            env = dict(op.fields)
            parent = self.plan_op(op.parent)
            return R.UnwindOp(ctx, parent,
                              self._fix(op.list_expr, op.parent),
                              op.var, env[op.var])
        if isinstance(op, L.Optional):
            tagged, rhs, rid = self._plan_optional(op.lhs, op.rhs)
            return R.OptionalJoinOp(ctx, tagged, rhs, rid)
        if isinstance(op, L.ExistsSemiJoin):
            tagged, rhs, rid = self._plan_optional(op.lhs, op.rhs)
            return R.ExistsJoinOp(ctx, tagged, rhs, rid, op.marker)
        if isinstance(op, L.CartesianProduct):
            l, r = self._plan_two(op.lhs, op.rhs)
            return R.CrossOp(ctx, l, r)
        if isinstance(op, L.ValueJoin):
            pairs = []
            for pred in op.predicates:
                if not isinstance(pred, E.Equals):
                    raise RelationalPlanningError(
                        f"ValueJoin predicate must be equality: {pred!r}")
                pairs.append((pred.lhs, pred.rhs))
            l, r = self._plan_two(op.lhs, op.rhs)
            return R.JoinOp(ctx, l, r, pairs, op.join_type)
        if isinstance(op, L.TabularUnionAll):
            l, r = self._plan_two(op.lhs, op.rhs, keep="pre")
            return R.UnionAllOp(ctx, l, r)
        if isinstance(op, L.FromGraph):
            planned = self.plan_op(op.parent)
            if self.graph_resolver is None:
                raise RelationalPlanningError(
                    f"FROM GRAPH {op.qgn!r} requires a catalog")
            self.current_graph = self.graph_resolver(op.qgn)
            return planned
        if isinstance(op, (L.ConstructGraph, L.ReturnGraph)):
            from caps_tpu.relational.construct import plan_construct
            return plan_construct(self, op)
        if isinstance(op, L.EmptyRecords):
            return R.StartOp(ctx)
        if isinstance(op, L.ProcedureCall):
            return self._plan_procedure(op)
        raise RelationalPlanningError(f"cannot plan {type(op).__name__}")

    def _plan_procedure(self, op: L.ProcedureCall) -> R.RelationalOperator:
        from caps_tpu.algo import registry
        from caps_tpu.algo.op import AlgoProcedureOp
        parent = self.plan_op(op.parent)
        sig = registry.lookup(op.procedure)
        prefer_host = False
        if self.cost_model is not None:
            try:
                prefer_host = not self.cost_model.algo_pushdown_wins(
                    sig.name, sig.est_iterations)
            except Exception:  # pragma: no cover — pricing must not fail
                prefer_host = False
        return AlgoProcedureOp(self.context, parent, self.current_graph,
                               sig, op.args, op.yields,
                               prefer_host=prefer_host)

    def _pushdown_wins(self, pushed) -> bool:
        """Price the matched count chain both ways (relational/cost.py
        ``count_pushdown_wins``) — SpMV touches every edge once, the
        cascade the padded expanded frontiers."""
        model = self.cost_model
        seed = pushed.seed
        try:
            return model.count_pushdown_wins(
                seed.labels, model.selectivity(seed.preds, seed.labels),
                [(h.rel_types, h.direction, h.target.labels,
                  model.selectivity(h.target.preds, h.target.labels))
                 for h in pushed.hops])
        except Exception:  # pragma: no cover — pricing must not fail
            return True

    # -- branch-scoped graph context ----------------------------------------

    def _plan_two(self, lhs: L.LogicalOperator, rhs: L.LogicalOperator,
                  keep: str = "lhs"):
        """Plan two independent subtrees with branch-scoped FROM GRAPH
        effects: a graph switch inside one branch must not leak into its
        sibling.  ``keep`` selects which graph context survives: the lhs
        chain's ("lhs", the main chain for joins/products) or the
        pre-branch one ("pre", for UNION where neither branch's switch
        outlives the union)."""
        pre = self.current_graph
        l = self.plan_op(lhs)
        lhs_graph = self.current_graph
        self.current_graph = pre
        r = self.plan_op(rhs)
        self.current_graph = lhs_graph if keep == "lhs" else pre
        return l, r

    def _plan_optional(self, lhs: L.LogicalOperator, rhs: L.LogicalOperator):
        """Optional-match planning: lhs is planned, tagged with a row index,
        and the optional side is planned on the tagged lhs (it continues the
        lhs graph context)."""
        lhs_planned = self.plan_op(lhs)
        rid = self.fresh("rid")
        tagged = R.RowIndexOp(self.context, lhs_planned, rid)
        self._memo[lhs] = tagged
        rhs_planned = self.plan_op(rhs)
        self._memo[lhs] = lhs_planned
        return tagged, rhs_planned, rid

    # -- Expand (SURVEY.md §3.2: the hot path generator) --------------------

    def _plan_expand(self, op: L.Expand) -> R.RelationalOperator:
        ctx = self.context
        rel_var = E.Var(op.rel)
        src_var = E.Var(op.source)
        tgt_var = E.Var(op.target)
        rel_ct = CTRelationship(op.rel_types)

        def branch(outgoing: bool, rel_name: str) -> R.RelationalOperator:
            # parent planning lives INSIDE the branch (memoized, so the
            # BOTH union's two branches still share one subtree): a WCOJ
            # substitution must not plan the chain below it until the
            # decision is made, or nested closing edges would substitute
            # their own operators into what becomes this op's fallback
            parent = self.plan_op(op.parent)
            rel_scan = R.ScanOp(ctx, self.current_graph, rel_name, rel_ct)
            rv = E.Var(rel_name)
            near = E.StartNode(rv) if outgoing else E.EndNode(rv)
            far = E.EndNode(rv) if outgoing else E.StartNode(rv)
            if op.into:
                return R.JoinOp(ctx, parent, rel_scan,
                                [(src_var, near), (tgt_var, far)], "inner")
            j1 = R.JoinOp(ctx, parent, rel_scan, [(src_var, near)], "inner")
            tgt_scan = R.ScanOp(ctx, self.current_graph, op.target,
                                CTNode(op.target_labels))
            return R.JoinOp(ctx, j1, tgt_scan, [(far, tgt_var)], "inner")

        if op.direction in (Direction.OUTGOING, Direction.INCOMING):
            if op.into and not getattr(self, "_in_wcoj_fallback", False):
                # cyclic pattern: a closing edge (both endpoints bound)
                # roots a segment the worst-case-optimal multiway join
                # can own (relational/wcoj.py) — cost-DECIDED before the
                # cascade is built, and the embedded fallback cascade is
                # built with nested substitution suppressed: ONE
                # MultiwayJoinOp per segment, never a second one buried
                # inside the fallback of the first (multi-closing-edge
                # patterns would otherwise substitute per into-Expand)
                from caps_tpu.relational.wcoj import try_plan_wcoj

                def build_cascade():
                    self._in_wcoj_fallback = True
                    try:
                        return branch(op.direction == Direction.OUTGOING,
                                      op.rel)
                    finally:
                        self._in_wcoj_fallback = False
                pushed = try_plan_wcoj(self, op, build_cascade)
                if pushed is not None:
                    return pushed
            return branch(op.direction == Direction.OUTGOING, op.rel)
        # BOTH: union of the two orientations; exclude self-loops from the
        # second branch so each loop edge matches exactly once.
        out_b = branch(True, op.rel)
        in_b = branch(False, op.rel)
        in_b = R.FilterOp(ctx, in_b,
                          E.Not(E.Equals(E.StartNode(rel_var), E.EndNode(rel_var))))
        return R.UnionAllOp(ctx, out_b, in_b)

"""Snapshot-keyed result & subplan caching — repeated reads from memory.

Every read used to pay the full device path: even a byte-identical
repeated query against an unchanged snapshot re-executed its compiled
program, so serving QPS on skewed (hot-query-heavy) traffic was capped
by device dwell instead of memory bandwidth.  PR 7's immutable
per-version :class:`GraphSnapshot` makes result reuse *provably sound* —
a result keyed by ``(result scope, snapshot version)`` can never be
stale, the same way paged KV-cache reuse is made sound by immutable
prefix blocks (Ragged Paged Attention; PAPERS.md).  Two levels:

* **Result cache** — a bounded LRU of fully materialized result rows
  keyed by ``(result scope, normalized query text, param value
  digest)`` plus the snapshot version checked at lookup.  Admission is
  **cost-aware**: an entry is admitted only when its observed service
  time (``session.op_stats``) times a recency-estimated re-hit
  probability beats its byte footprint — one giant scan can't evict a
  thousand cheap point-reads (the observed-statistics costing line of
  "Premature Dimensional Collapse ..."; PAPERS.md).  Bytes are charged
  to the memory ledger's ``mem.result_cache_bytes`` gauge and bounded
  by :class:`ResultCacheConfig.budget_bytes`.

* **Subplan cache** — deterministic scan→filter *prefixes* of the
  relational operator tree, memoized by structural signature within a
  snapshot.  Different plan families that share a prefix (the LDBC read
  mix is full of these) reuse ONE materialized intermediate: before
  execution the cached ``(header, table)`` is seeded into the prefix
  root's result memo, so the operators above it pull it without
  recomputing (and without re-appending op metrics — the observable
  proof of reuse).  Only param-free prefixes are eligible: a filter
  whose predicate reads ``$param`` computes different rows per binding.

Consistency is by construction, not invalidation: writes publish a new
snapshot version = a new key space, so a cached entry is *never*
invalidated by a write — it is retired when its version is superseded
(commit/compaction/``install_state``) or its plan family is quarantined
by the serving tier's failure containment.  Recency estimates read
``obs.clock`` (never ``time.*``) so the fake-clock tests can pin the
half-life decay exactly.
"""
from __future__ import annotations

import dataclasses
import itertools
from collections import OrderedDict
from typing import Any, Dict, List, Mapping, Optional, Tuple

from caps_tpu.obs import clock
from caps_tpu.obs.lockgraph import make_lock, make_rlock
from caps_tpu.relational.plan_cache import _value_token

_scope_tokens = itertools.count(1)
_scope_token_lock = make_lock("result_cache._scope_token_lock")


def result_scope(graph) -> Optional[int]:
    """A stable identity for the *lineage* a snapshot belongs to.

    Snapshots of one VersionedGraph share a scope (stamped on the
    handle, so retire-by-scope can drop every superseded version in one
    sweep); a plain immutable graph is its own scope.  The first-use
    stamp is locked, mirroring ``graph_plan_token``: concurrent serving
    threads submitting against a fresh graph must agree on ONE scope or
    their cache keys silently diverge.  None = unanchorable."""
    anchor = getattr(graph, "handle", None)
    if anchor is None:
        anchor = graph
    tok = getattr(anchor, "_rescache_scope", None)
    if tok is None:
        with _scope_token_lock:
            tok = getattr(anchor, "_rescache_scope", None)
            if tok is not None:
                return tok
            tok = next(_scope_tokens)
            try:
                anchor._rescache_scope = tok
            except Exception:
                return None
    return tok


def graph_version(graph) -> int:
    """The snapshot version a result read from ``graph`` is keyed by.
    Plain immutable graphs are version 0 forever — their single version
    never flips, so entries simply never retire."""
    try:
        return int(getattr(graph, "snapshot_version", 0) or 0)
    except Exception:
        return 0


def params_digest(params: Mapping[str, Any]) -> Optional[Tuple]:
    """A value-FAITHFUL digest of the parameter bindings, or None when
    one can't be built (an unfaithful token would serve another
    binding's rows — refuse caching instead; same discipline as the
    plan cache's value specializations)."""
    items = []
    for k in sorted(params):
        tok = _value_token(params[k])
        if tok is None:
            return None
        items.append((k, tok))
    return tuple(items)


def result_cache_key(graph, query: str,
                     params: Mapping[str, Any]) -> Optional[Tuple]:
    """The full cache key for one read, or None when the read is
    uncacheable (version-unstable handle that carries no snapshot
    identity, or un-digestable parameter values).  The snapshot VERSION
    is deliberately *not* part of the key: lookup checks it against the
    stored entry so a superseded entry reads as a miss (and is dropped)
    instead of lingering under a dead key."""
    from caps_tpu.frontend.parser import normalize_query
    scope = result_scope(graph)
    if scope is None:
        return None
    if getattr(graph, "plan_token_unstable", False) \
            and not hasattr(graph, "snapshot_version"):
        return None
    digest = params_digest(params or {})
    if digest is None:
        return None
    # the SAME token normal form the plan family uses, so family-scoped
    # eviction (quarantine) matches result keys by key[1]
    return (scope, normalize_query(query), digest)


@dataclasses.dataclass(frozen=True)
class ResultCacheConfig:
    """Knobs for the two-level cache (server-side: ``ServerConfig
    .result_cache``)."""
    #: hard ceiling on resident result+subplan bytes (the
    #: ``mem.result_cache_bytes`` ledger gauge never exceeds it)
    budget_bytes: int = 8 << 20
    #: entry-count cap across both levels (belt to the byte budget)
    max_entries: int = 1024
    #: re-hit probability half-life: an entry last seen ``half_life_s``
    #: ago is half as likely to recur as one seen just now
    half_life_s: float = 30.0
    #: admission floor: expected saved seconds per resident byte
    min_benefit_per_byte: float = 1e-10
    #: no single entry may take more than this fraction of the budget
    max_entry_fraction: float = 0.25
    enabled: bool = True
    #: memoize scan→filter prefixes too (the second level)
    subplan: bool = True


class _ResultEntry:
    __slots__ = ("key", "version", "rows", "nbytes", "service_s",
                 "hits", "stored_t", "last_t")

    def __init__(self, key, version, rows, nbytes, service_s, now_t):
        self.key = key
        self.version = int(version)
        self.rows = rows
        self.nbytes = int(nbytes)
        self.service_s = float(service_s)
        self.hits = 0
        self.stored_t = now_t
        self.last_t = now_t


class _SubplanEntry:
    __slots__ = ("key", "header", "table", "nbytes", "last_t")

    def __init__(self, key, header, table, nbytes, now_t):
        self.key = key
        self.header = header
        self.table = table
        self.nbytes = int(nbytes)
        self.last_t = now_t


class CachedRows:
    """The ``result=`` object completed onto a cache-hit handle: exposes
    the same ``to_maps()`` the records object does, so callers that go
    through ``handle.result().to_maps()`` and callers that go through
    ``handle.rows()`` both see the cached rows (fresh copies — a caller
    mutating its rows must never corrupt the cache or a co-hit)."""

    def __init__(self, rows: List[Dict[str, Any]]):
        self._rows = rows

    def to_maps(self) -> List[Dict[str, Any]]:
        return [dict(r) for r in self._rows]

    def __repr__(self):
        return f"CachedRows({len(self._rows)} rows)"


def _rows_nbytes(rows: List[Dict[str, Any]]) -> int:
    """Rough host bytes a materialized row list keeps resident."""
    n = 64 * (len(rows) + 1)
    for r in rows:
        for k, v in r.items():
            n += 48 + len(str(k)) + len(repr(v))
    return n


# -- subplan signatures ----------------------------------------------------

def _expr_has_param(expr) -> bool:
    """Walk a frozen-dataclass expression tree for any ``Param`` node —
    a parameterized predicate computes different rows per binding, so
    the prefix below it is ineligible for structural memoization."""
    from caps_tpu.ir import exprs as E
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, E.Param):
            return True
        if dataclasses.is_dataclass(node):
            for f in dataclasses.fields(node):
                v = getattr(node, f.name, None)
                if isinstance(v, (list, tuple)):
                    stack.extend(v)
                else:
                    stack.append(v)
    return False


def _prefix_signature(op) -> Optional[Tuple]:
    """Structural signature of a deterministic scan→filter prefix, or
    None when ``op`` roots no eligible prefix.  ``repr`` of the frozen
    predicate dataclass is faithful (every field participates), so two
    plan families that planned the same prefix produce the same
    signature — that's the whole point: cross-family reuse."""
    from caps_tpu.relational import ops as R
    if isinstance(op, R.ScanOp):
        return (("scan", op.var, repr(op.entity_type)),)
    if isinstance(op, R.FilterOp) and len(op.children) == 1:
        if _expr_has_param(op.predicate):
            return None
        child_sig = _prefix_signature(op.children[0])
        if child_sig is None:
            return None
        return child_sig + (("filter", repr(op.predicate)),)
    return None


def _prefix_anchor(op):
    """The leaf ScanOp of an eligible prefix — its ``.graph`` anchors
    the (scope, version) the memoized intermediate is sound for."""
    from caps_tpu.relational import ops as R
    while not isinstance(op, R.ScanOp):
        if not op.children:
            return None
        op = op.children[0]
    return op


def _eligible_prefixes(root) -> List[Tuple[Any, Tuple]]:
    """Maximal eligible prefixes under ``root``: walk top-down, stop
    descending at the first op that roots one (a sub-prefix of a
    memoized prefix would be redundant)."""
    out, seen, stack = [], set(), [root]
    while stack:
        op = stack.pop()
        if id(op) in seen:
            continue
        seen.add(id(op))
        sig = _prefix_signature(op)
        if sig is not None:
            out.append((op, sig))
            continue
        stack.extend(op.children)
    return out


class ResultCache:
    """The two-level, byte-budgeted, snapshot-keyed cache.

    One lock guards both levels and the byte ledger (lookups mutate LRU
    order and hit stamps; the serving tier calls in from admission,
    completion, quarantine, and the versioned write path's retirement
    hooks, all on different threads).  Counters live in the session's
    :class:`MetricsRegistry` so ``rescache.*`` shows up in
    ``session.metrics_snapshot()`` and fleet ``merge_snapshots``."""

    def __init__(self, config: Optional[ResultCacheConfig] = None,
                 registry=None):
        from caps_tpu.obs.metrics import MetricsRegistry
        self.config = config if config is not None else ResultCacheConfig()
        self.metrics = registry if registry is not None else MetricsRegistry()
        self._lock = make_rlock("result_cache.ResultCache._lock")
        self._entries: "OrderedDict[Tuple, _ResultEntry]" = OrderedDict()
        self._subplans: "OrderedDict[Tuple, _SubplanEntry]" = OrderedDict()
        self._bytes = 0
        #: recency notebook: key -> (miss_count, last_seen_t), bounded —
        #: the re-hit probability estimator's only state
        self._seen: "OrderedDict[Tuple, Tuple[int, float]]" = OrderedDict()
        self._seen_cap = max(64, 4 * self.config.max_entries)
        self._hits = self.metrics.counter("rescache.hits")
        self._misses = self.metrics.counter("rescache.misses")
        self._insertions = self.metrics.counter("rescache.insertions")
        self._evictions = self.metrics.counter("rescache.evictions")
        self._admission_rejects = self.metrics.counter(
            "rescache.admission_rejects")
        self._stale_rejects = self.metrics.counter("rescache.stale_rejects")
        self._retired = self.metrics.counter("rescache.retired")
        self._subplan_hits = self.metrics.counter("rescache.subplan_hits")
        self._subplan_misses = self.metrics.counter("rescache.subplan_misses")
        self._subplan_insertions = self.metrics.counter(
            "rescache.subplan_insertions")
        self.metrics.gauge("rescache.entries", fn=lambda: len(self._entries))
        self.metrics.gauge("rescache.subplan_entries",
                           fn=lambda: len(self._subplans))
        self.metrics.gauge("rescache.bytes", fn=lambda: self._bytes)
        self.metrics.gauge("rescache.hit_ratio", fn=self._hit_ratio)

    def _hit_ratio(self) -> float:
        h, m = self._hits.value, self._misses.value
        return (h / (h + m)) if (h + m) else 0.0

    # -- result level ------------------------------------------------------

    def _load(self, key: Tuple) -> Optional[_ResultEntry]:
        """The single entry-fetch seam, called under the cache lock —
        ``testing.faults.stale_cache`` patches it to forge wrong-version
        entries, proving the version check downstream of it holds."""
        return self._entries.get(key)

    def lookup(self, key: Tuple,
               version: int) -> Optional[List[Dict[str, Any]]]:
        """Rows for ``key`` at exactly ``version``, or None.  A stored
        entry at any OTHER version is dropped, not served: version-keyed
        consistency is the whole soundness story."""
        if not self.config.enabled or key is None:
            return None
        now_t = clock.now()
        with self._lock:
            entry = self._load(key)
            if entry is None:
                self._note_miss(key, now_t)
                self._misses.inc()
                return None
            if entry.version != int(version):
                self._stale_rejects.inc()
                real = self._entries.pop(key, None)
                if real is not None:
                    self._bytes -= real.nbytes
                    self._evictions.inc()
                self._note_miss(key, now_t)
                self._misses.inc()
                return None
            self._entries.move_to_end(key)
            entry.hits += 1
            entry.last_t = now_t
            self._hits.inc()
            return [dict(r) for r in entry.rows]

    def _note_miss(self, key: Tuple, now_t: float) -> None:
        count, _ = self._seen.get(key, (0, now_t))
        self._seen[key] = (count + 1, now_t)
        self._seen.move_to_end(key)
        while len(self._seen) > self._seen_cap:
            self._seen.popitem(last=False)

    def _rehit_probability(self, key: Tuple, now_t: float) -> float:
        """How likely this key recurs, from its miss history: each prior
        sighting raises the ceiling (count/(count+1)), decayed by how
        long ago the last one was (half-life ``half_life_s``)."""
        count, last_t = self._seen.get(key, (1, now_t))
        base = count / (count + 1.0)
        age = max(0.0, now_t - last_t)
        return base * (0.5 ** (age / max(1e-9, self.config.half_life_s)))

    def offer(self, key: Tuple, version: int, rows: List[Dict[str, Any]],
              nbytes: Optional[int] = None,
              service_s: float = 0.0) -> bool:
        """Cost-aware admission: admit when ``service_s`` (the seconds a
        future hit saves) × re-hit probability beats the byte footprint.
        Returns True when the entry was admitted."""
        cfg = self.config
        if not cfg.enabled or key is None:
            return False
        nbytes = int(nbytes) if nbytes else _rows_nbytes(rows)
        nbytes = max(1, nbytes)
        if nbytes > cfg.budget_bytes * cfg.max_entry_fraction:
            self._admission_rejects.inc()
            return False
        now_t = clock.now()
        with self._lock:
            benefit = float(service_s) * self._rehit_probability(key, now_t)
            if benefit / nbytes < cfg.min_benefit_per_byte:
                self._admission_rejects.inc()
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            entry = _ResultEntry(key, version,
                                 [dict(r) for r in rows],
                                 nbytes, service_s, now_t)
            self._entries[key] = entry
            self._bytes += nbytes
            self._insertions.inc()
            self._evict_over_budget()
        return True

    # -- subplan level -----------------------------------------------------

    def _subplan_key(self, op, sig: Tuple) -> Optional[Tuple]:
        anchor = _prefix_anchor(op)
        if anchor is None:
            return None
        scope = result_scope(anchor.graph)
        if scope is None:
            return None
        return (scope, graph_version(anchor.graph), sig)

    def seed_subplans(self, root) -> int:
        """Before execution: install memoized intermediates into every
        eligible prefix root's result memo, so the ops above pull them
        without recomputing (and without re-appending op metrics — the
        observable proof of reuse).  Returns the number seeded."""
        if not (self.config.enabled and self.config.subplan):
            return 0
        seeded = 0
        now_t = clock.now()
        for op, sig in _eligible_prefixes(root):
            key = self._subplan_key(op, sig)
            if key is None:
                continue
            with self._lock:
                entry = self._subplans.get(key)
                if entry is None:
                    self._subplan_misses.inc()
                    continue
                self._subplans.move_to_end(key)
                entry.last_t = now_t
                op._result = (entry.header, entry.table)
                self._subplan_hits.inc()
                seeded += 1
        return seeded

    def store_subplans(self, root) -> int:
        """After execution (BEFORE any ``reset_plan``): capture every
        eligible prefix's computed (header, table).  Tables are
        immutable columnar values shared by reference — the op tree
        itself holds the same objects between runs."""
        if not (self.config.enabled and self.config.subplan):
            return 0
        stored = 0
        now_t = clock.now()
        for op, sig in _eligible_prefixes(root):
            memo = getattr(op, "_result", None)
            if memo is None:
                continue
            key = self._subplan_key(op, sig)
            if key is None:
                continue
            header, table = memo
            try:
                nbytes = int(table.nbytes)
            except Exception:
                nbytes = 1024
            if nbytes > self.config.budget_bytes \
                    * self.config.max_entry_fraction:
                continue
            with self._lock:
                if key in self._subplans:
                    continue
                self._subplans[key] = _SubplanEntry(key, header, table,
                                                    nbytes, now_t)
                self._bytes += nbytes
                self._subplan_insertions.inc()
                self._evict_over_budget()
                stored += 1
        return stored

    # -- eviction / retirement --------------------------------------------

    def _evict_over_budget(self) -> None:
        """Under the lock: pop least-recently-used entries (across BOTH
        levels, by last-touch stamp) until bytes and entry count fit."""
        cfg = self.config
        while self._bytes > cfg.budget_bytes or \
                (len(self._entries) + len(self._subplans)) > cfg.max_entries:
            r_key = next(iter(self._entries), None)
            s_key = next(iter(self._subplans), None)
            if r_key is None and s_key is None:
                break
            r_t = self._entries[r_key].last_t if r_key is not None \
                else float("inf")
            s_t = self._subplans[s_key].last_t if s_key is not None \
                else float("inf")
            if r_t <= s_t:
                entry = self._entries.pop(r_key)
            else:
                entry = self._subplans.pop(s_key)
            self._bytes -= entry.nbytes
            self._evictions.inc()

    def retire_superseded(self, scope: Optional[int],
                          version: int) -> int:
        """Drop every entry of ``scope`` whose version predates
        ``version`` — the versioned write path's hook, called when a
        commit / compaction / ``install_state`` publishes a newer
        snapshot.  New versions never *invalidate* (new key space); this
        only reclaims bytes a dead version can never serve again."""
        if scope is None:
            return 0
        version = int(version)
        dropped = 0
        with self._lock:
            for key in [k for k, e in self._entries.items()
                        if k[0] == scope and e.version < version]:
                self._bytes -= self._entries.pop(key).nbytes
                dropped += 1
            for key in [k for k in self._subplans
                        if k[0] == scope and k[1] < version]:
                self._bytes -= self._subplans.pop(key).nbytes
                dropped += 1
            if dropped:
                self._retired.inc(dropped)
        return dropped

    def evict_family(self, family: str) -> int:
        """Failure containment, mirroring ``PlanCache.quarantine``: a
        plan family the serving tier quarantined may have produced
        poisoned rows, so drop its result entries — and every memoized
        intermediate, since a poisoned prefix can't be attributed to one
        family (prefixes are shared across families by design)."""
        dropped = 0
        with self._lock:
            for key in [k for k in self._entries if k[1] == family]:
                self._bytes -= self._entries.pop(key).nbytes
                dropped += 1
            for key in list(self._subplans):
                self._bytes -= self._subplans.pop(key).nbytes
                dropped += 1
            if dropped:
                self._evictions.inc(dropped)
        return dropped

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._subplans.clear()
            self._seen.clear()
            self._bytes = 0

    # -- introspection -----------------------------------------------------

    @property
    def bytes(self) -> int:
        return self._bytes

    @property
    def entries(self) -> int:
        return len(self._entries)

    @property
    def hits(self) -> int:
        return self._hits.value

    @property
    def misses(self) -> int:
        return self._misses.value

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "subplan_entries": len(self._subplans),
                "bytes": self._bytes,
                "budget_bytes": self.config.budget_bytes,
                "hits": self._hits.value,
                "misses": self._misses.value,
                "hit_ratio": self._hit_ratio(),
                "insertions": self._insertions.value,
                "evictions": self._evictions.value,
                "admission_rejects": self._admission_rejects.value,
                "stale_rejects": self._stale_rejects.value,
                "retired": self._retired.value,
                "subplan_hits": self._subplan_hits.value,
                "subplan_misses": self._subplan_misses.value,
            }

"""Backend-generic session orchestration: the parse → IR → logical →
relational → execute pipeline, result records, and entity materialization.

Mirrors the reference's ``RelationalCypherSession`` / ``RelationalCypherRecords``
(ref: okapi-relational/.../relational/api/ — reconstructed, mount empty;
SURVEY.md §2, §3.1).
"""
from __future__ import annotations

import abc
import contextlib
import hashlib
import json
import logging
import threading
from collections import OrderedDict
from typing import Any, Dict, Iterator, List, Mapping, Optional, Tuple

logger = logging.getLogger("caps_tpu")

from caps_tpu import obs
from caps_tpu.obs import clock
from caps_tpu.frontend.parser import normalize_query, parse_query, query_mode
from caps_tpu.ir import blocks as B
from caps_tpu.ir import exprs as E
from caps_tpu.ir.builder import IRBuilder
from caps_tpu.logical.optimizer import LogicalOptimizer
from caps_tpu.logical.planner import LogicalPlanner
from caps_tpu.okapi.catalog import CypherCatalog
from caps_tpu.okapi.config import DEFAULT_CONFIG, EngineConfig
from caps_tpu.okapi.graph import (
    CypherRecords, CypherResult, CypherSession, QualifiedGraphName,
)
from caps_tpu.okapi.schema import Schema
from caps_tpu.okapi.types import (
    CypherType, _CTList, _CTNode, _CTPath, _CTRelationship,
)
from caps_tpu.okapi.values import CypherNode, CypherPath, CypherRelationship
from caps_tpu.relational import ops as R
from caps_tpu.relational.graphs import EmptyGraph, RelationalCypherGraph, ScanGraph
from caps_tpu.relational.header import RecordHeader
from caps_tpu.relational.plan_cache import (
    CachedPlan, PlanCache, PlanParams, PreparedQuery, _plan_nbytes,
    graph_plan_token, param_signature, reset_plan,
)
from caps_tpu.relational.planner import RelationalPlanner
from caps_tpu.relational.shapes import ShapeBucketLattice
from caps_tpu.relational.table import Table, TableFactory
from caps_tpu.relational.updates import (
    UpdateError, VersionedGraph, describe_plan, is_update_statement,
    plan_update, stage_rows,
)
from caps_tpu.serve.deadline import cancel_scope, checkpoint


class NondeterministicResultError(RuntimeError):
    """Raised by the determinism check (EngineConfig.determinism_check)
    when a replayed query yields a different result multiset."""


# -- degraded execution (failure containment, caps_tpu/serve/) --------------
#
# When the serving tier suspects shared cached state (a quarantined plan
# entry, a poisoned fused memo), it re-executes the query in a degraded
# mode that provably avoids that state: ``no_plan_cache`` bypasses the
# session plan cache in BOTH directions (no lookup, no store — a
# degraded run must not mutate shared state), ``no_fused`` additionally
# forces per-operator eager execution on backends with a fused
# record/replay executor.  The flags are per-THREAD: one worker's
# degraded re-execution must not strip another worker's fast path.

_degraded_tls = threading.local()


def degraded_state() -> Tuple[bool, bool]:
    """(no_plan_cache, no_fused) for the calling thread."""
    return (getattr(_degraded_tls, "no_plan_cache", False),
            getattr(_degraded_tls, "no_fused", False))


@contextlib.contextmanager
def degraded_execution(no_plan_cache: bool = True,
                       no_fused: bool = False) -> Iterator[None]:
    """Run queries on this thread in a degraded mode (see above).
    Nests by OR-ing: an unfused region inside a replan region stays
    unfused."""
    prev = degraded_state()
    _degraded_tls.no_plan_cache = prev[0] or no_plan_cache
    _degraded_tls.no_fused = prev[1] or no_fused
    try:
        yield
    finally:
        _degraded_tls.no_plan_cache, _degraded_tls.no_fused = prev


def result_digest(result: "CypherResult") -> str:
    """Order-insensitive sha256 of a result's rows (multiset digest):
    per-row digests are sorted before hashing, so any valid row order
    yields the same digest."""
    rows = result.to_maps()
    row_digests = sorted(
        hashlib.sha256(repr(sorted(r.items())).encode()).hexdigest()
        for r in rows)
    return hashlib.sha256("".join(row_digests).encode()).hexdigest()


class RelationalCypherRecords(CypherRecords):
    def __init__(self, session: "RelationalCypherSession", header: RecordHeader,
                 table: Table, columns: Tuple[str, ...],
                 graph: Optional[RelationalCypherGraph] = None):
        self._session = session
        self._header = header
        self._table = table
        self._columns = tuple(columns)
        self._graph = graph

    @property
    def columns(self) -> Tuple[str, ...]:
        return self._columns

    @property
    def header(self) -> RecordHeader:
        return self._header

    @property
    def table(self) -> Table:
        return self._table

    def size(self) -> int:
        return self._table.exact_size()

    # -- materialization ----------------------------------------------------

    def to_maps(self) -> List[Dict[str, Any]]:
        header, table = self._header, self._table
        n = table.exact_size()
        out: List[Dict[str, Any]] = [dict() for _ in range(n)]
        for name in self._columns:
            values = self._materialize_var(name, header, table, n)
            for i in range(n):
                out[i][name] = values[i]
        return out

    def _materialize_var(self, name: str, header: RecordHeader, table: Table,
                         n: int) -> List[Any]:
        var = E.Var(name)
        t = header.type_of(var).material
        if isinstance(t, _CTNode):
            return self._materialize_nodes(name, header, table, n)
        if isinstance(t, _CTRelationship):
            return self._materialize_rels(name, header, table, n)
        if isinstance(t, _CTList) and isinstance(t.inner.material,
                                                 _CTRelationship):
            ids_list = table.column_values(header.column(var))
            lookup = self._rel_lookup()
            return [None if ids is None else
                    [self._rel_from_lookup(i, lookup) for i in ids]
                    for ids in ids_list]
        if isinstance(t, _CTList) and isinstance(t.inner.material, _CTNode):
            ids_list = table.column_values(header.column(var))
            lookup = self._node_lookup()
            return [None if ids is None else
                    [self._node_from_lookup(i, lookup) for i in ids]
                    for ids in ids_list]
        if isinstance(t, _CTPath):
            return self._materialize_paths(name, header, table, n)
        return table.column_values(header.column(var))

    def _materialize_nodes(self, name, header, table, n) -> List[Any]:
        var = E.Var(name)
        ids = table.column_values(header.column(var))
        label_cols = []
        prop_cols = []
        for e in header.exprs:
            if isinstance(e, E.HasLabel) and e.node == var:
                label_cols.append((e.label, table.column_values(header.column(e))))
            elif isinstance(e, E.Property) and e.entity == var:
                prop_cols.append((e.key, table.column_values(header.column(e))))
        if not label_cols and not prop_cols:
            # bare id column (e.g. an indexed element of nodes(p)): fill
            # labels/properties from the graph's host-side lookup
            lookup = self._node_lookup()
            return [None if i is None else self._node_from_lookup(i, lookup)
                    for i in ids]
        out = []
        for i in range(n):
            if ids[i] is None:
                out.append(None)
                continue
            labels = tuple(lbl for lbl, col in label_cols if col[i] is True)
            props = {k: col[i] for k, col in prop_cols if col[i] is not None}
            out.append(CypherNode(ids[i], labels, props))
        return out

    def _materialize_rels(self, name, header, table, n) -> List[Any]:
        var = E.Var(name)
        ids = table.column_values(header.column(var))
        if not header.has(E.StartNode(var)):
            # bare rel-id column (e.g. an indexed element of
            # relationships(p)): materialize via the graph lookup
            lookup = self._rel_lookup()
            return [None if i is None else self._rel_from_lookup(i, lookup)
                    for i in ids]
        srcs = table.column_values(header.column(E.StartNode(var)))
        tgts = table.column_values(header.column(E.EndNode(var)))
        types = table.column_values(header.column(E.Type(var)))
        prop_cols = []
        for e in header.exprs:
            if isinstance(e, E.Property) and e.entity == var:
                prop_cols.append((e.key, table.column_values(header.column(e))))
        out = []
        for i in range(n):
            if ids[i] is None:
                out.append(None)
                continue
            props = {k: col[i] for k, col in prop_cols if col[i] is not None}
            out.append(CypherRelationship(ids[i], srcs[i], tgts[i],
                                          types[i] or "", props))
        return out

    def _materialize_paths(self, name, header, table, n) -> List[Any]:
        """Assemble path values: start node id + per-hop rel id (or rel-id
        list) columns, walking each hop's stored endpoints to find the next
        node (direction-agnostic: next = the endpoint that isn't current,
        which also handles undirected matches and self-loops)."""
        var = E.Var(name)
        starts = table.column_values(header.column(var))
        segs = sorted(
            ((e.index, e.is_varlen, table.column_values(header.column(e)))
             for e in header.exprs
             if isinstance(e, E.PathSeg) and e.path == var),
            key=lambda s: s[0])
        rel_lk = self._rel_lookup()
        node_lk = self._node_lookup()
        out: List[Any] = []
        for i in range(n):
            if starts[i] is None:
                out.append(None)
                continue
            cur = starts[i]
            nodes = [self._node_from_lookup(cur, node_lk)]
            rels: List[CypherRelationship] = []
            dead = False
            for _, is_varlen, col in segs:
                cell = col[i]
                if cell is None:
                    dead = True  # null hop (optional path): whole path null
                    break
                for rid in (cell if is_varlen else [cell]):
                    rel = self._rel_from_lookup(rid, rel_lk)
                    rels.append(rel)
                    cur = rel.end if rel.start == cur else rel.start
                    nodes.append(self._node_from_lookup(cur, node_lk))
            out.append(None if dead else CypherPath(tuple(nodes), tuple(rels)))
        return out

    def _rel_lookup(self) -> Dict[int, Tuple[int, int, str, Dict[str, Any]]]:
        if self._graph is None:
            return {}
        return self._graph.rel_lookup()

    def _node_lookup(self) -> Dict[int, Tuple[Tuple[str, ...], Dict[str, Any]]]:
        if self._graph is None:
            return {}
        return self._graph.node_lookup()

    def _node_from_lookup(self, nid, lookup) -> CypherNode:
        if nid in lookup:
            labels, props = lookup[nid]
            return CypherNode(nid, labels, props)
        return CypherNode(nid)

    def _rel_from_lookup(self, rid, lookup) -> CypherRelationship:
        if rid in lookup:
            src, tgt, typ, props = lookup[rid]
            return CypherRelationship(rid, src, tgt, typ, props)
        return CypherRelationship(rid, -1, -1, "")


class RelationalCypherResult(CypherResult):
    def __init__(self, records: Optional[RelationalCypherRecords] = None,
                 graph: Optional[RelationalCypherGraph] = None,
                 plans: Optional[Dict[str, str]] = None,
                 metrics: Optional[Dict[str, Any]] = None):
        self._records = records
        self._graph = graph
        self.plans = plans or {}
        self.metrics = metrics or {}
        # PROFILE annotation (obs/profile.py): plain-dict operator tree
        # with per-node rows/seconds/bytes; None unless profiled.
        self.profile: Optional[Dict[str, Any]] = None

    @property
    def records(self) -> Optional[RelationalCypherRecords]:
        return self._records

    @property
    def graph(self) -> Optional[RelationalCypherGraph]:
        return self._graph

    def to_maps(self) -> List[Dict[str, Any]]:
        return self._records.to_maps() if self._records is not None else []

    def explain(self) -> str:
        parts = []
        for phase in ("ir", "logical", "relational", "cost", "profile"):
            if phase in self.plans:
                parts.append(f"=== {phase.upper()} ===\n{self.plans[phase]}")
        return "\n\n".join(parts)


class RelationalCypherSession(CypherSession):
    """Backend-generic session; concrete backends provide a TableFactory."""

    def __init__(self, config: Optional[EngineConfig] = None):
        self._catalog = CypherCatalog()
        self.config = config or DEFAULT_CONFIG
        self._ambient = EmptyGraph(self)
        # Observability (caps_tpu/obs/): the session tracer collects
        # query → phase → operator spans; the registry absorbs the
        # session's counters (plan cache, per-phase histograms) behind
        # metrics_snapshot().  Tracing is off unless config.trace or a
        # PROFILE query force-enables it.
        self.metrics_registry = obs.MetricsRegistry()
        self.tracer = obs.Tracer(enabled=self.config.trace)
        # Observed per-operator statistics (obs/telemetry.py): every
        # execution folds its op_metrics entries in, keyed by
        # (plan family, operator id) — the calibration substrate the
        # cost-based planner (relational/cost.py) reads, and the
        # model-divergence detector that triggers re-planning.  Fused-
        # replay aware for free: the entries recorded are the same ones
        # PROFILE annotates.
        self.op_stats = obs.OpStatsStore(
            registry=self.metrics_registry,
            replan_threshold=max(1, self.config.replan_threshold or 1),
            # late-binding: the lattice is constructed below; divergence
            # only counts model error big enough to change the padded
            # launch bucket (and fused-replay "rows" ARE served sizes)
            bucket_fn=lambda n: self.shape_lattice.bucket(n))
        # Divergence-triggered re-planning (ROADMAP item 3): families
        # whose executions keep diverging from the MODEL estimate
        # retire their cached plans (plan_cache.evict_family — the
        # quarantine path) and re-plan with calibrated statistics.
        # Listeners (the serving tier) observe structured replan.*
        # events; the pending set marks families whose NEXT cold plan
        # is the re-plan completion.
        self.replan_listeners: List[Any] = []
        self._replanned_pending: set = set()
        # Compile telemetry (obs/compile.py): every compile boundary —
        # the cold plan phase here, fused record runs on the TPU
        # backend, count-pushdown / dist-join program builds — charges
        # this ledger per plan family (compile.* counters, the
        # warmup_report substrate).
        self.compile_ledger = obs.CompileLedger(
            registry=self.metrics_registry)
        self._profiling = False
        # Prepared-statement plan cache (relational/plan_cache.py): keyed
        # value-independently; catalog mutations evict dependent entries.
        self.plan_cache = PlanCache(self.config.plan_cache_size,
                                    enabled=self.config.use_plan_cache,
                                    registry=self.metrics_registry)
        # Snapshot-keyed result & subplan cache (relational/
        # result_cache.py): attached by the serving tier (ServerConfig
        # .result_cache) — None means every read pays the device path.
        # Must exist before the MemoryLedger below registers its
        # mem.result_cache_bytes gauge over it.
        self.result_cache = None
        # Memory ledger (obs/ledger.py): live mem.* gauges over the plan
        # cache, string pool, tracked graphs, and device allocator stats.
        self.memory_ledger = obs.MemoryLedger(
            registry=self.metrics_registry, session=self)
        # Scoped catalog eviction: a mutation of graph X drops exactly
        # X's dependents from the plan cache (okapi/catalog.py
        # dep_token) — unrelated graphs' cached plans survive.
        self._catalog.subscribe(
            lambda _version, qgn: self.plan_cache.evict_dependents(qgn))
        # per-thread recorder of catalog graphs resolved while planning
        # (they become the cached plan's catalog_deps)
        self._deps_tls = threading.local()
        # Shape-bucket lattice (relational/shapes.py): the session-level
        # view of operator-launch size buckets.  Device backends adopt
        # it as their padding ladder; ``seed_shape_buckets()`` folds
        # observed op_stats sizes in, and the persistent plan store
        # (relational/plan_store.py) carries the boundaries across
        # processes.
        self.shape_lattice = ShapeBucketLattice(
            self.config.bucket_sizes, registry=self.metrics_registry)
        # Warm-path binding recorder: the last JSON-able parameter
        # binding seen per plan family, captured ONLY on the cold path
        # (a plan-cache hit records nothing — zero hot-path cost).  The
        # plan store persists these so a fresh process's AOT warmup
        # (serve/warmup.py) can re-execute each hot family with a
        # shape-faithful binding instead of synthetic values.
        from caps_tpu.obs.lockgraph import make_lock
        self._warm_bindings: "OrderedDict[str, Tuple[str, Dict]]" = \
            OrderedDict()
        self._warm_bindings_lock = make_lock(
            "session._warm_bindings_lock")
        self._warm_bindings_cap = 128

    # -- backend SPI --------------------------------------------------------

    @property
    @abc.abstractmethod
    def table_factory(self) -> TableFactory:
        ...

    # -- public API ---------------------------------------------------------

    @property
    def catalog(self) -> CypherCatalog:
        return self._catalog

    def cypher(self, query: str,
               parameters: Optional[Mapping[str, Any]] = None) -> CypherResult:
        return self.cypher_on_graph(self._ambient, query, parameters)

    def clone(self) -> "RelationalCypherSession":
        """A fresh session of the same class and config — the serving
        tier's per-device replica seam (serve/devices.py): the clone
        owns its own plan cache, catalog, metrics registry, and (on
        device backends) string pool and fused memos.  Nothing compiled
        or cached is shared with this session, so one replica's
        corruption or quarantine can never leak into another's."""
        return type(self)(config=self.config)

    def prepare(self, query: str,
                graph: Optional[RelationalCypherGraph] = None) -> PreparedQuery:
        """Prepare a query for repeated execution: parses (and validates)
        once, and every ``.run(params)`` serves the planned operator tree
        from the session plan cache — the steady-state serving path skips
        parse/IR/logical/relational planning entirely."""
        return PreparedQuery(self, query, graph)

    def cypher_batch(self, graph: RelationalCypherGraph,
                     items: List[Tuple[str, Mapping[str, Any]]],
                     scopes: Optional[List] = None) -> List[Any]:
        """Micro-batched execution (the serving tier's hot path —
        ``caps_tpu/serve/batcher.py``): ``items`` is a list of
        ``(query, params)`` pairs that share one plan-cache key family,
        executed back-to-back as ONE batch — a single tracer span, and
        after the first member every later one re-binds the same cached
        plan, so the whole batch runs without re-entering the scalar
        frontend (on the TPU backend the members' fused replays
        dispatch as one uninterrupted async stream).

        Returns a list aligned with ``items``; each element is the
        member's CypherResult *or the exception it raised* — one
        member's deadline expiry must not fail the rest of the batch.
        ``scopes`` optionally installs a per-member
        :class:`~caps_tpu.serve.deadline.CancelScope`."""
        out: List[Any] = []
        with self._observed(), self.tracer.span("batch", kind="query",
                                                n=len(items)):
            for i, (query, params) in enumerate(items):
                scope = scopes[i] if scopes is not None else None
                try:
                    with cancel_scope(scope):
                        out.append(self.cypher_on_graph(graph, query,
                                                        params))
                except Exception as ex:
                    out.append(ex)
        self.metrics_registry.observe("session.batch_size", len(items))
        return out

    def cypher_degraded(self, graph: RelationalCypherGraph, query: str,
                        parameters: Optional[Mapping[str, Any]] = None, *,
                        no_plan_cache: bool = True,
                        no_fused: bool = False) -> CypherResult:
        """Degraded re-execution for failure containment (the serving
        tier's ladder — see :func:`degraded_execution`): bypass the plan
        cache (fresh plan, nothing stored) and optionally force unfused
        per-operator execution.  Correct results, none of the shared
        cached state a poisoned entry could hide in."""
        with degraded_execution(no_plan_cache=no_plan_cache,
                                no_fused=no_fused):
            return self.cypher_on_graph(graph, query, parameters)

    def cypher_on_graph(self, graph: RelationalCypherGraph, query: str,
                        parameters: Optional[Mapping[str, Any]] = None
                        ) -> CypherResult:
        # EXPLAIN / PROFILE prefixes strip HERE, before any cache key is
        # formed — a PROFILE run hits the same plan-cache / fused-memo
        # entries as the plain query (and vice versa), never a poisoned
        # key.
        mode, body = query_mode(query)
        if isinstance(graph, VersionedGraph):
            # snapshot isolation: a READ resolves the mutable handle to
            # the latest committed snapshot ONCE, here, and runs on it
            # end to end — commits that land meanwhile are invisible.
            # Writes keep the handle (they serialize on its commit
            # lock); so does EXPLAIN of a write.
            from caps_tpu.relational.updates import is_update_query
            if not is_update_query(body if mode is not None else query):
                graph = graph.current()
        if mode == "explain":
            return self._explain_on_graph(graph, body, parameters)
        if mode == "profile":
            return self._profile_on_graph(graph, body, parameters)
        # Compile attribution (obs/compile.py): every compile boundary
        # crossed below — the cold plan phase, a fused record run, a
        # count-pushdown or dist-join program build — charges the
        # session ledger under THIS query's plan-cache family, and the
        # per-query total is stamped into the result metrics (the
        # serving tier copies it into the request's ledger dict).
        with obs.compile_attributed(self.compile_ledger,
                                    normalize_query(query)) as charges:
            with self._observed():
                result = self._cypher_on_graph(graph, query, parameters)
            if self.config.determinism_check and result.records is not None:
                # SURVEY.md §5.2: deterministic replay — run the same
                # query a second time and compare multiset digests.
                again = self._cypher_on_graph(graph, query, parameters)
                d1 = result_digest(result)
                d2 = result_digest(again)
                if d1 != d2:
                    raise NondeterministicResultError(
                        f"query produced different results on replay "
                        f"({d1[:12]} vs {d2[:12]}): {query!r}")
                result.metrics["determinism_digest"] = d1
        self._stamp_compile_charges(result, charges)
        if charges:
            # warm-path binding capture: ANY binding that crossed a
            # compile boundary (a cold plan, a fused record, a
            # per-value count-pushdown build) is a binding AOT warmup
            # must cover — record it for the plan store
            self._note_warm_binding(normalize_query(query), query,
                                    dict(parameters or {}))
        return result

    @staticmethod
    def _stamp_compile_charges(result, charges) -> None:
        """Per-query compile accounting onto the result metrics:
        ``compile_s_charged`` is ALWAYS present (0.0 on a fully warmed
        path — the serving tier and the replay tests read it), the
        per-charge detail only when something actually compiled."""
        if result.metrics is None:
            return
        result.metrics["compile_s_charged"] = round(
            sum(c["seconds"] for c in charges), 9)
        if charges:
            result.metrics["compile_charges"] = [
                {"kind": c["kind"], "seconds": round(c["seconds"], 9),
                 "recompile": c["recompile"]} for c in charges]

    def _make_cost_model(self, graph: RelationalCypherGraph,
                         family: Optional[str] = None):
        """One query's cost model (relational/cost.py): the graph's
        ingest-time statistics sketch + the session shape lattice +
        observed-actuals calibration for ``family``.  None with the
        model disabled (EngineConfig.use_cost_model=False — the
        heuristic-only baseline bench.py plan mode compares against)."""
        if not self.config.use_cost_model:
            return None
        from caps_tpu.relational.cost import CostModel
        from caps_tpu.relational.stats import graph_statistics
        return CostModel(graph_statistics(graph),
                         lattice=self.shape_lattice,
                         op_stats=self.op_stats,
                         compile_ledger=self.compile_ledger,
                         config=self.config, family=family,
                         registry=self.metrics_registry)

    def _plan_ir(self, graph: RelationalCypherGraph, ir,
                 plan_params, params: Dict[str, Any],
                 family: Optional[str] = None):
        """Logical planning + optimization + relational planning for one
        (non-catalog) IR statement.  The ONE planning pipeline shared by
        the execute path, EXPLAIN, and CATALOG CREATE GRAPH — so the
        plan EXPLAIN renders is by construction the plan that executes,
        and the cost model's decisions (chain orientation, physical
        strategy, per-operator estimates) are identical in both.
        Returns (logical, context, rel_planner, root, t_logical_done);
        the model rides ``rel_planner.cost_model``."""
        model = self._make_cost_model(graph, family)
        with self.tracer.span("logical", kind="phase"):
            logical = LogicalPlanner(graph.schema, self._schema_resolver,
                                     plan_params).process(ir)
            logical = LogicalOptimizer(model).process(logical)
        t3 = clock.now()
        with self.tracer.span("relational", kind="phase"):
            context = R.RelationalRuntimeContext(self, params)
            rel_planner = RelationalPlanner(context, graph,
                                            self._graph_resolver,
                                            cost_model=model)
            root = rel_planner.process(logical)
        if model is not None:
            from caps_tpu.relational.cost import annotate_plan
            try:
                rel_planner.cost_summary = annotate_plan(root, model)
            except Exception:  # pragma: no cover — pricing must not fail
                rel_planner.cost_summary = None
        return logical, context, rel_planner, root, t3

    @contextlib.contextmanager
    def _observed(self):
        """Activate this session's tracer for the duration of a query so
        session-less instrumentation (collectives, the device backend's
        join accounting) lands in it.  With tracing disabled the only
        cost is one enabled check."""
        if not self.tracer.enabled:
            yield
            return
        with obs.activate(self.tracer):
            yield

    # -- EXPLAIN / PROFILE ---------------------------------------------------

    def _explain_on_graph(self, graph: RelationalCypherGraph, query: str,
                          parameters: Optional[Mapping[str, Any]] = None
                          ) -> CypherResult:
        """``EXPLAIN <query>``: run the full planning frontend and return
        the rendered plan trees WITHOUT executing anything — no operator
        ever computes, no catalog mutation applies (EXPLAIN of CATALOG
        CREATE/DROP GRAPH plans the inner query but stores/drops
        nothing)."""
        t0 = clock.now()
        params = dict(parameters or {})
        plan_params = PlanParams(params)
        with self._observed(), self.tracer.span("explain", kind="query",
                                                query=query):
            stmt = parse_query(query)
            if is_update_statement(stmt):
                # EXPLAIN of a write: render the staged update program
                # (and plan — not execute — its read half) without
                # committing anything
                up = plan_update(stmt)
                plans = {"updates": describe_plan(up)}
                if up.read_ast is not None:
                    read_graph = graph.current() \
                        if isinstance(graph, VersionedGraph) else graph
                    ir = IRBuilder(read_graph.schema,
                                   self._schema_resolver,
                                   plan_params).process(up.read_ast)
                    logical, _ctx, _planner, root, _t = self._plan_ir(
                        read_graph, ir, plan_params, params)
                    plans["logical"] = logical.pretty()
                    plans["relational"] = root.pretty()
                metrics = {"mode": "explain", "plan_s": clock.now() - t0,
                           "rows": 0}
                return RelationalCypherResult(plans=plans, metrics=metrics)
            ir = IRBuilder(graph.schema, self._schema_resolver,
                           plan_params).process(stmt)
            plans: Dict[str, str] = {}
            pretty = getattr(ir, "pretty", None)
            if pretty is not None:
                plans["ir"] = pretty()
            if isinstance(ir, B.DropGraphStatement):
                plans.setdefault("ir", f"DropGraph({ir.qgn})")
                metrics = {"mode": "explain", "plan_s": clock.now() - t0,
                           "rows": 0}
                return RelationalCypherResult(plans=plans, metrics=metrics)
            inner = ir.inner if isinstance(ir, B.CreateGraphStatement) else ir
            logical, _context, planner, root, _t3 = self._plan_ir(
                graph, inner, plan_params, params,
                family=normalize_query(query))
            plans["logical"] = logical.pretty()
            plans["relational"] = root.pretty()
            summary = getattr(planner, "cost_summary", None)
            if summary and summary.get("decisions"):
                # estimated-vs-chosen: the model's decision log rides
                # EXPLAIN next to the annotated operator tree
                plans["cost"] = planner.cost_model.render_decisions()
        metrics = {"mode": "explain", "plan_s": clock.now() - t0, "rows": 0}
        return RelationalCypherResult(plans=plans, metrics=metrics)

    def _profile_on_graph(self, graph: RelationalCypherGraph, query: str,
                          parameters: Optional[Mapping[str, Any]] = None
                          ) -> CypherResult:
        """``PROFILE <query>``: execute with the tracer force-enabled and
        annotate every relational operator with its measured span
        (rows / wall time / bytes; device time when per-op sync is on —
        config.profile_sync_each_op)."""
        prev_profiling = self._profiling
        self._profiling = True
        try:
            with self.tracer.forced(
                    sync_device=self.config.profile_sync_each_op):
                with obs.activate(self.tracer):
                    with self.tracer.span("query", kind="query",
                                          query=query, mode="profile"), \
                            obs.compile_attributed(
                                self.compile_ledger,
                                normalize_query(query)) as charges:
                        result = self._cypher_on_graph(graph, query,
                                                       parameters)
            self._stamp_compile_charges(result, charges)
        finally:
            self._profiling = prev_profiling
        if result.metrics is not None:
            result.metrics["mode"] = "profile"
        if result.profile is not None:
            # copy-on-write: the plans dict may be SHARED with a cached
            # plan entry — annotating in place would leak profile text
            # into later non-profile results served from the cache
            result.plans = dict(result.plans)
            result.plans["profile"] = obs.render_profile(result.profile)
        return result

    # -- metrics / trace export ----------------------------------------------

    def metrics_snapshot(self) -> Dict[str, Any]:
        """One flat dict of every session-level stat: the metrics
        registry (plan-cache counters, per-phase histograms) plus
        derived plan-cache numbers.  Backends extend this with their
        device counters.  Consumers measure intervals with
        ``obs.diff_snapshots(before, after)``."""
        snap = self.metrics_registry.snapshot()
        for k, v in self.plan_cache.stats().items():
            snap[f"plan_cache.{k}"] = v
        snap["tracer.spans"] = len(self.tracer.spans)
        snap["tracer.dropped"] = self.tracer.dropped
        return snap

    def export_trace(self, path: str, fmt: str = "chrome") -> str:
        """Dump the tracer's collected spans: ``fmt='chrome'`` writes a
        ``chrome://tracing``-loadable file, ``fmt='jsonl'`` one JSON
        object per span."""
        if fmt == "chrome":
            obs.write_chrome_trace(self.tracer.spans, path)
        elif fmt == "jsonl":
            obs.write_jsonl(self.tracer.spans, path)
        else:
            raise ValueError(f"unknown trace format {fmt!r}")
        return path

    # -- warm path (serve/warmup.py + relational/plan_store.py) --------------

    #: distinct compile-charging bindings retained per family — enough
    #: to cover a per-value compile cache's rotation (the count-pushdown
    #: closures) without letting ad-hoc values grow the store
    _WARM_BINDINGS_PER_FAMILY = 4

    def _note_warm_binding(self, family: str, query: str,
                           params: Mapping[str, Any]) -> None:
        """Record a compile-charging binding for the family — only when
        the values are JSON-able (the store is plain JSON; anything else
        is silently skipped, warmup then simply cannot cover that
        binding).  Distinct bindings are kept up to a small per-family
        cap: every one of them crossed a compile boundary, so every one
        is a binding AOT warmup should pre-pay."""
        try:
            token = json.dumps(dict(params), sort_keys=True)
            clean = json.loads(token)
        except (TypeError, ValueError):
            return
        with self._warm_bindings_lock:
            ent = self._warm_bindings.pop(family, None)
            if ent is None:
                ent = (query, [], set())
            q, bindings, tokens = ent
            if token not in tokens and \
                    len(bindings) < self._WARM_BINDINGS_PER_FAMILY:
                tokens.add(token)
                bindings.append(clean)
            self._warm_bindings[family] = (q, bindings, tokens)
            while len(self._warm_bindings) > self._warm_bindings_cap:
                self._warm_bindings.popitem(last=False)

    def warmup_bindings(self) -> List[Dict[str, Any]]:
        """Per hot plan family: the original query text and every
        retained compile-charging binding — the plan store's family
        entries (``relational/plan_store.py collect_warm_state``)."""
        with self._warm_bindings_lock:
            return [{"family": fam, "query": q,
                     "params": dict(bs[0]) if bs else {},
                     "bindings": [dict(b) for b in bs]}
                    for fam, (q, bs, _toks) in
                    self._warm_bindings.items()]

    def seed_shape_buckets(self) -> int:
        """Fold observed operator-launch sizes (``op_stats`` actual max
        rows) into the session's shape-bucket lattice.  Returns how many
        boundaries were added."""
        return self.shape_lattice.seed_from_op_stats(self.op_stats)

    def _plan_cache_key(self, graph: RelationalCypherGraph, query: str,
                        params: Mapping[str, Any]) -> Optional[Tuple]:
        gtok = graph_plan_token(graph)
        if gtok is None:
            return None
        # catalog consistency is per-plan (CachedPlan.catalog_deps),
        # not part of the key: a catalog mutation invalidates exactly
        # its dependents instead of re-keying the whole session
        return (normalize_query(query), gtok, param_signature(params))

    def _cypher_on_graph(self, graph: RelationalCypherGraph, query: str,
                         parameters: Optional[Mapping[str, Any]] = None
                         ) -> CypherResult:
        t0 = clock.now()
        params = dict(parameters or {})
        tracer = self.tracer

        no_plan_cache, _no_fused = degraded_state()
        cache_key: Optional[Tuple] = None
        if self.plan_cache.enabled and not no_plan_cache:
            cache_key = self._plan_cache_key(graph, query, params)
            if cache_key is not None:
                cached = self.plan_cache.lookup(cache_key, params,
                                                catalog=self._catalog)
                if cached is not None:
                    return self._run_cached(cached, query, params, t0,
                                            family=cache_key[0])

        # Cold path: the full frontend.  Planning sees the parameters
        # through a PlanParams view, which records any plan-time VALUE
        # read as a cache specialization; runtime parameter reads go
        # through the context's plain dict and stay free.
        plan_params = PlanParams(params)
        with tracer.span("parse", kind="phase"):
            stmt = parse_query(query)
        checkpoint("parse")

        if is_update_statement(stmt):
            # the write path: read on the current snapshot, stage,
            # commit atomically (relational/updates.py)
            return self._run_update(graph, stmt, query, params, t0)

        t1 = clock.now()
        with self._record_catalog_deps() as catalog_deps:
            with tracer.span("ir", kind="phase"):
                ir = IRBuilder(graph.schema, self._schema_resolver,
                               plan_params).process(stmt)
            t2 = clock.now()

            if isinstance(ir, B.CreateGraphStatement):
                return self._run_create_graph(graph, ir, params)
            if isinstance(ir, B.DropGraphStatement):
                self._catalog.delete(ir.qgn)
                return RelationalCypherResult()

            family = cache_key[0] if cache_key is not None \
                else normalize_query(query)
            logical, context, rel_planner, root, t3 = self._plan_ir(
                graph, ir, plan_params, params, family=family)
        checkpoint("plan")
        t4 = clock.now()
        # Compile ledger (obs/compile.py): the cold plan phase is a
        # compile boundary — a cache hit never pays it again, and a
        # post-quarantine re-plan of the same (family, signature) shows
        # up as a re-compile.
        obs.compile_charge("plan", t4 - t0,
                           shape=repr(param_signature(params)))

        plans = {"ir": ir.pretty(), "logical": logical.pretty(),
                 "relational": root.pretty()}
        cost_summary = getattr(rel_planner, "cost_summary", None)
        if cost_summary and cost_summary.get("decisions"):
            plans["cost"] = rel_planner.cost_model.render_decisions()
        if family in self._replanned_pending:
            # this cold plan IS the divergence-triggered re-plan: its
            # planning seconds were charged to the compile ledger above
            # (the event log's compile.charged accounts them), and the
            # new plan's estimates are calibrated from observed actuals
            self._replanned_pending.discard(family)
            self.metrics_registry.counter("replan.completed").inc()
            self._notify_replan("replan.completed", {
                "family": family, "plan_s": t4 - t0,
                "root_est_rows": (cost_summary or {}).get("root_est_rows"),
                "decisions": (cost_summary or {}).get("decisions"),
            })
        if self.config.print_ir:
            print(plans["ir"])
        if self.config.print_logical_plan:
            print(plans["logical"])
        if self.config.print_relational_plan:
            print(plans["relational"])

        result_graph: Optional[RelationalCypherGraph] = None
        records: Optional[RelationalCypherRecords] = None
        with tracer.span("execute", kind="phase"):
            if logical.returns_graph:
                result_graph = self._evaluate_graph(root)
            else:
                rcache = self.result_cache
                if rcache is not None:
                    # snapshot-keyed subplan reuse: seed memoized
                    # scan→filter intermediates before pulling the root
                    rcache.seed_subplans(root)
                header, table = root.result
                if rcache is not None:
                    # capture BEFORE any reset_plan clears the memos
                    rcache.store_subplans(root)
                records = RelationalCypherRecords(
                    self, header, table, logical.result_fields,
                    graph=rel_planner.current_graph)
        checkpoint("execute")
        t5 = clock.now()

        metrics = {
            "parse_s": t1 - t0, "ir_s": t2 - t1, "plan_s": t3 - t2,
            "relational_s": t4 - t3, "execute_s": t5 - t4,
            # size_hint: never syncs (generic replay may only know an
            # upper bound until the result is materialized)
            "rows": records.table.size_hint() if records is not None else 0,
            "operators": context.op_metrics,
            # roofline numerator: bytes the operators pulled through
            # memory; achieved GB/s = bytes_touched / execute_s
            "bytes_touched": sum(m.get("bytes_in", 0)
                                 for m in context.op_metrics),
            "plan_cache": "miss" if cache_key is not None else "off",
        }
        if self.config.print_timings:
            print(f"[caps-tpu] timings: {metrics}")
        logger.debug("query %r: %d rows in %.1f ms", query,
                     metrics["rows"], 1e3 * (t5 - t0))
        self.metrics_registry.observe("query.plan_s", t4 - t0)
        self.metrics_registry.observe("query.execute_s", t5 - t4)
        # observed-statistics fold: the plan family is the cache key's
        # normalized query text (computed lazily when the cache was
        # bypassed — uncacheable graph, degraded run, cache off)
        self.op_stats.record(family, context.op_metrics)
        self._maybe_replan()
        if self._profiling:
            # snapshot per-operator measurements into plain dicts BEFORE
            # the cache store resets the tree (obs/profile.py)
            result_profile = obs.profile_tree(root, context)
        else:
            result_profile = None

        if (cache_key is not None and records is not None
                and not logical.returns_graph and plan_params.cacheable):
            entry = CachedPlan(
                root=root, result_fields=logical.result_fields, plans=plans,
                records_graph=rel_planner.current_graph, context=context,
                spec_key=plan_params.spec_key(),
                cold_phase_s=t4 - t0,
                nbytes=_plan_nbytes(plans, root, context=context,
                                    catalog_deps=catalog_deps),
                catalog_deps=tuple(sorted(catalog_deps.items())),
                query_text=query)
            # Drop the memoized results before parking the tree in the
            # cache: the records object holds the (header, table) refs,
            # so a cached plan retains no tables between executions.
            reset_plan(root)
            self.plan_cache.store(cache_key, entry)
        result = RelationalCypherResult(records, result_graph, plans, metrics)
        result.profile = result_profile
        return result

    def _run_cached(self, plan: CachedPlan, query: str,
                    params: Dict[str, Any], t0: float,
                    family: Optional[str] = None) -> CypherResult:
        """Execute a cached relational operator tree with fresh parameter
        bindings: swap the shared runtime context's parameters, clear the
        per-run memos, and pull the root's result.  parse/ir/plan/
        relational metrics are ~0 by construction (only the cache lookup
        preceded this)."""
        # The plan's operator tree and runtime context are shared
        # mutable state (parameter dict, per-op result memos): concurrent
        # executions of the SAME cached plan serialize on its lock —
        # different plans still run independently (fine-grained, not a
        # cache-wide lock).
        with plan.exec_lock:
            context = plan.context
            context.rebind(params)
            reset_plan(plan.root)
            rcache = self.result_cache
            if rcache is not None:
                # seed AFTER reset_plan (reset clears seeded memos)
                rcache.seed_subplans(plan.root)
            t1 = clock.now()
            try:
                with self.tracer.span("execute", kind="phase",
                                      plan_cache="hit"):
                    header, table = plan.root.result
                    if rcache is not None:
                        # capture before the finally's reset_plan
                        rcache.store_subplans(plan.root)
                    records = RelationalCypherRecords(
                        self, header, table, plan.result_fields,
                        graph=plan.records_graph)
                op_metrics = context.op_metrics
                result_profile = (obs.profile_tree(plan.root, context)
                                  if self._profiling else None)
            finally:
                # the records object owns (header, table) now; the
                # parked tree must not pin device buffers until its next
                # execution — including when a deadline/cancel aborted
                # the run mid-tree (a routine serving path) with partial
                # operator memos already computed
                reset_plan(plan.root)
        checkpoint("execute")
        t2 = clock.now()
        if self.config.print_ir:
            print(plan.plans["ir"])
        if self.config.print_logical_plan:
            print(plan.plans["logical"])
        if self.config.print_relational_plan:
            print(plan.plans["relational"])
        metrics = {
            "parse_s": 0.0, "ir_s": 0.0, "plan_s": 0.0, "relational_s": 0.0,
            "plan_cache_lookup_s": t1 - t0,
            "execute_s": t2 - t1,
            "rows": table.size_hint(),
            "operators": op_metrics,
            "bytes_touched": sum(m.get("bytes_in", 0)
                                 for m in op_metrics),
            "plan_cache": "hit",
            "plan_cache_saved_s": plan.cold_phase_s,
        }
        if self.config.print_timings:
            print(f"[caps-tpu] timings: {metrics}")
        logger.debug("query %r: %d rows in %.1f ms (plan cache hit)",
                     query, metrics["rows"], 1e3 * (t2 - t0))
        self.metrics_registry.observe("query.execute_s", t2 - t1)
        # observed statistics: op_metrics was captured before the exec
        # lock released (rebind swaps in a FRESH list per run, so this
        # reference stays consistent even if another thread re-executes
        # the same cached plan meanwhile)
        self.op_stats.record(
            family if family is not None else normalize_query(query),
            op_metrics)
        self._maybe_replan()
        result = RelationalCypherResult(records, None, plan.plans, metrics)
        result.profile = result_profile
        return result

    # -- divergence-triggered re-planning (ROADMAP item 3) -------------------

    def _maybe_replan(self) -> None:
        """Retire every plan family whose executions crossed the model-
        divergence threshold (obs/telemetry.py OpStatsStore): its cached
        plans evict through the quarantine path, the family is marked so
        its next cold plan reports ``replan.completed``, and listeners
        (serve/server.py wires the structured event log) observe
        ``replan.triggered`` — the end-to-end feedback loop."""
        if not self.config.use_cost_model \
                or (self.config.replan_threshold or 0) <= 0:
            return
        for family in self.op_stats.take_replan_candidates():
            dropped = self.plan_cache.evict_family(family)
            # retire the fused recordings with the plans: the re-planned
            # tree may have a different shape (re-rooted chain, changed
            # physical strategy) and replaying the OLD plan's recorded
            # size stream against it would mis-gather — the same
            # (plan quarantine + fused forget) pairing the serving
            # tier's poisoned-plan ladder applies
            fused = getattr(self, "fused", None)
            if fused is not None:
                seen = set()
                for p in dropped:
                    fk = (id(p.records_graph), p.query_text)
                    if p.query_text and fk not in seen:
                        seen.add(fk)
                        try:
                            fused.forget(p.records_graph, p.query_text)
                        except Exception:  # pragma: no cover
                            pass
            # the family's observed history is deliberately KEPT: when
            # the re-plan keeps the plan shape (the prior was wrong but
            # nothing re-rooted), calibration replaces the mis-priced
            # estimates with the observed means and the divergence
            # stops — one re-plan, not churn.  If the re-plan CHANGES
            # shape, cost.annotate_plan detects the operator-id
            # mismatch and resets the history there (op ids do not
            # transfer across plan shapes).
            self.metrics_registry.counter("replan.triggered").inc()
            if len(self._replanned_pending) < 64:
                self._replanned_pending.add(family)
            self._notify_replan("replan.triggered", {
                "family": family, "quarantined_plans": len(dropped)})

    def _notify_replan(self, event: str, info: Dict[str, Any]) -> None:
        for listener in list(self.replan_listeners):
            try:
                listener(event, info)
            except Exception:  # pragma: no cover — observers must not fail
                pass

    # -- update statements (relational/updates.py) ---------------------------

    def _run_update(self, graph: RelationalCypherGraph,
                    stmt, query: str, params: Dict[str, Any],
                    t0: float) -> CypherResult:
        """Execute a ``CREATE``/``SET``/``DELETE`` statement: plan-split
        it into a read query + staging directives, run the read part on
        the writer's CURRENT snapshot through the normal pipeline, stage
        per-row update ops host-side, and commit them atomically through
        the versioned handle.  A failure anywhere before the publish —
        validation, device placement, an injected fault — leaves the
        graph untouched (the commit is failure-atomic), so the serving
        tier may retry a transiently-failed write safely."""
        if not isinstance(graph, VersionedGraph):
            kind = type(graph).__name__
            if kind == "GraphSnapshot":
                raise UpdateError(
                    "snapshots are immutable — submit writes against "
                    "the versioned graph handle, not a pinned snapshot")
            raise UpdateError(
                f"updates need a versioned graph "
                f"(session.create_versioned_graph / "
                f"caps_tpu.relational.updates.versioned), got {kind}")
        tracer = self.tracer
        from caps_tpu.frontend.semantic import check_statement
        check_statement(stmt)  # scope errors surface before any staging
        plan = plan_update(stmt)
        snap = graph.current()
        t1 = clock.now()
        rows: List[Dict[str, Any]] = [{}]
        if plan.read_ast is not None:
            rows = self._execute_read_ast(snap, plan.read_ast, params)
        checkpoint("execute")
        t2 = clock.now()
        staged = stage_rows(plan, rows, params)
        with tracer.span("apply", kind="phase"):
            info = graph.apply(staged)
        checkpoint("execute")
        t3 = clock.now()
        metrics = {
            "parse_s": t1 - t0, "read_s": t2 - t1, "apply_s": t3 - t2,
            "rows": 0, "plan_cache": "off",
            "updates": info.counts(),
            "snapshot_version": info.version,
        }
        self.metrics_registry.observe("query.execute_s", t3 - t1)
        plans = {"ir": describe_plan(plan)}
        logger.debug("update %r: %s -> v%d in %.1f ms", query,
                     info.counts(), info.version, 1e3 * (t3 - t0))
        return RelationalCypherResult(plans=plans, metrics=metrics)

    def _execute_read_ast(self, graph: RelationalCypherGraph, read_ast,
                          params: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Plan + execute the synthesized read half of an update
        statement on the pinned snapshot and materialize its rows (the
        bindings and computed SET/CREATE values the staging step
        consumes).  Uncached on purpose: the snapshot advances with
        every commit, so a write's read half is almost never re-planned
        against the same version."""
        plan_params = PlanParams(params)
        ir = IRBuilder(graph.schema, self._schema_resolver,
                       plan_params).process(read_ast)
        logical, _context, rel_planner, root, _t3 = self._plan_ir(
            graph, ir, plan_params, params)
        checkpoint("plan")
        with self.tracer.span("execute", kind="phase", update_read=True):
            header, table = root.result
            records = RelationalCypherRecords(
                self, header, table, logical.result_fields,
                graph=rel_planner.current_graph)
        return records.to_maps()

    def create_versioned_graph(self, node_tables=(),
                               rel_tables=()) -> VersionedGraph:
        """A writable graph: an immutable base plus the versioned delta
        store — ``CREATE``/``SET``/``DELETE`` and ``graph.apply(...)``
        commit new snapshots; readers are isolated on the snapshot they
        started with (relational/updates.py)."""
        return VersionedGraph(self,
                              self.create_graph(node_tables, rel_tables))

    # -- graph-returning statements -----------------------------------------

    def _run_create_graph(self, graph, ir: B.CreateGraphStatement, params):
        """CATALOG CREATE GRAPH qgn { inner }: evaluate the inner query's
        graph and store it under the qualified name."""
        logical, context, planner, root, _t3 = self._plan_ir(
            graph, ir.inner, params, params)
        if not logical.returns_graph:
            raise ValueError(
                "CATALOG CREATE GRAPH requires the inner query to end with "
                "RETURN GRAPH")
        result_graph = self._evaluate_graph(root)
        self._catalog.store(ir.qgn, result_graph)
        return RelationalCypherResult(graph=result_graph)

    def _evaluate_graph(self, root: R.RelationalOperator):
        result_graph = getattr(root, "result_graph", None)
        if result_graph is None:
            raise ValueError("query does not produce a graph")
        return result_graph

    @contextlib.contextmanager
    def _record_catalog_deps(self):
        """Collect every catalog graph the planning phases resolve on
        this thread — the cached plan stores (qgn, dep token) pairs and
        lookup revalidates them (scoped invalidation)."""
        prev = getattr(self._deps_tls, "rec", None)
        rec: Dict[QualifiedGraphName, Tuple] = {}
        self._deps_tls.rec = rec
        try:
            yield rec
        finally:
            self._deps_tls.rec = prev

    def _note_catalog_dep(self, qgn: QualifiedGraphName) -> None:
        rec = getattr(self._deps_tls, "rec", None)
        if rec is not None:
            rec[qgn] = self._catalog.dep_token(qgn)

    def _schema_resolver(self, qgn: QualifiedGraphName) -> Schema:
        self._note_catalog_dep(qgn)
        src = self._catalog.source(qgn.namespace)
        s = src.schema(qgn.graph_name)
        if s is None:
            raise KeyError(f"graph {qgn!r} not found")
        return s

    def _graph_resolver(self, qgn: QualifiedGraphName) -> RelationalCypherGraph:
        self._note_catalog_dep(qgn)
        g = self._catalog.graph(qgn)
        if not isinstance(g, RelationalCypherGraph):
            raise TypeError(f"graph {qgn!r} is not a relational graph")
        return g

    # -- helpers used by graphs ---------------------------------------------

    def records_from(self, header: RecordHeader, table: Table,
                     columns: Tuple[str, ...]) -> RelationalCypherRecords:
        return RelationalCypherRecords(self, header, table, columns)

    def create_graph(self, node_tables=(), rel_tables=()) -> ScanGraph:
        return ScanGraph(self, node_tables, rel_tables)

"""Shape bucketing: a bounded lattice of operator-launch sizes.

Every device-side operator launch pads its rows up to a capacity bucket
(``backends/tpu/table.py``), so XLA programs compile once per
(plan, bucket) rather than once per exact row count.  Until now the
bucket boundaries were a fixed geometric ladder
(``EngineConfig.bucket_sizes``); this module makes them a first-class,
*observable* lattice:

* :class:`ShapeBucketLattice` rounds sizes up power-of-two-ish and can
  be **seeded from observed sizes** (``session.op_stats`` actual rows,
  or a persisted plan store's recorded maxima — the tensor-path costing
  idea of "Premature Dimensional Collapse ..." in PAPERS.md applied to
  padding: boundaries go where the workload's sizes actually land, so
  padding waste shrinks where it matters and the bucket count stays
  bounded);
* :func:`param_shape_signature` maps a parameter binding to a
  **value-independent bucketed shape token** — the compile-shape label
  the compile ledger charges under (two bindings whose sizes fall in
  one bucket are ONE compiled shape, so ``compile.recompiles`` counts
  genuinely redundant compile work, not value churn) and the ragged
  micro-batcher's bucket key (serve/batcher.py): requests whose shapes
  agree per-bucket pack into one shared device launch, the
  Ragged-Paged-Attention pad-and-pack shape (PAPERS.md) with the
  DeviceTable validity masks playing the exact-row-mask role.

The lattice only ever grows monotonically (boundaries are added, never
removed, and never beyond ``max_buckets``): a mid-session seed can
change which bucket NEW launches pad to, but every already-recorded
fused size stream stays valid — recorded capacities are plain integers,
and the generic-replay relation checks (backends/tpu/table.py) verify
every served size on device regardless of where the boundaries sit.
"""
from __future__ import annotations

from typing import Any, Iterable, Mapping, Optional, Tuple

from caps_tpu.obs.lockgraph import make_lock

#: the fixed ladder EngineConfig ships — kept as the un-seeded default
#: so an un-adapted lattice buckets exactly like ``config.bucket_for``
DEFAULT_BUCKETS: Tuple[int, ...] = (256, 1024, 4096, 16384, 65536,
                                    262144, 1048576)


def _pow2_ceil(n: int) -> int:
    n = max(1, int(n))
    return 1 << (n - 1).bit_length()


class ShapeBucketLattice:
    """A bounded, monotonically growing set of row-capacity boundaries.

    ``bucket(n)`` rounds ``n`` up to the smallest boundary >= n (beyond
    the largest boundary: repeated doubling, exactly like the old
    ``EngineConfig.bucket_for``).  ``seed(sizes)`` inserts the
    power-of-two ceiling of each observed size as a new boundary —
    bounded by ``max_buckets``, so ad-hoc size churn cannot fragment the
    lattice (and with it the per-bucket compile cache) without bound.
    """

    def __init__(self, buckets: Optional[Iterable[int]] = None,
                 max_buckets: int = 64, registry=None):
        base = tuple(buckets) if buckets else DEFAULT_BUCKETS
        self.max_buckets = max(len(base), int(max_buckets))
        self._buckets: Tuple[int, ...] = tuple(sorted(
            {max(1, int(b)) for b in base}))
        self._lock = make_lock("shapes.ShapeBucketLattice._lock")
        self._seeded_c = (registry.counter("bucket.seeded")
                          if registry is not None else None)
        if registry is not None:
            registry.gauge("bucket.boundaries",
                           fn=lambda: len(self._buckets))

    def bucket(self, n: int) -> int:
        n = int(n)
        buckets = self._buckets  # tuple read is atomic; no lock on reads
        for b in buckets:
            if n <= b:
                return b
        b = buckets[-1]
        while b < n:
            b *= 2
        return b

    def signature(self, n: int) -> str:
        """The bucket token of a size — stable across every value that
        pads to the same capacity."""
        return f"b{self.bucket(n)}"

    def boundaries(self) -> Tuple[int, ...]:
        return self._buckets

    def seed(self, sizes: Iterable[int]) -> int:
        """Insert the power-of-two ceiling of each observed size as a
        boundary (idempotent; bounded).  Returns how many boundaries
        were actually added."""
        wanted = sorted({_pow2_ceil(s) for s in sizes if int(s) > 0})
        added = 0
        with self._lock:
            have = set(self._buckets)
            for b in wanted:
                if b in have or len(have) >= self.max_buckets:
                    continue
                have.add(b)
                added += 1
            if added:
                self._buckets = tuple(sorted(have))
        if added and self._seeded_c is not None:
            self._seeded_c.inc(added)
        return added

    def seed_from_op_stats(self, op_stats) -> int:
        """Seed from the observed-statistics store (obs/telemetry.py):
        each (plan family, operator)'s actual max row count becomes a
        candidate boundary — the sizes real traffic launches at."""
        sizes = []
        try:
            for ops in op_stats.stats().values():
                for st in ops.values():
                    sizes.append(int(st.get("rows_max") or 0))
        except Exception:  # pragma: no cover — stats shape drift
            return 0
        return self.seed(sizes)


# -- module-default lattice (the batcher's bucket key source) ----------------

_default_lock = make_lock("shapes._default_lock")
_default: Optional[ShapeBucketLattice] = None


def default_lattice() -> ShapeBucketLattice:
    """Process-shared lattice for callers with no session at hand (the
    micro-batcher's bucket keys).  Sessions hold their own instance."""
    global _default
    with _default_lock:
        if _default is None:
            _default = ShapeBucketLattice()
        return _default


# -- parameter shape signatures ----------------------------------------------

def param_shape_token(value: Any,
                      lattice: Optional[ShapeBucketLattice] = None) -> str:
    """A value-independent shape token for one parameter binding:
    scalars reduce to their coarse type, containers to type + LENGTH
    BUCKET (the only aspect of a container value that shapes a compiled
    launch), maps additionally to their key set (pattern-property
    expansion plans per key — plan_cache.PlanParams.map_keys)."""
    lat = lattice if lattice is not None else default_lattice()
    if value is None:
        return "null"
    if isinstance(value, bool):
        return "bool"
    if isinstance(value, int):
        return "int"
    if isinstance(value, float):
        return "float"
    if isinstance(value, str):
        return "str"
    if isinstance(value, bytes):
        return "bytes"
    if isinstance(value, (list, tuple)):
        return f"list:{lat.signature(len(value))}"
    if isinstance(value, (set, frozenset)):
        return f"set:{lat.signature(len(value))}"
    if isinstance(value, Mapping):
        keys = ",".join(sorted(str(k) for k in value))
        return f"map[{keys}]"
    return f"?{type(value).__name__}"


def param_shape_signature(params: Mapping[str, Any],
                          lattice: Optional[ShapeBucketLattice] = None
                          ) -> Tuple[Tuple[str, str], ...]:
    """Sorted (name, shape token) tuple — hashable (the ragged batch
    key component) and stable across parameter VALUES whose shapes land
    in the same buckets."""
    return tuple(sorted((k, param_shape_token(v, lattice))
                        for k, v in params.items()))


def signature_text(sig: Tuple[Tuple[str, str], ...]) -> str:
    """Compact string form of a signature — the compile ledger's shape
    label."""
    return "{" + ",".join(f"{k}:{t}" for k, t in sig) + "}"

"""Ingest-time graph statistics: cardinalities, degree sketches, skew.

The observed-statistics store (obs/telemetry.py) answers "what did this
plan family actually do"; this module answers "what does the GRAPH look
like" — the prior a cost model needs BEFORE a family has history.  Per
label combination and relationship type it computes, host-side at graph
construction (lazily, cached per graph object):

* **cardinalities** — rows per node label combination and per
  relationship type (the reference engine had none of this: Spark-CAPS
  planned Catalyst-blind, SURVEY.md §2);
* **degree-distribution sketches** — per rel type and direction the
  mean/p90/max out- and in-degree over distinct endpoints, the
  Zipf-tail signal a join-order choice needs (JSPIM, PAPERS.md);
* **hot-key skew sketches** — the top heavy-hitter endpoint ids and the
  max/mean skew factor, the planned analog of the runtime hot-key
  sample ``backends/tpu/table.py _detect_hot_keys`` draws reactively;
* **per-property distinct counts** (bounded) — equality-predicate
  selectivities (``WHERE a.name = $seed`` estimates actual duplicate
  counts instead of a magic constant).

Snapshots fold their delta counts over the base's sketch
(:func:`fold_delta`) so live writes refresh the statistics without a
full recompute; compaction re-bases and the next snapshot recomputes
from the folded base.  ``to_payload``/``from_payload`` round-trip plain
JSON so the persistent plan store (relational/plan_store.py) can carry
the sketch across processes.

Everything here is advisory: a wrong statistic mis-prices a plan, it
can never shape results — and the divergence feedback loop
(relational/cost.py + obs/telemetry.py) detects exactly that case.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, FrozenSet, Iterable, List, Mapping, Optional, Tuple

import numpy as np

#: distinct-count computation is skipped above this many rows — the
#: sketch must stay an ingest-time blip, never an ingest-time phase
_MAX_DISTINCT_ROWS = 2_000_000

#: heavy hitters retained per degree sketch
_HOT_KEYS = 8

#: a key is "hot" when its degree exceeds this multiple of the mean
#: (matches the runtime detector's spirit — okapi/config.py
#: ``join_hot_factor`` is the serving-side knob)
_HOT_FACTOR = 4.0


@dataclasses.dataclass(frozen=True)
class DegreeSketch:
    """Degree distribution over one endpoint role of one rel type."""
    rows: int = 0
    distinct: int = 0
    mean: float = 0.0
    p90: float = 0.0
    max: int = 0
    #: ((endpoint id, degree), ...) heavy hitters, heaviest first
    hot_keys: Tuple[Tuple[int, int], ...] = ()

    @property
    def skew(self) -> float:
        """max/mean degree — 1.0 is perfectly uniform."""
        return (self.max / self.mean) if self.mean > 0 else 1.0

    def to_payload(self) -> Dict[str, Any]:
        return {"rows": self.rows, "distinct": self.distinct,
                "mean": self.mean, "p90": self.p90, "max": self.max,
                "hot_keys": [list(h) for h in self.hot_keys]}

    @staticmethod
    def from_payload(p: Mapping[str, Any]) -> "DegreeSketch":
        return DegreeSketch(
            rows=int(p.get("rows") or 0),
            distinct=int(p.get("distinct") or 0),
            mean=float(p.get("mean") or 0.0),
            p90=float(p.get("p90") or 0.0),
            max=int(p.get("max") or 0),
            hot_keys=tuple((int(k), int(c))
                           for k, c in (p.get("hot_keys") or ())))


def _sketch(keys: np.ndarray) -> DegreeSketch:
    """Degree sketch of one endpoint-id array."""
    rows = int(keys.shape[0])
    if rows == 0:
        return DegreeSketch()
    vals, counts = np.unique(keys, return_counts=True)
    mean = rows / vals.shape[0]
    hot_mask = counts > _HOT_FACTOR * mean
    order = np.argsort(counts[hot_mask])[::-1][:_HOT_KEYS]
    hot = tuple((int(vals[hot_mask][i]), int(counts[hot_mask][i]))
                for i in order)
    return DegreeSketch(rows=rows, distinct=int(vals.shape[0]),
                        mean=float(mean),
                        p90=float(np.percentile(counts, 90)),
                        max=int(counts.max()), hot_keys=hot)


@dataclasses.dataclass(frozen=True)
class RelStats:
    """One relationship type's cardinality + both degree sketches."""
    rel_type: str
    rows: int
    out: DegreeSketch = DegreeSketch()
    inn: DegreeSketch = DegreeSketch()

    def to_payload(self) -> Dict[str, Any]:
        return {"rel_type": self.rel_type, "rows": self.rows,
                "out": self.out.to_payload(), "in": self.inn.to_payload()}

    @staticmethod
    def from_payload(p: Mapping[str, Any]) -> "RelStats":
        return RelStats(str(p.get("rel_type") or ""),
                        int(p.get("rows") or 0),
                        DegreeSketch.from_payload(p.get("out") or {}),
                        DegreeSketch.from_payload(p.get("in") or {}))


class GraphStatistics:
    """The queryable sketch: cardinality / degree / skew / distinct-count
    lookups the cost model (relational/cost.py) prices plans with."""

    def __init__(self,
                 node_combos: Mapping[FrozenSet[str], int],
                 rels: Mapping[str, RelStats],
                 property_distinct: Optional[Mapping[Tuple[FrozenSet[str],
                                                           str], int]] = None,
                 version: int = 0):
        self.node_combos: Dict[FrozenSet[str], int] = {
            frozenset(k): int(v) for k, v in node_combos.items()}
        self.rels: Dict[str, RelStats] = dict(rels)
        self.property_distinct: Dict[Tuple[FrozenSet[str], str], int] = {
            (frozenset(k), p): int(v)
            for (k, p), v in (property_distinct or {}).items()}
        #: snapshot version the sketch describes (0 = a fresh base)
        self.version = int(version)

    # -- lookups --------------------------------------------------------

    @property
    def total_nodes(self) -> int:
        return sum(self.node_combos.values())

    @property
    def total_rels(self) -> int:
        return sum(r.rows for r in self.rels.values())

    def node_cardinality(self, labels: Iterable[str] = ()) -> int:
        """Rows a node scan with these labels sees (label combinations
        that contain every requested label)."""
        want = frozenset(labels)
        return sum(n for combo, n in self.node_combos.items()
                   if want <= combo)

    def label_fraction(self, labels: Iterable[str] = ()) -> float:
        """Fraction of all nodes a label set selects (1.0 unlabeled)."""
        total = self.total_nodes
        if not frozenset(labels) or total <= 0:
            return 1.0
        return min(1.0, self.node_cardinality(labels) / total)

    def rel_cardinality(self, rel_types: Iterable[str] = ()) -> int:
        want = frozenset(rel_types)
        if not want:
            return self.total_rels
        return sum(r.rows for t, r in self.rels.items() if t in want)

    def degree_per_node(self, rel_types: Iterable[str] = (),
                        outgoing: bool = True) -> float:
        """Expected matching edges per FRONTIER node in one direction.

        Containment assumption (System R): a frontier that reached an
        Expand through the pattern's structural constraints is drawn
        from the direction's endpoint domain, so the expansion factor
        is the per-direction sketch mean — edges divided by DISTINCT
        endpoints on that side.  This is what makes the two
        orientations of a chain price differently on asymmetric edges
        (1M edges out of 10 hubs: ~100k per frontier node walking out
        of the hub side, ~1 walking out of the wide side); the
        direction-blind edges/total-nodes average prices both walks
        identically.  Falls back to edges/total when a sketch carries
        no distinct count (empty or folded-away domain)."""
        total = self.total_nodes
        if total <= 0:
            return 0.0
        want = frozenset(rel_types)
        rows = 0
        distinct = 0
        for t, r in self.rels.items():
            if want and t not in want:
                continue
            rows += r.rows
            distinct += (r.out if outgoing else r.inn).distinct
        if rows <= 0:
            return 0.0
        if distinct <= 0:
            return rows / total
        return rows / min(max(distinct, 1), max(total, 1))

    def skew(self, rel_types: Iterable[str] = (),
             outgoing: bool = True) -> float:
        """Worst max/mean degree skew across the matching types."""
        want = frozenset(rel_types)
        out = 1.0
        for t, r in self.rels.items():
            if want and t not in want:
                continue
            sk = (r.out if outgoing else r.inn).skew
            out = max(out, sk)
        return out

    def hot_keys(self, rel_types: Iterable[str] = (),
                 outgoing: bool = True) -> Tuple[Tuple[int, int], ...]:
        want = frozenset(rel_types)
        hits: List[Tuple[int, int]] = []
        for t, r in self.rels.items():
            if want and t not in want:
                continue
            hits.extend((r.out if outgoing else r.inn).hot_keys)
        return tuple(sorted(hits, key=lambda kv: -kv[1])[:_HOT_KEYS])

    def eq_distinct(self, labels: Iterable[str],
                    prop: str) -> Optional[int]:
        """Distinct values of a property over the label set, or None
        when the sketch has no count (too big at ingest / never seen)."""
        want = frozenset(labels)
        total = 0
        seen = False
        for (combo, p), n in self.property_distinct.items():
            if p == prop and (not want or want <= combo):
                total += n
                seen = True
        return total if seen else None

    def summary(self) -> Dict[str, Any]:
        return {
            "nodes": self.total_nodes,
            "rels": self.total_rels,
            "label_combos": len(self.node_combos),
            "rel_types": sorted(self.rels),
            "max_skew": max([r.out.skew for r in self.rels.values()]
                            + [1.0]),
            "version": self.version,
        }

    # -- persistence (plan_store.py payload section) --------------------

    def to_payload(self) -> Dict[str, Any]:
        return {
            "version": self.version,
            "node_combos": [[sorted(k), v]
                            for k, v in sorted(self.node_combos.items(),
                                               key=lambda kv: sorted(kv[0]))],
            "rels": {t: r.to_payload() for t, r in self.rels.items()},
            "property_distinct": [[sorted(k), p, v]
                                  for (k, p), v
                                  in sorted(self.property_distinct.items(),
                                            key=lambda kv: (sorted(kv[0][0]),
                                                            kv[0][1]))],
        }

    @staticmethod
    def from_payload(p: Mapping[str, Any]) -> Optional["GraphStatistics"]:
        """Validated inverse of :meth:`to_payload` — a malformed payload
        yields None (the store is a hint, never an authority)."""
        try:
            combos = {frozenset(k): int(v)
                      for k, v in (p.get("node_combos") or ())}
            rels = {str(t): RelStats.from_payload(r)
                    for t, r in (p.get("rels") or {}).items()}
            distinct = {(frozenset(k), str(prop)): int(v)
                        for k, prop, v in (p.get("property_distinct")
                                           or ())}
            return GraphStatistics(combos, rels, distinct,
                                   version=int(p.get("version") or 0))
        except (TypeError, ValueError, AttributeError):
            return None


EMPTY_STATS = GraphStatistics({}, {})


# -- computation -------------------------------------------------------------


def _host_ints(table, col: str) -> Optional[np.ndarray]:
    """One column as a host int64 array (None rows dropped).  Device
    tables expose ``host_column`` (one cached transfer); anything else
    materializes through the Table SPI."""
    host = getattr(table, "host_column", None)
    if host is not None:
        got = host(col)
        if got is not None:
            vals, ok = got
            return np.asarray(vals)[np.asarray(ok)].astype(np.int64)
    vals = table.column_values(col)
    return np.array([v for v in vals if v is not None], dtype=np.int64)


def compute_graph_statistics(graph, version: int = 0) -> GraphStatistics:
    """Host-side sketch of a ScanGraph's entity tables.  One pass at
    ingest (lazy, cached by the graph); failure degrades to
    :data:`EMPTY_STATS` — statistics must never fail a query."""
    node_combos: Dict[FrozenSet[str], int] = {}
    rels: Dict[str, RelStats] = {}
    distinct: Dict[Tuple[FrozenSet[str], str], int] = {}
    try:
        for nt in getattr(graph, "node_tables", ()):
            combo = frozenset(nt.labels)
            n = int(nt.table.exact_size())
            node_combos[combo] = node_combos.get(combo, 0) + n
            if 0 < n <= _MAX_DISTINCT_ROWS:
                for key, col in nt.mapping.property_cols.items():
                    try:
                        vals = [v for v in nt.table.column_values(col)
                                if v is not None]
                        k = (combo, key)
                        distinct[k] = distinct.get(k, 0) + len(set(vals))
                    except Exception:  # pragma: no cover — advisory only
                        continue
        for rt in getattr(graph, "rel_tables", ()):
            m = rt.mapping
            src = _host_ints(rt.table, m.source_col)
            tgt = _host_ints(rt.table, m.target_col)
            prev = rels.get(rt.rel_type)
            cur = RelStats(rt.rel_type, int(src.shape[0]),
                           out=_sketch(src), inn=_sketch(tgt))
            if prev is not None:
                # same type split over tables: keep the bigger sketch,
                # sum the cardinalities (the mean/skew stays approximate)
                cur = RelStats(rt.rel_type, prev.rows + cur.rows,
                               out=max((prev.out, cur.out),
                                       key=lambda s: s.rows),
                               inn=max((prev.inn, cur.inn),
                                       key=lambda s: s.rows))
            rels[rt.rel_type] = cur
    except Exception:  # pragma: no cover — statistics must not fail
        return EMPTY_STATS
    return GraphStatistics(node_combos, rels, distinct, version=version)


def fold_delta(base: GraphStatistics, state,
               version: int) -> GraphStatistics:
    """Refresh a base sketch with a snapshot's delta counts (cheap —
    the delta records are host-resident): created nodes/rels add to
    their combo/type cardinalities, tombstones subtract from the
    totals proportionally.  Degree sketches keep the base shape (the
    delta is bounded by compaction, so the distortion is too)."""
    combos = dict(base.node_combos)
    for rec in getattr(state, "nodes", ()):
        combo = frozenset(rec.labels)
        combos[combo] = combos.get(combo, 0) + 1
    hidden_n = len(getattr(state, "hidden_nodes", ()))
    if hidden_n and combos:
        total = sum(combos.values()) or 1
        combos = {k: max(0, v - (hidden_n * v) // total)
                  for k, v in combos.items()}
    rels = dict(base.rels)
    added_rels: Dict[str, int] = {}
    for rec in getattr(state, "rels", ()):
        added_rels[rec.rel_type] = added_rels.get(rec.rel_type, 0) + 1
    hidden_r = len(getattr(state, "hidden_rels", ()))
    for t, extra in added_rels.items():
        prev = rels.get(t) or RelStats(t, 0)
        rels[t] = dataclasses.replace(prev, rows=prev.rows + extra)
    if hidden_r and rels:
        total = sum(r.rows for r in rels.values()) or 1
        rels = {t: dataclasses.replace(
            r, rows=max(0, r.rows - (hidden_r * r.rows) // total))
            for t, r in rels.items()}
    return GraphStatistics(combos, rels, base.property_distinct,
                           version=version)


def graph_statistics(graph) -> GraphStatistics:
    """The one entry point planners use: a graph's (lazily computed,
    cached) statistics — :data:`EMPTY_STATS` for graphs that have none
    (EmptyGraph, union graphs, mocks)."""
    fn = getattr(graph, "statistics", None)
    if fn is None:
        return EMPTY_STATS
    try:
        got = fn()
    except Exception:  # pragma: no cover — statistics must not fail
        return EMPTY_STATS
    return got if isinstance(got, GraphStatistics) else EMPTY_STATS

"""The columnar ``Table`` SPI — the port surface every backend implements.

Mirrors the reference's ``Table[T]`` trait (select/filter/drop/join/
unionAll/orderBy/skip/limit/distinct/group/withColumn/size/physicalColumns/
columnType/rows/cache) (ref: okapi-relational/.../api/table/Table.scala —
reconstructed, mount empty; SURVEY.md §2 "Table SPI").

Like the reference — where ``filter(expr)`` takes an okapi ``Expr`` and each
backend compiles it (SparkSQLExprMapper for Spark) — expression-bearing
methods here receive ``(expr, header, parameters)`` and the backend brings
its own expression compiler.  Aggregations and sort keys are pre-projected
to physical columns by the relational planner, so ``group``/``order_by``
deal in column names only.
"""
from __future__ import annotations

import abc
import dataclasses
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from caps_tpu.ir.exprs import Expr
from caps_tpu.okapi.types import CypherType
from caps_tpu.relational.header import RecordHeader


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregation over a pre-projected input column.

    kind: count_star | count | sum | avg | min | max | collect | stdev
          | percentile_cont | percentile_disc
    """
    name: str
    kind: str
    col: Optional[str] = None       # None for count_star
    distinct: bool = False
    percentile: Optional[float] = None
    result_type: Optional[CypherType] = None


JoinType = str  # "inner" | "left" | "cross"


class Table(abc.ABC):
    """Immutable columnar table."""

    # -- shape --------------------------------------------------------------

    @property
    @abc.abstractmethod
    def columns(self) -> Tuple[str, ...]:
        ...

    @property
    @abc.abstractmethod
    def size(self) -> int:
        ...

    def exact_size(self) -> int:
        """The exact live row count.  Equal to ``size`` everywhere except
        a device table under generic fused replay, where ``size`` is a
        served upper bound and this method pays the one materialization
        sync.  Use at materialization boundaries only."""
        return self.size

    def size_hint(self) -> int:
        """A row count that NEVER syncs: exact when known (eager mode, or
        after a materialization already paid the sync), otherwise the
        served upper bound.  For metrics/logging only."""
        return self.size

    def branch_empty(self) -> bool:
        """``size == 0`` as a CONTROL-FLOW predicate.  Plan code must use
        this (not ``.size``) when branching on emptiness: under generic
        fused replay ``size`` is a served upper bound, and this method
        routes the decision through the record/replay stream so a
        divergent branch is detected instead of silently followed."""
        return self.size == 0

    @property
    def nbytes(self) -> int:
        """Approximate resident bytes of this table's columns — the input
        to the per-operator bytes-touched accounting (SURVEY.md §5.5; the
        single-chip roofline proxy: achieved GB/s = bytes / wall-clock).
        Backends override with exact buffer sizes; the default assumes 8
        bytes + validity per cell."""
        return self.size * len(self.columns) * 9

    @abc.abstractmethod
    def column_type(self, col: str) -> CypherType:
        ...

    # -- column ops ---------------------------------------------------------

    @abc.abstractmethod
    def select(self, cols: Sequence[str]) -> "Table":
        """Narrow to exactly these columns, in order."""

    @abc.abstractmethod
    def rename(self, mapping: Mapping[str, str]) -> "Table":
        ...

    @abc.abstractmethod
    def with_column(self, name: str, expr: Expr, header: RecordHeader,
                    parameters: Mapping[str, Any],
                    cypher_type: CypherType) -> "Table":
        """Append a column computed from ``expr`` (backend-compiled)."""

    @abc.abstractmethod
    def with_literal_column(self, name: str, value: Any,
                            cypher_type: CypherType) -> "Table":
        ...

    @abc.abstractmethod
    def with_row_index(self, name: str) -> "Table":
        """Append a unique int64 row-id column (used for Optional joins)."""

    @abc.abstractmethod
    def copy_column(self, src: str, dst: str) -> "Table":
        """Append ``dst`` as a copy of ``src`` (entity aliasing)."""

    # -- row ops ------------------------------------------------------------

    @abc.abstractmethod
    def filter(self, expr: Expr, header: RecordHeader,
               parameters: Mapping[str, Any]) -> "Table":
        """Keep rows where ``expr`` evaluates to exactly true (3VL)."""

    @abc.abstractmethod
    def join(self, other: "Table", how: JoinType,
             pairs: Sequence[Tuple[str, str]]) -> "Table":
        """Join on equality of column pairs; null keys never match.
        Column sets must be disjoint."""

    @abc.abstractmethod
    def union_all(self, other: "Table") -> "Table":
        """Bag union; ``other`` must have the same columns."""

    def drop_in(self, col: str, values) -> "Table":
        """Drop rows whose ``col`` value is in ``values`` — the tombstone
        mask of the versioned-snapshot overlay (relational/updates.py).
        Device backends keep this on-device (a padded ``isin`` mask over
        a size-bucketed id array, so the compiled program is shared
        across snapshots); null cells never match and are kept."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement drop_in")

    @abc.abstractmethod
    def distinct(self) -> "Table":
        ...

    @abc.abstractmethod
    def order_by(self, items: Sequence[Tuple[str, bool]]) -> "Table":
        """Stable multi-key sort; (column, ascending); Cypher null ordering
        (nulls last ascending, first descending)."""

    @abc.abstractmethod
    def skip(self, n: int) -> "Table":
        ...

    @abc.abstractmethod
    def limit(self, n: int) -> "Table":
        ...

    @abc.abstractmethod
    def group(self, by: Sequence[str], aggs: Sequence[AggSpec]) -> "Table":
        """Group by columns, compute aggregations.  Empty ``by`` = one
        global group (which aggregates over zero rows to count=0/sum=0/
        null for min/max/avg, per Cypher)."""

    @abc.abstractmethod
    def explode(self, list_col: str, out_col: str,
                out_type: CypherType) -> "Table":
        """UNWIND: one output row per element of ``list_col``; empty lists
        and nulls produce no rows."""

    @abc.abstractmethod
    def pack_list(self, cols: Sequence[str], out_col: str,
                  out_type: CypherType) -> "Table":
        """Combine columns into one list-valued column per row, skipping
        nulls (used for variable-length relationship lists)."""

    # -- materialization ----------------------------------------------------

    @abc.abstractmethod
    def column_values(self, col: str) -> List[Any]:
        """Materialize one column to host Python values (None for null)."""

    def rows(self) -> List[Dict[str, Any]]:
        cols = self.columns
        data = {c: self.column_values(c) for c in cols}
        return [{c: data[c][i] for c in cols} for i in range(self.size)]

    def cache(self) -> "Table":
        return self

    def device_sync(self) -> None:
        """Wait for any in-flight device work producing this table
        (PROFILE's per-operator device-time mode — obs/).  Host-side
        backends are synchronous already: no-op.  Never transfers data
        or consumes fused-replay sizes — purely a completion barrier."""
        return None


class TableFactory(abc.ABC):
    """Backend-side constructors for tables."""

    @abc.abstractmethod
    def from_columns(self, data: Mapping[str, Sequence[Any]],
                     types: Mapping[str, CypherType]) -> Table:
        ...

    @abc.abstractmethod
    def unit(self) -> Table:
        """One row, zero columns (the Start operator's table)."""

    @abc.abstractmethod
    def empty(self, cols: Sequence[str],
              types: Mapping[str, CypherType]) -> Table:
        ...

    def prepare_rel_table(self, rel_table) -> None:
        """Backend hook called once per relationship table at graph
        creation: device backends build their physical adjacency layout
        (HBM-resident CSR over the source/target columns) here so every
        later Expand hop probes it.  Default: no-op."""

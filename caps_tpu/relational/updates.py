"""Live graph updates: delta store, immutable snapshots, versioned graphs.

ROADMAP item 3: everything before this module was read-only over frozen
CSR snapshots.  Production graph serving needs writes *under load*, and
on a TPU backend the one thing a write must never do is reshape (and
recompile) the world: compiled programs are keyed by shape, and the
base tables' shapes are what the whole compile cache amortizes over.

The design follows the pad-and-mask discipline of Ragged Paged
Attention (PAPERS.md) — fixed-shape base structures plus bounded ragged
deltas:

* the **base** is an ordinary immutable :class:`ScanGraph` (HBM-resident
  CSR adjacency, device columns — untouched by writes);
* committed writes live in a **delta store**: append-only node/rel
  records materialized as small scan tables through the same table
  factory (so the device gets a bounded delta CSR next to the base
  one), plus **tombstone masks** — id sets dropped from the base scan
  on-device (``Table.drop_in``: an ``isin`` mask over the padded
  tombstone array, compiled once per size bucket);
* every committed write publishes a new immutable
  :class:`GraphSnapshot` — base + delta overlay + version.  Snapshots
  are plan-cacheable and fused-replayable exactly like frozen graphs
  (they ARE frozen); the mutable object is the :class:`VersionedGraph`
  handle, which is deliberately *not* a valid plan-cache anchor
  (``plan_token_unstable``) — readers resolve it to the current
  snapshot at query start and finish on that snapshot no matter how
  many writes commit meanwhile.  No torn reads, ever.
* **compaction** folds base + delta into a fresh base snapshot
  (``VersionedGraph.compact``; the serving tier runs it as a background
  task — serve/compaction.py), resetting the tombstone masks and delta
  CSR to empty.

Writes are **failure-atomic**: a commit stages host-side first (pure
validation — any :class:`UpdateError` leaves the graph untouched), then
builds the device-resident delta tables under a string-pool mark
(generalizing the PR 4 ``pool.mark/rollback`` ingest machinery to delta
state), and only then publishes the new snapshot with one reference
swap.  A fault anywhere mid-apply — an injected device OOM, a string
pool growth failure, an abort between delta columns
(testing/faults.py ``abort_write``) — rolls back completely; a retried
write re-executes against an unchanged graph.
"""
from __future__ import annotations

import contextlib
import dataclasses
import functools
import itertools
import threading
import weakref
from collections.abc import Mapping as _MappingABC
from typing import (Any, Dict, FrozenSet, Iterable, List, Mapping, Optional,
                    Sequence, Tuple, Union)

from caps_tpu.frontend import ast
from caps_tpu.ir import exprs as E
from caps_tpu.obs.lockgraph import make_lock, make_rlock
from caps_tpu.okapi.schema import Schema
from caps_tpu.okapi.types import CTInteger, CypherType, from_python, join_all
from caps_tpu.okapi.values import CypherNode, CypherRelationship
from caps_tpu.relational.entity_tables import (NodeMapping, NodeTable,
                                               RelationshipMapping,
                                               RelationshipTable)
from caps_tpu.relational.graphs import (RelationalCypherGraph, ScanGraph,
                                        align_scan)
from caps_tpu.relational.header import RecordHeader


class UpdateError(ValueError):
    """A write that cannot be applied (unknown entity id, constraint
    violation, unsupported update form).  Raised during host-side
    staging/validation — BEFORE any state changes — so a failed write
    is always a no-op.  Deterministic: classified FATAL by the serving
    tier (retrying cannot change the outcome)."""


# -- literal evaluation (shared with testing/factory.py) ---------------------

def eval_literal_expr(expr: E.Expr, params: Mapping[str, Any]) -> Any:
    """Evaluate a parameter-and-literal-only expression host-side (the
    CREATE-property subset: literals, $params, lists, maps, negation,
    temporal constructors)."""
    if isinstance(expr, E.Lit):
        return expr.value
    if isinstance(expr, E.Param):
        if expr.name not in params:
            raise UpdateError(f"missing parameter ${expr.name}")
        return params[expr.name]
    if isinstance(expr, E.Negate):
        return -eval_literal_expr(expr.expr, params)
    if isinstance(expr, E.ListLit):
        return [eval_literal_expr(i, params) for i in expr.items]
    if isinstance(expr, E.MapLit):
        return {k: eval_literal_expr(v, params)
                for k, v in zip(expr.keys, expr.values)}
    if isinstance(expr, E.FunctionExpr) \
            and expr.name in ("date", "datetime", "localdatetime",
                              "duration"):
        from caps_tpu.okapi.values import temporal_construct
        try:
            return temporal_construct(
                expr.name, *[eval_literal_expr(a, params)
                             for a in expr.args])
        except (ValueError, TypeError) as ex:
            raise UpdateError(str(ex))
    raise UpdateError(f"expression is not host-evaluable: {expr!r}")


def _is_static(expr: E.Expr) -> bool:
    """True when :func:`eval_literal_expr` can evaluate ``expr`` with
    only the parameter map — no row context needed."""
    if isinstance(expr, (E.Lit, E.Param)):
        return True
    if isinstance(expr, E.Negate):
        return _is_static(expr.expr)
    if isinstance(expr, E.ListLit):
        return all(_is_static(i) for i in expr.items)
    if isinstance(expr, E.MapLit):
        return all(_is_static(v) for v in expr.values)
    if isinstance(expr, E.FunctionExpr) \
            and expr.name in ("date", "datetime", "localdatetime",
                              "duration"):
        return all(_is_static(a) for a in expr.args)
    return False


# -- table building (shared by the delta store, compaction, and the test
#    factory — testing/factory.py delegates here) ----------------------------

def build_node_tables(factory, nodes: Iterable[Tuple[int, Iterable[str],
                                                     Mapping[str, Any]]]
                      ) -> List[NodeTable]:
    """Group ``(id, labels, props)`` records by exact label combination
    and build one :class:`NodeTable` per combo through ``factory``."""
    by_labels: Dict[Tuple[str, ...],
                    List[Tuple[int, Mapping[str, Any]]]] = {}
    for nid, labels, props in nodes:
        by_labels.setdefault(tuple(sorted(labels)), []).append((nid, props))
    out = []
    for labels, rows in sorted(by_labels.items()):
        keys = sorted({k for _, p in rows for k in p})
        types: Dict[str, CypherType] = {"_id": CTInteger}
        data: Dict[str, List[Any]] = {"_id": [nid for nid, _ in rows]}
        for k in keys:
            vals = [p.get(k) for _, p in rows]
            t = join_all(from_python(v) for v in vals if v is not None)
            if any(v is None for v in vals):
                t = t.nullable
            types[k] = t
            data[k] = vals
        mapping = NodeMapping.on("_id").with_implied_labels(*labels)
        for k in keys:
            mapping = mapping.with_property(k)
        out.append(NodeTable(mapping, factory.from_columns(data, types)))
    return out


def build_rel_tables(factory, rels: Iterable[Tuple[int, int, int, str,
                                                   Mapping[str, Any]]]
                     ) -> List[RelationshipTable]:
    """Group ``(id, src, tgt, type, props)`` records by relationship type
    and build one :class:`RelationshipTable` per type."""
    by_type: Dict[str, List[Tuple[int, int, int, Mapping[str, Any]]]] = {}
    for rid, src, tgt, rel_type, props in rels:
        by_type.setdefault(rel_type, []).append((rid, src, tgt, props))
    out = []
    for rel_type, rows in sorted(by_type.items()):
        keys = sorted({k for *_, p in rows for k in p})
        types: Dict[str, CypherType] = {"_id": CTInteger, "_src": CTInteger,
                                        "_tgt": CTInteger}
        data: Dict[str, List[Any]] = {
            "_id": [r[0] for r in rows], "_src": [r[1] for r in rows],
            "_tgt": [r[2] for r in rows]}
        for k in keys:
            vals = [r[3].get(k) for r in rows]
            t = join_all(from_python(v) for v in vals if v is not None)
            if any(v is None for v in vals):
                t = t.nullable
            types[k] = t
            data[k] = vals
        mapping = RelationshipMapping.on(rel_type)
        for k in keys:
            mapping = mapping.with_property(k)
        out.append(RelationshipTable(mapping,
                                     factory.from_columns(data, types)))
    return out


# -- update operations (the programmatic ``graph.apply`` vocabulary) ---------

@dataclasses.dataclass(frozen=True, eq=False)
class CreateNode:
    """Create one node.  ``id=None`` lets the graph allocate a fresh id;
    the instance itself can be used as a :class:`CreateRel` endpoint (or
    a Set/Delete target) within the same ``apply`` batch."""
    labels: Tuple[str, ...] = ()
    properties: Mapping[str, Any] = dataclasses.field(default_factory=dict)
    id: Optional[int] = None


@dataclasses.dataclass(frozen=True, eq=False)
class CreateRel:
    """Create one relationship.  ``src``/``tgt`` accept a node id, a
    materialized :class:`CypherNode`, or a :class:`CreateNode` from the
    same batch."""
    rel_type: str
    src: Any
    tgt: Any
    properties: Mapping[str, Any] = dataclasses.field(default_factory=dict)
    id: Optional[int] = None


@dataclasses.dataclass(frozen=True, eq=False)
class DeleteNode:
    id: Any
    detach: bool = False


@dataclasses.dataclass(frozen=True, eq=False)
class DeleteRel:
    id: Any


@dataclasses.dataclass(frozen=True, eq=False)
class SetNodeProps:
    """Merge (default) or replace a node's properties.  A ``None`` value
    removes the key (Cypher ``SET n.k = null`` semantics)."""
    id: Any
    properties: Mapping[str, Any]
    replace: bool = False


@dataclasses.dataclass(frozen=True, eq=False)
class SetRelProps:
    id: Any
    properties: Mapping[str, Any]
    replace: bool = False


UpdateOp = Union[CreateNode, CreateRel, DeleteNode, DeleteRel,
                 SetNodeProps, SetRelProps]


@dataclasses.dataclass(frozen=True)
class UpdateResult:
    """What one committed ``apply`` did: the published snapshot version
    and per-kind counts."""
    version: int
    created_nodes: int = 0
    created_rels: int = 0
    deleted_nodes: int = 0
    deleted_rels: int = 0
    props_set: int = 0

    def counts(self) -> Dict[str, int]:
        return {"created_nodes": self.created_nodes,
                "created_rels": self.created_rels,
                "deleted_nodes": self.deleted_nodes,
                "deleted_rels": self.deleted_rels,
                "props_set": self.props_set}


# -- the delta store ---------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class _NodeRec:
    id: int
    labels: Tuple[str, ...]
    props: Tuple[Tuple[str, Any], ...]

    def props_dict(self) -> Dict[str, Any]:
        return dict(self.props)


@dataclasses.dataclass(frozen=True)
class _RelRec:
    id: int
    src: int
    tgt: int
    rel_type: str
    props: Tuple[Tuple[str, Any], ...]

    def props_dict(self) -> Dict[str, Any]:
        return dict(self.props)


@dataclasses.dataclass(frozen=True)
class DeltaState:
    """The host-level truth of everything a snapshot overlays on its
    base: tombstone id sets (base rows masked out on scan) and live
    delta records (appended — including base entities re-emitted with
    merged properties after a SET).  Immutable; commits build a new one
    (O(delta), bounded by compaction)."""
    hidden_nodes: FrozenSet[int] = frozenset()
    hidden_rels: FrozenSet[int] = frozenset()
    nodes: Tuple[_NodeRec, ...] = ()
    rels: Tuple[_RelRec, ...] = ()

    @property
    def delta_rows(self) -> int:
        """Compaction-backlog metric: delta records + tombstones."""
        return (len(self.nodes) + len(self.rels)
                + len(self.hidden_nodes) + len(self.hidden_rels))

    @property
    def empty(self) -> bool:
        return self.delta_rows == 0


_EMPTY_DELTA = DeltaState()


def _props_tuple(props: Mapping[str, Any]) -> Tuple[Tuple[str, Any], ...]:
    return tuple(sorted((k, v) for k, v in props.items() if v is not None))


# -- delta-state serialization (the fleet replication unit) ------------------
#
# serve/fleet.py ships committed writes between processes as (delta
# state, version) pairs: the owner serializes its current snapshot's
# overlay, a peer rebuilds the same DeltaState and installs it at the
# OWNER'S version (``VersionedGraph.install_state``).  Only the
# host-level truth travels — delta tables, device buffers, and compiled
# state rebuild locally on the peer (the "compiled state never
# migrates" rule at process granularity).

def delta_state_to_payload(state: DeltaState) -> Dict[str, Any]:
    """JSON-able form of a delta overlay.  Property values must be
    JSON-representable (the update vocabulary's literal subset);
    ordering is canonical so equal states serialize identically."""
    return {
        "hidden_nodes": sorted(state.hidden_nodes),
        "hidden_rels": sorted(state.hidden_rels),
        "nodes": [[r.id, list(r.labels),
                   [[k, v] for k, v in r.props]] for r in state.nodes],
        "rels": [[r.id, r.src, r.tgt, r.rel_type,
                  [[k, v] for k, v in r.props]] for r in state.rels],
    }


def delta_state_from_payload(payload: Mapping[str, Any]) -> DeltaState:
    """The inverse of :func:`delta_state_to_payload`, validated — a
    malformed payload raises :class:`UpdateError` (classified FATAL by
    the serving tier) without touching any graph."""
    try:
        nodes = tuple(
            _NodeRec(int(nid), tuple(str(lb) for lb in labels),
                     tuple((str(k), v) for k, v in props))
            for nid, labels, props in payload["nodes"])
        rels = tuple(
            _RelRec(int(rid), int(src), int(tgt), str(rel_type),
                    tuple((str(k), v) for k, v in props))
            for rid, src, tgt, rel_type, props in payload["rels"])
        return DeltaState(
            hidden_nodes=frozenset(int(i)
                                   for i in payload["hidden_nodes"]),
            hidden_rels=frozenset(int(i) for i in payload["hidden_rels"]),
            nodes=nodes, rels=rels)
    except (KeyError, TypeError, ValueError) as ex:
        raise UpdateError(f"malformed delta-state payload: "
                          f"{type(ex).__name__}: {ex}")


class _OverlayLookup(_MappingABC):
    """Base entity lookup with hidden ids removed and delta entries
    overlaid — without copying the (potentially huge) base dict per
    snapshot."""

    def __init__(self, base: Mapping, hidden: FrozenSet[int],
                 added: Dict[int, Any]):
        self._base = base
        self._hidden = hidden
        self._added = added

    def __getitem__(self, key):
        if key in self._added:
            return self._added[key]
        if key in self._hidden:
            raise KeyError(key)
        return self._base[key]

    def __contains__(self, key) -> bool:
        if key in self._added:
            return True
        return key not in self._hidden and key in self._base

    def __iter__(self):
        for k in self._base:
            if k not in self._hidden and k not in self._added:
                yield k
        yield from self._added

    def __len__(self) -> int:
        n = sum(1 for k in self._hidden if k in self._base)
        dup = sum(1 for k in self._added
                  if k in self._base and k not in self._hidden)
        return len(self._base) - n - dup + len(self._added)


# -- snapshots ---------------------------------------------------------------

class GraphSnapshot(RelationalCypherGraph):
    """One immutable version of a versioned graph: the base ScanGraph
    plus a delta overlay.  Scans = (base scan minus tombstone mask)
    ∪ (delta scan), aligned to the union schema's header — every
    operator (Scan, Expand, the SpMV count pushdown, var-expand) reads
    through :meth:`scan_node`/:meth:`scan_rel`, so the whole engine is
    delta-aware through this one seam.

    Snapshots are valid plan-cache anchors and fused-replay keys (their
    data never changes); each commit's snapshot gets its own tokens, so
    plans and size memos are keyed *per snapshot version* by
    construction."""

    def __init__(self, session, base: ScanGraph,
                 delta_graph: Optional[ScanGraph], state: DeltaState,
                 snapshot_version: int, handle=None):
        super().__init__(session)
        self.base = base
        self.delta_graph = delta_graph
        self.state = state
        #: monotone logical version of the lineage (0 = the fresh base)
        self.snapshot_version = snapshot_version
        #: handle that published this snapshot (None on replica rebasings)
        self.handle = handle
        # device memo / size-cache identity (same counter as ScanGraph)
        self.version = next(ScanGraph._version_counter)
        schema = base.schema
        if delta_graph is not None:
            schema = schema.union(delta_graph.schema)
        self._schema = schema
        self._node_lookup_cache: Optional[Mapping] = None
        self._rel_lookup_cache: Optional[Mapping] = None
        self._statistics_cache = None

    @property
    def schema(self) -> Schema:
        return self._schema

    def statistics(self):
        """The base sketch refreshed with this snapshot's delta counts
        (relational/stats.py ``fold_delta``) — commits and compactions
        keep the cost model's cardinalities current without a full
        host recompute (the delta is bounded by compaction, so the
        fold's distortion is too)."""
        if self._statistics_cache is None:
            from caps_tpu.relational.stats import fold_delta
            self._statistics_cache = fold_delta(
                self.base.statistics(), self.state,
                version=self.snapshot_version)
        return self._statistics_cache

    # -- lookups (materialization) -------------------------------------

    def node_lookup(self):
        if self._node_lookup_cache is None:
            added = {rec.id: (rec.labels, rec.props_dict())
                     for rec in self.state.nodes}
            self._node_lookup_cache = _OverlayLookup(
                self.base.node_lookup(), self.state.hidden_nodes, added)
        return self._node_lookup_cache

    def rel_lookup(self):
        if self._rel_lookup_cache is None:
            added = {rec.id: (rec.src, rec.tgt, rec.rel_type,
                              rec.props_dict())
                     for rec in self.state.rels}
            self._rel_lookup_cache = _OverlayLookup(
                self.base.rel_lookup(), self.state.hidden_rels, added)
        return self._rel_lookup_cache

    # -- scans (the delta-overlay seam) --------------------------------

    def scan_node(self, var: str, labels: Iterable[str] = ()):
        labels = frozenset(labels)
        header = RecordHeader.for_node(var, self._schema, labels)
        _bh, bt = self.base.scan_node(var, labels)
        if self.state.hidden_nodes:
            # tombstone mask: base rows whose id is in the hidden set
            # drop on-device (padded isin mask — Table.drop_in)
            bt = bt.drop_in(f"{var}__id", self.state.hidden_nodes)
        out = align_scan(header, bt)
        if self.delta_graph is not None:
            _dh, dt = self.delta_graph.scan_node(var, labels)
            out = out.union_all(align_scan(header, dt))
        return header, out

    def scan_rel(self, var: str, rel_types: Iterable[str] = ()):
        rel_types = frozenset(rel_types)
        header = RecordHeader.for_relationship(var, self._schema, rel_types)
        _bh, bt = self.base.scan_rel(var, rel_types)
        if self.state.hidden_rels:
            bt = bt.drop_in(f"{var}__id", self.state.hidden_rels)
        out = align_scan(header, bt)
        if self.delta_graph is not None:
            _dh, dt = self.delta_graph.scan_rel(var, rel_types)
            out = out.union_all(align_scan(header, dt))
        return header, out

    # -- memory accounting (obs/ledger.py, serve/compaction.py) ---------

    def delta_nbytes(self) -> int:
        """Approximate bytes the delta overlay holds resident: the
        appended delta tables plus the tombstone id sets — the input to
        the byte-based compaction trigger
        (``ServerConfig.compaction_threshold_bytes``) and the memory
        ledger's per-snapshot delta accounting."""
        n = 8 * (len(self.state.hidden_nodes)
                 + len(self.state.hidden_rels))
        if self.delta_graph is not None:
            for et in (tuple(self.delta_graph.node_tables)
                       + tuple(self.delta_graph.rel_tables)):
                try:
                    n += int(et.table.nbytes)
                except Exception:  # pragma: no cover — must not fail
                    pass
        return n

    # -- replication (serve/devices.py) --------------------------------

    def rebase(self, session, base_copy: ScanGraph) -> "GraphSnapshot":
        """This snapshot's overlay re-anchored on another session's copy
        of the base (device-replica serving): the host-level delta state
        is device-independent, so only the small delta tables rebuild
        through the target session's factory — the base re-ingests once
        per device and is shared by every snapshot of the lineage."""
        delta = build_delta_graph(session, self.state)
        return GraphSnapshot(session, base_copy, delta, self.state,
                             self.snapshot_version, handle=None)


def build_delta_graph(session, state: DeltaState) -> Optional[ScanGraph]:
    """Materialize a delta state's appended records as a (small)
    ScanGraph through ``session``'s table factory — device placement and
    delta-CSR layout happen here.  None when nothing was appended."""
    if not state.nodes and not state.rels:
        return None
    factory = session.table_factory
    node_tables = build_node_tables(
        factory, [(r.id, r.labels, r.props_dict()) for r in state.nodes])
    rel_tables = build_rel_tables(
        factory,
        [(r.id, r.src, r.tgt, r.rel_type, r.props_dict())
         for r in state.rels])
    return ScanGraph(session, node_tables, rel_tables)


# -- compaction scoping (testing/faults.py flaky_compaction keys off it) -----

_compaction_tls = threading.local()


def in_compaction() -> bool:
    """True on a thread currently folding a compaction (the
    compaction-scoped fault injectors key off this)."""
    return getattr(_compaction_tls, "active", False)


@contextlib.contextmanager
def _compaction_scope():
    prev = getattr(_compaction_tls, "active", False)
    _compaction_tls.active = True
    try:
        yield
    finally:
        _compaction_tls.active = prev


# -- the versioned handle ----------------------------------------------------

_delta_gauge_guard = make_lock("updates._delta_gauge_guard")


def _register_delta_gauge(registry, handle: "VersionedGraph") -> None:
    """``updates.delta_rows`` reports the total compaction backlog across
    every live versioned graph on this registry (weakly referenced — a
    dropped graph falls out of the gauge instead of pinning buffers)."""
    with _delta_gauge_guard:
        live = getattr(registry, "_caps_live_versioned", None)
        if live is None:
            live = registry._caps_live_versioned = weakref.WeakSet()
            registry.gauge("updates.delta_rows",
                           fn=lambda: sum(g.delta_rows() for g in live))
        live.add(handle)


class VersionedGraph(RelationalCypherGraph):
    """The mutable handle of a snapshot lineage.

    Reads against the handle resolve to :meth:`current` — the latest
    committed snapshot — at query start (the session and the serving
    tier both do this), so a reader NEVER observes a half-applied
    write.  Writes (:meth:`apply`, or ``CREATE``/``SET``/``DELETE``
    Cypher through the session) serialize on the commit lock and
    publish a new snapshot atomically.

    The handle itself is not a plan-cache anchor
    (``plan_token_unstable``): a stable token over changing data would
    serve stale plans.  Snapshots carry the tokens instead."""

    #: serving-tier marker (duck-typed to keep serve/ import-light)
    graph_is_versioned = True
    #: relational/plan_cache.py: never anchor a cache entry on the handle
    plan_token_unstable = True

    def __init__(self, session, base: ScanGraph):
        super().__init__(session)
        if not isinstance(base, ScanGraph):
            raise UpdateError(
                f"versioned graphs wrap scan graphs, got "
                f"{type(base).__name__}")
        # Serializes commits AND compaction publication; reentrant so a
        # locked compaction retry can call commit helpers.
        self._lock = make_rlock("updates.VersionedGraph._lock")
        self._current = GraphSnapshot(session, base, None, _EMPTY_DELTA,
                                      snapshot_version=0, handle=self)
        self._next_id = _max_entity_id(base) + 1
        registry = session.metrics_registry
        self._commits = registry.counter("updates.commits")
        self._rolled_back = registry.counter("updates.rolled_back")
        self._created_nodes = registry.counter("updates.created_nodes")
        self._created_rels = registry.counter("updates.created_rels")
        self._deleted_nodes = registry.counter("updates.deleted_nodes")
        self._deleted_rels = registry.counter("updates.deleted_rels")
        self._props_set = registry.counter("updates.props_set")
        self._compaction_runs = registry.counter("compaction.runs")
        self._compaction_conflicts = registry.counter(
            "compaction.conflicts")
        self._compaction_folded = registry.counter(
            "compaction.folded_rows")
        self._compaction_s = registry.histogram("compaction.duration_s")
        #: durability seam (caps_tpu/durability): ``pre_publish(new_snap)``
        #: runs under the commit lock after the new snapshot is BUILT
        #: but before it publishes — the WAL's append-before-acknowledge
        #: point and the shard group's prepare/commit round.  A raise
        #: rolls the string pool back and aborts the commit with the
        #: graph untouched (same containment as a device-build failure).
        self.pre_publish = None
        #: ``on_compacted(folded_snap, new_snap)`` runs under the commit
        #: lock right after a compaction publishes — the WAL's
        #: checkpoint-truncation point.  Compaction is already durable
        #: in the log (entries are cumulative), so the hook must treat
        #: checkpoint failures as deferrable, never abort the fold.
        self.on_compacted = None
        _register_delta_gauge(registry, self)

    # -- read surface --------------------------------------------------

    def current(self) -> GraphSnapshot:
        """The latest committed snapshot (one reference read — commits
        publish with a single atomic swap)."""
        return self._current

    snapshot = current  # alias

    def delta_rows(self) -> int:
        return self._current.state.delta_rows

    def delta_nbytes(self) -> int:
        """Byte-side compaction backlog of the current snapshot."""
        return self._current.delta_nbytes()

    @property
    def schema(self) -> Schema:
        return self._current.schema

    def scan_node(self, var: str, labels: Iterable[str] = ()):
        return self._current.scan_node(var, labels)

    def scan_rel(self, var: str, rel_types: Iterable[str] = ()):
        return self._current.scan_rel(var, rel_types)

    def node_lookup(self):
        return self._current.node_lookup()

    def rel_lookup(self):
        return self._current.rel_lookup()

    def statistics(self):
        """The CURRENT snapshot's refreshed sketch — commits publish a
        new snapshot, whose fold over the base keeps the cost model's
        cardinalities live across writes."""
        return self._current.statistics()

    # -- write surface -------------------------------------------------

    def apply(self, updates: Sequence[UpdateOp]) -> UpdateResult:
        """Commit a batch of updates atomically: every op applies, or —
        on ANY failure (validation, device placement, injected fault) —
        none do and the string pool rolls back to its pre-commit mark.
        Returns the published version and per-kind counts; readers
        admitted before the commit keep their snapshot."""
        ops = list(updates)
        if not ops:
            return UpdateResult(self._current.snapshot_version)
        with self._lock:
            snap = self._current
            state, counts, next_id = _fold(snap, ops, self._next_id)
            new_snap = self._build_and_publish(snap, state)
            self._next_id = next_id
        self._commits.inc()
        self._created_nodes.inc(counts["created_nodes"])
        self._created_rels.inc(counts["created_rels"])
        self._deleted_nodes.inc(counts["deleted_nodes"])
        self._deleted_rels.inc(counts["deleted_rels"])
        self._props_set.inc(counts["props_set"])
        self._evict_snapshot_plans(snap)
        return UpdateResult(new_snap.snapshot_version, **counts)

    def _build_and_publish(self, snap: GraphSnapshot,
                           state: DeltaState,
                           base: Optional[ScanGraph] = None
                           ) -> GraphSnapshot:
        """Device-build + atomic publish, under the commit lock.  The
        build runs under a string-pool mark: a failure between delta
        columns rolls the pool back and re-raises with the graph
        untouched (the failure-atomicity seam the abort_write fault
        injector exercises)."""
        compaction = base is not None
        pool = getattr(getattr(self._session, "backend", None), "pool",
                       None)
        mark = pool.mark() if pool is not None else None
        try:
            if base is None:
                base = snap.base
                delta_graph = build_delta_graph(self._session, state)
            else:
                delta_graph = None  # compaction: fresh base, empty delta
        except BaseException:
            if pool is not None:
                pool.rollback(mark)
            self._rolled_back.inc()
            raise
        new_snap = GraphSnapshot(self._session, base, delta_graph, state,
                                 snap.snapshot_version + 1, handle=self)
        if not compaction and self.pre_publish is not None:
            # append-before-acknowledge: a failed WAL append (or a
            # failed shard prepare round) aborts the whole commit here,
            # with the same pool rollback as a device-build failure
            try:
                self.pre_publish(new_snap)
            except BaseException:
                if pool is not None:
                    pool.rollback(mark)
                self._rolled_back.inc()
                raise
        self._current = new_snap
        if compaction and self.on_compacted is not None:
            self.on_compacted(snap, new_snap)
        return new_snap

    def install_state(self, state: DeltaState, version: int,
                      on_install=None) -> GraphSnapshot:
        """Replication seam (serve/fleet.py): adopt an OWNER process's
        delta state at the owner's version — the peer half of snapshot
        shipping.  The delta tables rebuild through THIS session's
        factory (compiled state never ships), the new snapshot carries
        the owner's ``snapshot_version`` verbatim, and the flip is the
        same single atomic reference swap a local commit publishes
        with, so readers keep snapshot isolation throughout.  Versions
        at or behind the current snapshot are ignored (idempotent
        re-ship, out-of-order delivery); the id allocator advances past
        the shipped entities so a later owner promotion cannot collide.

        ``on_install(new_snap)`` runs UNDER the commit lock, BEFORE the
        reference swap publishes the snapshot (``current()`` is a
        lock-free single read) — the rejoin fencing seam: version gauges
        and superseded result-cache retirement happen-before any reader
        can be admitted at the new version, so no read is ever served a
        version the gauges don't yet report.  It also runs on the
        idempotent early return (re-publishing current state is
        harmless; skipping it would leave a rejoining peer's gauges
        stale forever)."""
        with self._lock:
            snap = self._current
            if version <= snap.snapshot_version:
                if on_install is not None:
                    on_install(snap)
                return snap
            pool = getattr(getattr(self._session, "backend", None),
                           "pool", None)
            mark = pool.mark() if pool is not None else None
            try:
                delta_graph = build_delta_graph(self._session, state)
            except BaseException:
                if pool is not None:
                    pool.rollback(mark)
                self._rolled_back.inc()
                raise
            new_snap = GraphSnapshot(self._session, snap.base, delta_graph,
                                     state, version, handle=self)
            self._retire_superseded_results(version)
            if on_install is not None:
                on_install(new_snap)
            self._current = new_snap
            hi = max((r.id for r in state.nodes + state.rels), default=-1)
            self._next_id = max(self._next_id, hi + 1)
        self._evict_snapshot_plans(snap)
        return new_snap

    def _evict_snapshot_plans(self, old_snap: GraphSnapshot) -> None:
        """Scoped eviction: only plans anchored on the superseded
        snapshot's token drop — an unrelated graph's cached plans (and
        other sessions' caches) are untouched.  Zero catalog fanout."""
        from caps_tpu.relational.plan_cache import graph_plan_token
        self._retire_superseded_results(self._current.snapshot_version)
        tok = getattr(old_snap, "_plan_token", None)
        if tok is None:
            return  # never anchored a plan: nothing to evict
        cache = getattr(self._session, "plan_cache", None)
        if cache is not None:
            cache.evict_graph(tok)

    def _retire_superseded_results(self, live_version: int) -> None:
        """Result-cache retirement (relational/result_cache.py): drop
        every cached result/intermediate of this lineage whose version
        predates ``live_version`` — a dead version can never be read
        again (readers resolve ``current()`` at admission), so its
        entries are pure ballast.  New versions never *invalidate*
        (version-keyed = new key space)."""
        rcache = getattr(self._session, "result_cache", None)
        if rcache is not None:
            rcache.retire_superseded(
                getattr(self, "_rescache_scope", None), live_version)

    # -- compaction ----------------------------------------------------

    def compact(self) -> bool:
        """Fold base + delta into a fresh base snapshot (empty delta,
        empty tombstone masks).  Returns False when the delta was
        already empty.  Optimistic: the (slow) re-ingest runs outside
        the commit lock; if a write raced in, one conflict is counted
        and the retry folds while HOLDING the lock (bounded writer
        stall, guaranteed progress)."""
        from caps_tpu.obs import clock
        for attempt in range(2):
            snap = self._current
            if snap.state.empty:
                return False
            t0 = clock.now()
            if attempt == 0:
                with _compaction_scope():
                    base = self._fold_base(snap)
                with self._lock:
                    if self._current is not snap:
                        self._compaction_conflicts.inc()
                        continue
                    self._build_and_publish(snap, _EMPTY_DELTA, base=base)
            else:
                with self._lock, _compaction_scope():
                    snap = self._current
                    if snap.state.empty:
                        return False
                    base = self._fold_base(snap)
                    self._build_and_publish(snap, _EMPTY_DELTA, base=base)
            self._compaction_runs.inc()
            self._compaction_folded.inc(snap.state.delta_rows)
            self._compaction_s.observe(clock.now() - t0)
            self._evict_snapshot_plans(snap)
            return True
        return False  # pragma: no cover — loop always returns

    def _fold_base(self, snap: GraphSnapshot) -> ScanGraph:
        """Materialize the snapshot's full live entity set host-side and
        re-ingest it as a fresh base.  A failed fold rolls the string
        pool back to the pre-fold mark — but ONLY if no write committed
        meanwhile (checked under the commit lock): the optimistic fold
        runs outside the lock, and rolling back past a concurrent
        commit's interned strings would corrupt PUBLISHED data.  A
        skipped rollback merely leaks pool growth (a re-record, never a
        wrong result)."""
        pool = getattr(getattr(self._session, "backend", None), "pool",
                       None)
        mark = pool.mark() if pool is not None else None
        try:
            factory = self._session.table_factory
            nodes = [(nid, labels, props)
                     for nid, (labels, props) in snap.node_lookup().items()]
            rels = [(rid, src, tgt, typ, props)
                    for rid, (src, tgt, typ, props)
                    in snap.rel_lookup().items()]
            node_tables = build_node_tables(factory, nodes)
            rel_tables = build_rel_tables(factory, rels)
            return ScanGraph(self._session, node_tables, rel_tables)
        except BaseException:
            if pool is not None:
                with self._lock:
                    if self._current is snap:
                        pool.rollback(mark)
            self._rolled_back.inc()
            raise


def _max_entity_id(base: ScanGraph) -> int:
    hi = -1
    for nt in base.node_tables:
        for v in nt.table.column_values(nt.mapping.id_col):
            if v is not None and v > hi:
                hi = v
    for rt in base.rel_tables:
        for v in rt.table.column_values(rt.mapping.id_col):
            if v is not None and v > hi:
                hi = v
    return hi


def versioned(session, graph: Optional[ScanGraph] = None) -> VersionedGraph:
    """Wrap a scan graph (or a fresh empty one) in a versioned handle."""
    if graph is None:
        graph = session.create_graph((), ())
    return VersionedGraph(session, graph)


# -- commit folding (host-side, pure) ----------------------------------------

def _base_incidence(base: ScanGraph) -> Dict[int, List[int]]:
    """node id -> incident base rel ids, built once per base (immutable)
    and cached on it — the DETACH DELETE / delete-constraint index."""
    idx = getattr(base, "_caps_incidence", None)
    if idx is None:
        idx = {}
        for rid, (src, tgt, _typ, _props) in base.rel_lookup().items():
            idx.setdefault(src, []).append(rid)
            if tgt != src:
                idx.setdefault(tgt, []).append(rid)
        base._caps_incidence = idx
    return idx


def _fold(snap: GraphSnapshot, ops: Sequence[UpdateOp], next_id: int
          ) -> Tuple[DeltaState, Dict[str, int], int]:
    """Validate + fold a batch of ops over a snapshot's delta state.
    Pure host-side: raises :class:`UpdateError` without touching
    anything; returns (new state, counts, next free id)."""
    state = snap.state
    nodes: Dict[int, List[Any]] = {r.id: [r.labels, r.props_dict()]
                                   for r in state.nodes}
    rels: Dict[int, List[Any]] = {
        r.id: [r.src, r.tgt, r.rel_type, r.props_dict()]
        for r in state.rels}
    hidden_nodes = set(state.hidden_nodes)
    hidden_rels = set(state.hidden_rels)
    base_nodes = snap.base.node_lookup()
    base_rels = snap.base.rel_lookup()
    counts = {"created_nodes": 0, "created_rels": 0, "deleted_nodes": 0,
              "deleted_rels": 0, "props_set": 0}
    tmp_ids: Dict[int, int] = {}  # id(CreateNode/CreateRel) -> entity id
    next_free = next_id

    def alloc(explicit: Optional[int] = None) -> int:
        # explicit ids advance the allocator past themselves, or a later
        # auto-allocated create would collide with them
        nonlocal next_free
        if explicit is not None:
            next_free = max(next_free, explicit + 1)
            return explicit
        v = next_free
        next_free += 1
        return v

    def node_live(nid: int) -> bool:
        return nid in nodes or (nid in base_nodes
                                and nid not in hidden_nodes)

    def rel_live(rid: int) -> bool:
        return rid in rels or (rid in base_rels and rid not in hidden_rels)

    def resolve(ref: Any, *, as_node: bool) -> int:
        if isinstance(ref, (CreateNode, CreateRel)):
            # earlier in this batch, or committed by a previous apply
            # (the fold stamps the allocated id back onto the op)
            got = tmp_ids.get(id(ref), ref.id)
            if got is None:
                raise UpdateError(
                    "update references a created entity that is not in "
                    "(or is later in) this batch")
            return got
        if isinstance(ref, CypherNode):
            if not as_node:
                raise UpdateError(f"expected a relationship, got {ref!r}")
            return ref.id
        if isinstance(ref, CypherRelationship):
            if as_node:
                raise UpdateError(f"expected a node, got {ref!r}")
            return ref.id
        if isinstance(ref, bool) or not isinstance(ref, int):
            raise UpdateError(
                f"expected an entity or id, got {type(ref).__name__}")
        return ref

    def live_incident(nid: int) -> List[int]:
        out = [rid for rid, rec in rels.items()
               if rec[0] == nid or rec[1] == nid]
        out.extend(rid for rid in _base_incidence(snap.base).get(nid, ())
                   if rid not in hidden_rels)
        return out

    def set_props(rec_props: Dict[str, Any], update: Mapping[str, Any],
                  replace: bool) -> Dict[str, Any]:
        out = {} if replace else dict(rec_props)
        for k, v in update.items():
            if v is None:
                out.pop(k, None)
            else:
                out[k] = v
        return out

    for op in ops:
        if isinstance(op, CreateNode):
            nid = alloc(op.id)
            if node_live(nid):
                raise UpdateError(f"node id {nid} already exists")
            # NOTE: a tombstone on this id (a deleted base row) must
            # STAY — the delta row supersedes it; unmasking the base
            # row would resurrect the deleted entity alongside this one
            nodes[nid] = [tuple(sorted(op.labels)),
                          {k: v for k, v in dict(op.properties).items()
                           if v is not None}]
            tmp_ids[id(op)] = nid
            if op.id is None:
                # stamp the allocation back so a LATER apply batch can
                # keep referencing this op object
                object.__setattr__(op, "id", nid)
            counts["created_nodes"] += 1
        elif isinstance(op, CreateRel):
            src = resolve(op.src, as_node=True)
            tgt = resolve(op.tgt, as_node=True)
            for endpoint in (src, tgt):
                if not node_live(endpoint):
                    raise UpdateError(
                        f"relationship endpoint node {endpoint} does not "
                        f"exist")
            rid = alloc(op.id)
            if rel_live(rid):
                raise UpdateError(f"relationship id {rid} already exists")
            if not op.rel_type:
                raise UpdateError("relationships need a type")
            rels[rid] = [src, tgt, op.rel_type,
                         {k: v for k, v in dict(op.properties).items()
                          if v is not None}]
            tmp_ids[id(op)] = rid
            if op.id is None:
                object.__setattr__(op, "id", rid)
            counts["created_rels"] += 1
        elif isinstance(op, DeleteRel):
            rid = resolve(op.id, as_node=False)
            if rid in rels:
                del rels[rid]
            elif rid in base_rels and rid not in hidden_rels:
                hidden_rels.add(rid)
            else:
                raise UpdateError(f"relationship {rid} does not exist")
            counts["deleted_rels"] += 1
        elif isinstance(op, DeleteNode):
            nid = resolve(op.id, as_node=True)
            if not node_live(nid):
                raise UpdateError(f"node {nid} does not exist")
            incident = live_incident(nid)
            if incident and not op.detach:
                raise UpdateError(
                    f"cannot delete node {nid}: it still has "
                    f"{len(incident)} relationship(s) — use DETACH DELETE")
            for rid in incident:
                if rid in rels:
                    del rels[rid]
                else:
                    hidden_rels.add(rid)
                counts["deleted_rels"] += 1
            if nid in nodes:
                del nodes[nid]
            if nid in base_nodes:
                hidden_nodes.add(nid)
            counts["deleted_nodes"] += 1
        elif isinstance(op, SetNodeProps):
            nid = resolve(op.id, as_node=True)
            if nid in nodes:
                rec = nodes[nid]
                rec[1] = set_props(rec[1], op.properties, op.replace)
            elif nid in base_nodes and nid not in hidden_nodes:
                labels, props = base_nodes[nid]
                hidden_nodes.add(nid)
                nodes[nid] = [tuple(labels),
                              set_props(dict(props), op.properties,
                                        op.replace)]
            else:
                raise UpdateError(f"node {nid} does not exist")
            counts["props_set"] += max(1, len(op.properties))
        elif isinstance(op, SetRelProps):
            rid = resolve(op.id, as_node=False)
            if rid in rels:
                rec = rels[rid]
                rec[3] = set_props(rec[3], op.properties, op.replace)
            elif rid in base_rels and rid not in hidden_rels:
                src, tgt, typ, props = base_rels[rid]
                hidden_rels.add(rid)
                rels[rid] = [src, tgt, typ,
                             set_props(dict(props), op.properties,
                                       op.replace)]
            else:
                raise UpdateError(f"relationship {rid} does not exist")
            counts["props_set"] += max(1, len(op.properties))
        else:
            raise UpdateError(
                f"unknown update operation {type(op).__name__}")

    new_state = DeltaState(
        hidden_nodes=frozenset(hidden_nodes),
        hidden_rels=frozenset(hidden_rels),
        nodes=tuple(_NodeRec(nid, rec[0], _props_tuple(rec[1]))
                    for nid, rec in sorted(nodes.items())),
        rels=tuple(_RelRec(rid, rec[0], rec[1], rec[2],
                           _props_tuple(rec[3]))
                   for rid, rec in sorted(rels.items())))
    return new_state, counts, next_free


# -- Cypher update statements (CREATE / SET / DELETE clauses) ----------------

_UPDATE_CLAUSES = (ast.CreateClause, ast.SetClause, ast.DeleteClause)


def is_update_statement(stmt) -> bool:
    """True when the parsed statement contains update clauses (the
    session routes it through the write path)."""
    if not isinstance(stmt, ast.SingleQuery):
        return False
    return any(isinstance(c, _UPDATE_CLAUSES) for c in stmt.clauses)


def is_update_query(query: str) -> bool:
    """Text-level update detection (memoized parse; unparsable text is
    'not an update' — the execution path reports the real error)."""
    from caps_tpu.frontend.parser import parse_query, query_mode
    try:
        _mode, body = query_mode(query)
        return is_update_statement(parse_query(body))
    except Exception:
        return False


@dataclasses.dataclass(frozen=True)
class _ValueSrc:
    kind: str          # "static" | "col"
    payload: Any       # expr (static) | projected column alias (col)


@dataclasses.dataclass(frozen=True)
class _EntityRef:
    kind: str          # "row" | "tmp"
    name: str


@dataclasses.dataclass(frozen=True)
class UpdatePlan:
    """One parsed update statement, split into the read query (planned
    and executed through the normal read pipeline, on the writer's
    snapshot) and per-row staging directives."""
    read_ast: Optional[ast.SingleQuery]
    directives: Tuple[Tuple, ...]


def _plan_update_uncached(stmt: ast.SingleQuery) -> UpdatePlan:
    read_clauses: List[ast.Clause] = []
    update_clauses: List[ast.Clause] = []
    seen_update = False
    for c in stmt.clauses:
        if isinstance(c, _UPDATE_CLAUSES):
            seen_update = True
            update_clauses.append(c)
        elif seen_update:
            raise UpdateError(
                f"{type(c).__name__} after an update clause is not "
                f"supported — updates must end the query (read, then "
                f"write)")
        else:
            if isinstance(c, (ast.ReturnClause, ast.ReturnGraphClause,
                              ast.ConstructClause)):
                raise UpdateError(
                    "RETURN/CONSTRUCT cannot precede update clauses")
            read_clauses.append(c)

    projections: List[ast.ReturnItem] = []
    row_vars: List[str] = []
    tmp_vars: set = set()
    directives: List[Tuple] = []
    anon = itertools.count()

    def value_src(expr: E.Expr) -> _ValueSrc:
        if _is_static(expr):
            return _ValueSrc("static", expr)
        alias = f"__upd{len(projections)}"
        projections.append(ast.ReturnItem(expr, alias))
        return _ValueSrc("col", alias)

    def props_src(properties: Optional[E.Expr]) -> _ValueSrc:
        if properties is None:
            return _ValueSrc("static", E.MapLit((), ()))
        return value_src(properties)

    def entity_ref(name: str) -> _EntityRef:
        if name in tmp_vars:
            return _EntityRef("tmp", name)
        if name not in row_vars:
            row_vars.append(name)
        return _EntityRef("row", name)

    for clause in update_clauses:
        if isinstance(clause, ast.CreateClause):
            for part in clause.pattern.parts:
                prev_ref: Optional[_EntityRef] = None
                pending: Optional[ast.RelPattern] = None
                for el in part.elements:
                    if isinstance(el, ast.NodePattern):
                        declares = bool(el.labels) or el.properties is not None
                        if el.var is not None and el.var in tmp_vars:
                            if declares:
                                raise UpdateError(
                                    f"variable `{el.var}` already created; "
                                    f"reference it without labels/"
                                    f"properties")
                            ref = _EntityRef("tmp", el.var)
                        elif el.var is None or declares:
                            name = el.var or f"__anon{next(anon)}"
                            if name in tmp_vars:
                                raise UpdateError(
                                    f"variable `{name}` created twice")
                            tmp_vars.add(name)
                            directives.append(
                                ("create_node", name,
                                 tuple(sorted(el.labels)),
                                 props_src(el.properties)))
                            ref = _EntityRef("tmp", name)
                        else:
                            ref = entity_ref(el.var)
                        if pending is not None:
                            rel = pending
                            if len(rel.rel_types) != 1:
                                raise UpdateError(
                                    "CREATE relationships need exactly "
                                    "one type")
                            if rel.direction == ast.Direction.INCOMING:
                                src_ref, tgt_ref = ref, prev_ref
                            elif rel.direction == ast.Direction.OUTGOING:
                                src_ref, tgt_ref = prev_ref, ref
                            else:
                                raise UpdateError(
                                    "CREATE relationships must be "
                                    "directed")
                            rel_name = (rel.var
                                        or f"__anon{next(anon)}")
                            if rel_name in tmp_vars:
                                raise UpdateError(
                                    f"variable `{rel_name}` created twice")
                            tmp_vars.add(rel_name)
                            directives.append(
                                ("create_rel", rel_name,
                                 rel.rel_types[0], src_ref, tgt_ref,
                                 props_src(rel.properties)))
                            pending = None
                        prev_ref = ref
                    else:
                        pending = el
        elif isinstance(clause, ast.SetClause):
            for item in clause.items:
                if item.labels:
                    raise UpdateError("SET :Label is not supported")
                ref = entity_ref(item.var)
                if item.key is not None:
                    directives.append(
                        ("set", ref, item.key, False,
                         value_src(item.value)))
                else:
                    # SET n = map (replace) / SET n += map (merge)
                    directives.append(
                        ("set", ref, None, not item.merge,
                         value_src(item.value)))
        elif isinstance(clause, ast.DeleteClause):
            for expr in clause.exprs:
                if isinstance(expr, E.Var) and expr.name in tmp_vars:
                    directives.append(("delete", _EntityRef("tmp",
                                                            expr.name),
                                       clause.detach))
                elif isinstance(expr, E.Var):
                    directives.append(("delete", entity_ref(expr.name),
                                       clause.detach))
                else:
                    src = value_src(expr)
                    directives.append(("delete", src, clause.detach))

    read_ast: Optional[ast.SingleQuery] = None
    if read_clauses:
        items = tuple(ast.ReturnItem(E.Var(v), v) for v in row_vars) \
            + tuple(projections)
        if not items:
            # no bindings consumed: still need the row COUNT (CREATE
            # per matched row is Cypher semantics)
            items = (ast.ReturnItem(E.Lit(1), "__rows"),)
        read_ast = ast.SingleQuery(
            tuple(read_clauses)
            + (ast.ReturnClause(ast.ProjectionBody(items=items)),))
    elif row_vars or projections:
        missing = row_vars or [p.alias for p in projections]
        raise UpdateError(
            f"update references unbound variable(s) {missing} and has "
            f"no reading clauses")
    return UpdatePlan(read_ast, tuple(directives))


@functools.lru_cache(maxsize=256)
def _plan_update_memo(stmt) -> UpdatePlan:
    return _plan_update_uncached(stmt)


def plan_update(stmt: ast.SingleQuery) -> UpdatePlan:
    """Split + compile one update statement (memoized per parsed AST —
    the parse memo interns statements per query text)."""
    try:
        return _plan_update_memo(stmt)
    except TypeError:  # unhashable AST (should not happen — frozen tree)
        return _plan_update_uncached(stmt)


def stage_rows(plan: UpdatePlan, rows: List[Mapping[str, Any]],
               params: Mapping[str, Any]) -> List[UpdateOp]:
    """Expand the plan's directives over the read query's result rows
    into concrete update ops (Cypher semantics: CREATE per row, SET/
    DELETE per row binding)."""

    def resolve_value(src: _ValueSrc, row: Mapping[str, Any]) -> Any:
        if src.kind == "static":
            return eval_literal_expr(src.payload, params)
        return row[src.payload]

    def resolve_props(src: _ValueSrc, row: Mapping[str, Any]
                      ) -> Dict[str, Any]:
        v = resolve_value(src, row)
        if v is None:
            return {}
        if not isinstance(v, dict):
            raise UpdateError(f"properties must be a map, got "
                              f"{type(v).__name__}")
        return dict(v)

    out: List[UpdateOp] = []
    for row in rows:
        tmp: Dict[str, UpdateOp] = {}

        def entity(ref: Any, row=row, tmp=tmp) -> Any:
            if isinstance(ref, _EntityRef):
                if ref.kind == "tmp":
                    return tmp[ref.name]
                if ref.name not in row:
                    raise UpdateError(
                        f"variable `{ref.name}` is not bound by the "
                        f"reading clauses")
                return row[ref.name]
            return resolve_value(ref, row)  # projected DELETE expression

        for d in plan.directives:
            kind = d[0]
            if kind == "create_node":
                _, name, labels, props = d
                op = CreateNode(labels=labels,
                                properties=resolve_props(props, row))
                tmp[name] = op
                out.append(op)
            elif kind == "create_rel":
                _, name, rel_type, src_ref, tgt_ref, props = d
                op = CreateRel(rel_type, entity(src_ref),
                               entity(tgt_ref),
                               properties=resolve_props(props, row))
                tmp[name] = op
                out.append(op)
            elif kind == "set":
                _, ref, key, replace, value = d
                target = entity(ref)
                if target is None:
                    continue  # SET on a null binding: no-op
                if key is not None:
                    props: Mapping[str, Any] = \
                        {key: resolve_value(value, row)}
                    # a single-key SET of null still reaches the fold
                    # (it REMOVES the property)
                else:
                    props = resolve_props(value, row)
                if isinstance(target, (CypherRelationship,)):
                    out.append(SetRelProps(target, props, replace=replace))
                elif isinstance(target, (CreateRel,)):
                    out.append(SetRelProps(target, props, replace=replace))
                else:
                    out.append(SetNodeProps(target, props,
                                            replace=replace))
            elif kind == "delete":
                _, ref, detach = d
                target = entity(ref)
                if target is None:
                    continue  # DELETE null is a no-op (Cypher)
                if isinstance(target, (CypherRelationship,)):
                    out.append(DeleteRel(target))
                elif isinstance(target, CreateRel):
                    out.append(DeleteRel(target))
                else:
                    out.append(DeleteNode(target, detach=detach))
            else:  # pragma: no cover — directive vocabulary is closed
                raise UpdateError(f"unknown directive {kind!r}")
    return out


def describe_plan(plan: UpdatePlan) -> str:
    """EXPLAIN rendering of an update statement's write half."""
    lines = []
    for d in plan.directives:
        if d[0] == "create_node":
            lines.append(f"CreateNode({d[1]}{':' if d[2] else ''}"
                         f"{':'.join(d[2])})")
        elif d[0] == "create_rel":
            lines.append(f"CreateRel({d[1]}:{d[2]} "
                         f"{d[3].name}->{d[4].name})")
        elif d[0] == "set":
            tgt = d[1].name if isinstance(d[1], _EntityRef) else "?"
            lines.append(f"SetProps({tgt}"
                         + (f".{d[2]}" if d[2] else "")
                         + (" replace" if d[3] else "") + ")")
        elif d[0] == "delete":
            tgt = d[1].name if isinstance(d[1], _EntityRef) else "<expr>"
            lines.append(("DetachDelete(" if d[2] else "Delete(")
                         + tgt + ")")
    return "\n".join(lines) if lines else "(no updates)"

"""Bounded variable-length expand.

Mirrors the reference's ``planBoundedVarLengthExpand`` — iterative
join-and-union up to the upper bound with relationship-uniqueness (edge
isomorphism) filters (ref: okapi-relational planner — reconstructed,
mount empty; SURVEY.md §3.2).

The unroll is static: hop ``k`` joins the frontier against a per-hop copy
of the relationship scan; every new hop id is filtered against all previous
hop ids; lengths ``lower..upper`` are unioned, with traversed relationship
ids packed into one list-valued column.  Static unrolling is deliberate —
on the TPU backend every hop is a fixed-shape join the compiler can fuse,
the device-side analog of ragged frontier schedules (SURVEY.md §5.7).

When the relationship variable is dead downstream (the planner proves it
— no projection, filter, or return touches it), the op instead computes a
per-seed path-count MATRIX and explodes (source, target, multiplicity)
back into rows — the general-frontier form of SURVEY.md §5.7's "frontier
= long sequence" story.  On a device mesh the matrix rides the ppermute
RING schedule against resident adjacency shards (parallel/ring.py,
``make_ring_varexpand``, strategy "ring-matrix"); single-chip the same
SpMV hops run as one jitted program (strategy "matrix").  Per-path
relationship lists cannot ride this form; those queries stay on joins.
"""
from __future__ import annotations

from typing import List, Optional as Opt, Tuple

import numpy as np

from caps_tpu.ir import exprs as E
from caps_tpu.ir.pattern import Direction
from caps_tpu.okapi.types import (
    CTInteger, CTList, CTNode, CTRelationship, CypherType,
)
from caps_tpu.relational.header import RecordHeader
from caps_tpu.relational.ops import RelationalOperator
from caps_tpu.relational.table import Table

# Safety cap for unbounded `[*]` patterns (the reference requires Spark to
# materialize each iteration too; unbounded expansion needs *some* limit).
DEFAULT_UNBOUNDED_UPPER = 10


def synth_header(table: Table) -> RecordHeader:
    """A header mapping every physical column to ``Var(col)`` — used for
    internal columnar filtering where no user-level header applies."""
    return RecordHeader([(E.Var(c), c, table.column_type(c))
                         for c in table.columns])


class VarExpandOp(RelationalOperator):
    def __init__(self, context, parent: RelationalOperator, graph,
                 source: str, rel: str, rel_types: Tuple[str, ...],
                 target: str, target_labels, direction: Direction,
                 lower: int, upper: Opt[int], into: bool,
                 rel_needed: bool = True, emit_len: Opt[str] = None):
        super().__init__(context, [parent])
        self.graph = graph
        self.source = source
        self.rel = rel
        self.rel_types = rel_types
        self.target = target
        self.target_labels = frozenset(target_labels)
        self.direction = direction
        self.lower = lower
        self.upper = upper if upper is not None else max(
            lower, DEFAULT_UNBOUNDED_UPPER)
        self.into = into
        # False = the planner proved no downstream operator reads the rel
        # variable, so per-path relationship lists need not materialize.
        self.rel_needed = rel_needed
        # Set when the planner rewrote every size(rel)/length(rel) read
        # to this path-length column (planner._collect_used_names).
        self.emit_len = emit_len
        self.strategy = "join"

    # ------------------------------------------------------------------

    def _rel_hop_table(self, k: int) -> Tuple[Table, str, str, str]:
        """The relationship table for hop ``k`` with per-hop column names
        (id, near, far) following the traversal direction."""
        tmp_var = f"__vle{k}"
        header, t = self.graph.scan_rel(tmp_var, self.rel_types)
        idc = header.column(E.Var(tmp_var))
        src = header.column(E.StartNode(E.Var(tmp_var)))
        tgt = header.column(E.EndNode(E.Var(tmp_var)))
        t = t.select([idc, src, tgt])
        hid, hnear, hfar = f"__hop{k}_id", f"__hop{k}_near", f"__hop{k}_far"
        if self.direction == Direction.OUTGOING:
            t = t.rename({idc: hid, src: hnear, tgt: hfar})
        elif self.direction == Direction.INCOMING:
            t = t.rename({idc: hid, tgt: hnear, src: hfar})
        else:  # BOTH: traverse each edge in either orientation
            fwd = t.rename({idc: hid, src: hnear, tgt: hfar})
            bwd = t.rename({idc: hid, tgt: hnear, src: hfar})
            sh = synth_header(bwd)
            bwd = bwd.filter(
                E.Not(E.Equals(E.Var(hnear), E.Var(hfar))), sh, {})
            fwd = fwd.select([hid, hnear, hfar])
            bwd = bwd.select([hid, hnear, hfar])
            t = fwd.union_all(bwd)
        return t.select([hid, hnear, hfar]), hid, hnear, hfar

    def _compute(self):
        out = self._try_ring()
        if out is None:
            self.strategy = "join"
            out = self._join_compute()
        self._metric_extra = {"strategy": self.strategy}
        return out

    # -- matrix path (ring on mesh, SpMV single-chip; see module docstring)

    # Refuse seed-matrix shapes beyond this many entries (int64 frontier
    # blocks must fit comfortably in HBM across the mesh); larger inputs
    # stay on the join path.  Seed-axis blocking is the scale-out path.
    _RING_MAX_MATRIX = 1 << 24

    @staticmethod
    def _host_arrays(table, col: str):
        """(values, ok) host copies of an integer column of a pure-device
        table (DeviceTable.host_column), or None when there is no device
        path."""
        from caps_tpu.backends.tpu.table import DeviceTable
        if not isinstance(table, DeviceTable):
            return None
        return table.host_column(col)

    def _try_ring(self):
        """Matrix-form var-expand (multiplicity form): returns the
        (header, table) result, or None when the shape is ineligible.
        All three directions qualify — undirected patterns symmetrize
        the edge list and use the degree-form isomorphism correction.
        On a mesh the per-seed count matrix rides the ppermute ring
        (parallel/ring.py make_ring_varexpand); single-chip it runs the
        same SpMV hops as one jitted program (the twin) — either way the
        join cascade and its per-hop materializations disappear."""
        # ``into`` (both endpoints bound) stays on joins: measured at
        # LDBC scale 11, the single-pair shape pays more in per-length
        # explode/union dispatch than the tiny bound-pair joins cost
        # (6.2 s vs 2.0 s p50 for IC13 on the CPU fallback).
        if self.rel_needed or self.into or self.upper > 3:
            return None
        backend = getattr(self.context.factory, "backend", None)
        if backend is None or not backend.config.use_ring:
            return None
        import jax.numpy as jnp
        from caps_tpu.backends.tpu import kernels as K
        from caps_tpu.backends.tpu.column import Column
        from caps_tpu.backends.tpu.table import DeviceTable
        from caps_tpu.okapi.types import CTInteger
        from caps_tpu.parallel.ring import (
            build_iso3_sparse, ring_varexpand3_cached,
            ring_varexpand3_single, ring_varexpand_cached,
            ring_varexpand_single,
        )

        parent_header, parent_table = self.children[0].result
        src_id_col = parent_header.column(E.Var(self.source))
        parent = self._host_arrays(parent_table, src_id_col)
        if parent is None:
            return None
        rel_header, rel_t = self.graph.scan_rel("__ring_r", self.rel_types)
        rv = E.Var("__ring_r")
        rsrc = self._host_arrays(rel_t, rel_header.column(E.StartNode(rv)))
        rtgt = self._host_arrays(rel_t, rel_header.column(E.EndNode(rv)))
        tgt_header, tgt_table = self.graph.scan_node(
            self.target, self.target_labels)
        tgt_id_col = tgt_header.column(E.Var(self.target))
        tids = self._host_arrays(tgt_table, tgt_id_col)
        if rsrc is None or rtgt is None or tids is None:
            return None

        hsrc, hok = parent
        esrc, eok1 = rsrc
        etgt, eok2 = rtgt
        eok = eok1 & eok2
        nids, nok = tids
        mx = -1
        for vals, ok in ((hsrc, hok), (esrc, eok), (etgt, eok),
                         (nids, nok)):
            if vals.shape[0] and ok.any():
                m = int(vals[ok].max())
                if int(vals[ok].min()) < 0:
                    return None
                mx = max(mx, m)
        n_shards = backend.n_shards
        n_pad = max(((mx + 1 + n_shards - 1) // n_shards) * n_shards,
                    n_shards)
        seeds = np.unique(hsrc[hok])
        n_seeds = int(seeds.shape[0])
        if n_pad > self._RING_MAX_MATRIX:
            return None  # a single frontier row exceeds the budget
        # (large SEED sets are fine — the execution below chunks them)
        lengths = tuple(range(self.lower, self.upper + 1))
        self.strategy = ("ring-matrix"
                         if backend.mesh is not None
                         and backend.mesh.devices.ndim == 1
                         else "matrix")
        rel_list_type = CTList(CTRelationship(self.rel_types))

        if n_seeds == 0:
            cols0 = {
                "__ring_src": Column("int", jnp.zeros(1, jnp.int64),
                                     jnp.zeros(1, bool), CTInteger),
                "__ring_tgt": Column("int", jnp.zeros(1, jnp.int64),
                                     jnp.zeros(1, bool), CTInteger),
            }
            if self.emit_len:
                cols0[self.emit_len] = Column(
                    "int", jnp.zeros(1, jnp.int64), jnp.zeros(1, bool),
                    CTInteger)
            pairs = DeviceTable(backend, cols0, n=0)
            return self._ring_assemble(parent_header, parent_table,
                                       src_id_col, tgt_header, tgt_table,
                                       tgt_id_col, pairs, rel_list_type)

        # target mask + padded edges (seed-indicator frontiers are built
        # per seed CHUNK below, so host memory stays bounded too)
        tmask = np.zeros(n_pad, dtype=np.int64)
        tmask[nids[nok]] = 1
        if self.direction == Direction.BOTH:
            # symmetrize: each non-loop edge in both orientations,
            # self-loops once (VarExpandOp's BOTH hop table does the
            # same); isomorphism correction switches to degree form
            nonloop = eok & (esrc != etgt)
            a = np.concatenate([esrc, etgt[nonloop]])
            b = np.concatenate([etgt, esrc[nonloop]])
            ok_cat = np.concatenate([eok, np.ones(nonloop.sum(), bool)])
            correction = "degree"
        else:
            a, b = (esrc, etgt) if self.direction == Direction.OUTGOING \
                else (etgt, esrc)
            ok_cat = eok
            correction = "loops"
        def shard_pad(length: int) -> int:
            return max(((length + n_shards - 1) // n_shards) * n_shards,
                       n_shards)

        # compact to live entries: host mirrors are capacity-padded (the
        # bucket, not the live row count), and dead rows would inflate
        # every hop's gather width
        live = np.asarray(ok_cat)
        a, b = np.asarray(a)[live], np.asarray(b)[live]
        ok_cat = np.ones(a.shape[0], dtype=bool)
        e_pad = shard_pad(a.shape[0])
        # peak working set is the per-hop (seeds, edges) gather — bound
        # it like the (seeds, nodes) frontier.  Only the 1-D ring path
        # splits edges across devices; single-chip and 2-D meshes run
        # the whole gather on one device's program.  The 3-hop sparse
        # correction hops gather up to 4 entries per rel (vs <= 2 in the
        # base list), so bound the widest list the program will touch.
        on_ring = (backend.mesh is not None
                   and backend.mesh.devices.ndim == 1)
        widest = e_pad * 2 if self.upper == 3 else e_pad
        edges_per_device = widest // n_shards if on_ring else widest
        # SEED BLOCKING: the per-hop working set is seeds x max(nodes,
        # edges-per-device); larger seed sets run in fixed-size chunks
        # (one compile, zero-padded last block) whose pair tables union.
        per_seed = max(n_pad, edges_per_device)
        if per_seed > self._RING_MAX_MATRIX:
            return None  # even one seed's per-hop gather exceeds budget
        # pow2-pad the chunk dimension: tying it to the exact seed count
        # would recompile the hop programs (and rebuild different
        # shapes) for every distinct parameter value — padded chunks
        # keep shapes stable across a parameter sweep, and the last
        # block is zero-padded anyway.  Plain pow2, NOT backend.bucket:
        # its 256-row minimum would inflate a single-seed frontier (the
        # common point-lookup expand) by 256x in host upload and hop
        # gather work.
        seeds_p2 = 1 << max(0, n_seeds - 1).bit_length()
        chunk = max(1, min(seeds_p2, self._RING_MAX_MATRIX // per_seed))
        n_chunks = (n_seeds + chunk - 1) // chunk
        if n_chunks > 64:  # degenerate shapes stay on the join path
            return None
        frm = np.zeros(e_pad, dtype=np.int32)
        to = np.zeros(e_pad, dtype=np.int32)
        okp = np.zeros(e_pad, dtype=bool)
        frm[:a.shape[0]] = np.where(ok_cat, a, 0)
        to[:b.shape[0]] = np.where(ok_cat, b, 0)
        okp[:ok_cat.shape[0]] = ok_cat

        if self.upper == 3:
            # 3-hop isomorphism correction needs the entries' underlying
            # relationship ids (host-side sparse-hop build)
            rids = self._host_arrays(rel_t, rel_header.column(rv))
            if rids is None or not bool(np.all(rids[1] >= eok)):
                # the id column must be valid wherever the endpoints are
                # (a garbage id would corrupt the orientation grouping)
                return None
            rid_all = rids[0]
            if self.direction == Direction.BOTH:
                rid_cat = np.concatenate([rid_all, rid_all[nonloop]])
            else:
                rid_cat = rid_all
            # a/b are already live-compacted; align rids with the same mask
            sp13, spt = build_iso3_sparse(a, b, rid_cat[live], n_pad)

            def pad_sparse(tr):
                s, d, w = tr
                p = shard_pad(s.shape[0])
                ps = np.zeros(p, dtype=np.int32)
                pd = np.zeros(p, dtype=np.int32)
                pw = np.zeros(p, dtype=np.int64)
                ps[:s.shape[0]] = s
                pd[:d.shape[0]] = d
                pw[:w.shape[0]] = w
                return ps, pd, pw

            s13s, s13d, s13w = pad_sparse(sp13)
            sts, std_, stw = pad_sparse(spt)
            extra3 = tuple(jnp.asarray(x)
                           for x in (s13s, s13d, s13w, sts, std_, stw))
        else:
            extra3 = ()

        # constants uploaded ONCE; only the frontier block varies per call
        frm_d, to_d, okp_d, tmask_d = (jnp.asarray(frm), jnp.asarray(to),
                                       jnp.asarray(okp), jnp.asarray(tmask))

        def run_chunk(f0_np, lens):
            """One compiled program per distinct ``lens`` tuple."""
            base = (jnp.asarray(f0_np), frm_d, to_d, okp_d, tmask_d)
            if max(lens) == 3:
                fn = (ring_varexpand3_cached(backend.mesh, n_pad, lens,
                                             backend.axis, correction)
                      if on_ring
                      else ring_varexpand3_single(lens, correction))
                return fn(*base, *extra3)
            fn = (ring_varexpand_cached(backend.mesh, n_pad, lens,
                                        backend.axis, correction)
                  if on_ring
                  # single chip, or a 2-D (DCN x ICI) mesh where the
                  # GSPMD partitioner schedules the collectives
                  else ring_varexpand_single(lens, correction))
            return fn(*base)

        # emit_len: one multiplicity matrix PER length with its length
        # tagged on the rows; otherwise one matrix for the union
        length_runs = ([(L, (L,)) for L in lengths] if self.emit_len
                       else [(None, lengths)])
        parts: List[Table] = []
        for ci in range(n_chunks):
            block = seeds[ci * chunk:(ci + 1) * chunk]
            f0 = np.zeros((chunk, n_pad), dtype=np.int64)
            f0[np.arange(block.shape[0]), block] = 1
            for tag, lens in length_runs:
                m = run_chunk(f0, lens)
                counts = m.reshape(-1)
                total, live = backend.consume_rows(counts.sum())
                out_cap = backend.bucket(total)
                row, _within, valid, _tot = K.explode_expand(
                    counts, jnp.ones_like(counts, dtype=bool), out_cap)
                s_idx = row // n_pad
                v = row % n_pad
                block_pad = np.zeros(chunk, dtype=np.int64)
                block_pad[:block.shape[0]] = block
                src_ids = jnp.asarray(block_pad)[s_idx]
                cols = {
                    "__ring_src": Column(
                        "int", backend.place_rows(src_ids),
                        backend.place_rows(valid), CTInteger),
                    "__ring_tgt": Column(
                        "int", backend.place_rows(v.astype(jnp.int64)),
                        backend.place_rows(valid), CTInteger),
                }
                if tag is not None:
                    cols[self.emit_len] = Column(
                        "int",
                        backend.place_rows(jnp.full(out_cap, tag,
                                                    jnp.int64)),
                        backend.place_rows(valid), CTInteger)
                parts.append(DeviceTable(backend, cols, n=total, live=live))
        # balanced pairwise concat: incremental union over many chunk x
        # length parts would re-copy the accumulated rows quadratically
        while len(parts) > 1:
            parts = [parts[i].union_all(parts[i + 1])
                     if i + 1 < len(parts) else parts[i]
                     for i in range(0, len(parts), 2)]
        pairs = parts[0]
        return self._ring_assemble(parent_header, parent_table, src_id_col,
                                   tgt_header, tgt_table, tgt_id_col, pairs,
                                   rel_list_type)

    def _ring_assemble(self, parent_header, parent_table, src_id_col,
                       tgt_header, tgt_table, tgt_id_col, pairs,
                       rel_list_type):
        """(source, target) multiplicity rows -> the join path's exact
        output schema: parent columns + null rel-list (+ path-length)
        + target columns."""
        joined = parent_table.join(pairs, "inner",
                                   [(src_id_col, "__ring_src")])
        tt = tgt_table.rename({c: f"__t_{c}" for c in tgt_table.columns})
        joined = joined.join(tt, "inner",
                             [("__ring_tgt", f"__t_{tgt_id_col}")])
        joined = joined.rename({f"__t_{c}": c for c in tgt_table.columns})
        joined = joined.with_literal_column(self.rel, None, rel_list_type)
        out_header = parent_header.with_expr(E.Var(self.rel), rel_list_type,
                                             column=self.rel)
        if self.emit_len:
            out_header = out_header.with_expr(E.Var(self.emit_len),
                                              CTInteger,
                                              column=self.emit_len)
        out_header = out_header.concat(tgt_header)
        return out_header, joined.select(list(out_header.columns))

    # -- join path (the general form) --------------------------------------

    def _join_compute(self):
        parent_header, parent_table = self.children[0].result
        params = self.context.parameters
        rel_list_type: CypherType = CTList(CTRelationship(self.rel_types))

        src_id_col = parent_header.column(E.Var(self.source))
        if self.into:
            tgt_header = None
            tgt_id_col = parent_header.column(E.Var(self.target))
            final_cols = list(parent_table.columns) + [self.rel]
        else:
            tgt_header, tgt_table = self.graph.scan_node(
                self.target, self.target_labels)
            tgt_id_col = tgt_header.column(E.Var(self.target))
            final_cols = list(parent_table.columns) + [self.rel] \
                + list(tgt_header.columns)

        if self.emit_len:
            final_cols = final_cols + [self.emit_len]

        cur = "__vle_cur"
        frontier = parent_table.copy_column(src_id_col, cur)
        hop_id_cols: List[str] = []
        branches: List[Table] = []

        def finish_branch(t: Table, hops: List[str]) -> Table:
            """Pack hop ids into the rel list column, join/filter target,
            project to the uniform final column set."""
            t = t.pack_list(hops, self.rel, rel_list_type)
            if self.emit_len:
                t = t.with_literal_column(self.emit_len, len(hops),
                                          CTInteger)
            if self.into:
                sh = synth_header(t)
                t = t.filter(E.Equals(E.Var(cur), E.Var(tgt_id_col)), sh, params)
                return t.select(final_cols)
            tt = tgt_table.rename({c: f"__t_{c}" for c in tgt_table.columns})
            joined = t.join(tt, "inner", [(cur, f"__t_{tgt_id_col}")])
            joined = joined.rename(
                {f"__t_{c}": c for c in tgt_table.columns})
            return joined.select(final_cols)

        if self.lower == 0:
            branches.append(finish_branch(frontier, []))

        for k in range(1, self.upper + 1):
            hop_t, hid, hnear, hfar = self._rel_hop_table(k)
            joined = frontier.join(hop_t, "inner", [(cur, hnear)])
            # edge-isomorphism: this hop's rel must differ from all previous
            sh = synth_header(joined)
            for prev in hop_id_cols:
                joined = joined.filter(
                    E.Not(E.Equals(E.Var(hid), E.Var(prev))), sh, params)
            # advance the frontier cursor to the far end of this hop
            joined = joined.select(
                [c for c in joined.columns if c not in (cur, hnear)])
            joined = joined.copy_column(hfar, cur)
            joined = joined.select(
                [c for c in joined.columns if c != hfar])
            frontier = joined
            hop_id_cols = hop_id_cols + [hid]
            if k >= self.lower:
                branches.append(finish_branch(frontier, hop_id_cols))

        if not branches:
            raise ValueError("variable-length expand produced no branches")
        out = branches[0]
        for b in branches[1:]:
            out = out.union_all(b)

        out_header = parent_header.with_expr(E.Var(self.rel), rel_list_type,
                                             column=self.rel)
        if self.emit_len:
            out_header = out_header.with_expr(E.Var(self.emit_len),
                                              CTInteger,
                                              column=self.emit_len)
        if not self.into and tgt_header is not None:
            out_header = out_header.concat(tgt_header)
        return out_header, out.select(list(out_header.columns))

    def _pretty_args(self):
        return (f"({self.source})-[{self.rel}:{'|'.join(self.rel_types)}"
                f"*{self.lower}..{self.upper}]-({self.target})")

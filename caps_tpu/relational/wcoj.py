"""Worst-case-optimal multiway joins for cyclic MATCH patterns.

The binary join cascade the planner emits for a cyclic pattern —

    MATCH (a)-[r1:K]->(b)-[r2:K]->(c), (a)-[r3:K]->(c) RETURN a, b, c

— materializes every OPEN 2-path before the closing edge filters it:
intermediates grow with frontier x degree per hop, super-linearly with
pattern density, which is why rounds 3-5 had to hand-build the
count-only ``CountCycleOp`` just to make triangle counting viable.
:class:`MultiwayJoinOp` generalizes that analysis to arbitrary MATCH
(enumeration, not just counts), substituting ONE operator for the whole
detected cyclic segment (``logical/optimizer.py match_cyclic_segment``)
that binds the pattern variable-at-a-time in the TrieJax/leapfrog style
over the ``ops/wcoj.py`` sorted-edge layer:

* each new vertex expands along ONE cost-chosen **anchor** adjacency
  (the minimum-expected-degree incident edge — the leapfrog frontier),
  riding the same ``expand_positions`` kernel the join path uses;
* every OTHER incident pattern edge **semi-filters** the candidates
  immediately (sorted pair-key membership), so after compaction the
  frontier never exceeds the true partial-match count — the
  intermediate blow-up the cascade pays simply never materializes;
* the deferred edges then **close** by pair multiplicity, enumerating
  each parallel edge as its own binding (openCypher semantics), and
  relationship-isomorphism pairs absorbed from the segment's filters
  drop rows whose rel bindings coincide;
* finally each variable's scan columns are gathered once at the bound
  rows — the only full-width materialization in the whole pattern.

Established seams the operator rides:

* **pad-and-mask**: every step is a fixed-shape program at a
  ``shapes.py``-bucketed capacity with an exact live-row prefix, so the
  whole pattern compiles once per bucket and replays param-generically
  through the fused executor (sizes flow through ``consume_rows``);
* **compile ledger**: first-seen step shapes charge a ``wcoj`` kind
  (obs/compile.py) — warmed shapes and fused replays charge zero;
* **snapshot delta overlay**: scans go through ``graph.scan_node`` /
  ``scan_rel`` — the one seam that already serves masked base ∪ delta,
  so live writes are visible with no extra plumbing;
* **degraded fallback**: the embedded cascade child executes when the
  device path is unsuitable (host tables, mesh-sharded session, huge id
  domain) or FAULTS (``testing/faults.failing_wcoj``) — correctness
  never depends on the fast path;
* **cost model**: ``CostModel.wcoj_vs_cascade`` (relational/cost.py)
  decides substitution from the ingest-time degree/skew sketches and
  stamps the decision into EXPLAIN's cost section; the operator's
  ``est_rows`` feeds ``opstats.divergences`` and the existing re-plan
  loop.  ``EngineConfig.use_wcoj=False`` forces the cascade (the
  ``bench.py cyclic`` baseline contract).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional as Opt, Tuple

import numpy as np

from caps_tpu.ir import exprs as E
from caps_tpu.ir.pattern import Direction
from caps_tpu.logical.optimizer import (
    CyclicSegment, EdgeRef, match_cyclic_segment,
)
from caps_tpu.obs.compile import charged as _compile_charged
from caps_tpu.relational.header import RecordHeader
from caps_tpu.relational.ops import RelationalOperator, resolve_expr
from caps_tpu.serve.errors import CancellationError as _CancellationError

#: node-id domains above this refuse the composite-key form (keys are
#: frm*n + to in int64; the guard keeps n^2 < 2^52 with headroom)
_MAX_DOMAIN = 1 << 26


class _Unsuitable(Exception):
    """Runtime bail-out: serve this execution via the cascade child."""


@dataclasses.dataclass(frozen=True)
class ExtendStep:
    """Bind one new vertex: expand the ``anchor`` adjacency from
    ``probe`` (the bound endpoint), semi-filter by every other incident
    ``check`` edge."""
    var: str
    anchor: EdgeRef
    probe: str
    forward: bool  # probing along stored orientation (frm -> to)?
    checks: Tuple[EdgeRef, ...]


@dataclasses.dataclass(frozen=True)
class CloseStep:
    edge: EdgeRef


def plan_steps(seg: CyclicSegment, model=None
               ) -> Tuple[List[ExtendStep], List[CloseStep]]:
    """Assign each pattern edge a role under the plan-order binding
    sequence: for every new vertex, the incident edges whose other
    endpoint is already bound compete — the model's expected degree
    picks the anchor (min-degree frontier, the leapfrog choice), the
    rest semi-filter now and close later.  Without a model the
    introducing edge anchors (the cascade's own order)."""
    consumed: set = set()
    extends: List[ExtendStep] = []
    bound = {seg.seed}
    for var in seg.order[1:]:
        incident: List[Tuple[EdgeRef, str, bool]] = []
        for e in seg.edges:
            if e.rel in consumed or e.frm == e.to:
                continue
            if e.frm == var and e.to in bound:
                incident.append((e, e.to, False))
            elif e.to == var and e.frm in bound:
                incident.append((e, e.frm, True))
        if not incident:
            raise ValueError(f"variable {var!r} has no bound anchor")

        def score(item):
            e, _probe, forward = item
            if model is None:
                return 0.0 if e.intro == var else 1.0
            d = Direction.OUTGOING if forward else Direction.INCOMING
            return model.degree(e.rel_types, d)

        incident.sort(key=score)
        anchor, probe, forward = incident[0]
        consumed.add(anchor.rel)
        checks = tuple(e for e, _p, _f in incident[1:])
        extends.append(ExtendStep(var, anchor, probe, forward, checks))
        bound.add(var)
    closes = [CloseStep(e) for e in seg.edges if e.rel not in consumed]
    return extends, closes


def try_plan_wcoj(planner, op, build_fallback
                  ) -> Opt["MultiwayJoinOp"]:
    """Substitute a MultiwayJoinOp for the cyclic segment rooted at the
    into-Expand ``op``, or None to keep the cascade.  Selection is
    cost-based when the session carries a model; with the model off the
    detected shape substitutes unconditionally (use_wcoj=False disables
    both — the forced-cascade baseline).  ``build_fallback`` is a
    zero-arg builder invoked only AFTER the decision to substitute (the
    planner builds it with nested substitution suppressed, so one
    segment yields one operator and a pure-cascade fallback)."""
    session = planner.context.session
    config = getattr(session, "config", None)
    if not getattr(session, "supports_wcoj", False):
        return None
    if config is None or not getattr(config, "use_wcoj", False):
        return None
    seg = match_cyclic_segment(op)
    if seg is None:
        return None
    model = planner.cost_model
    try:
        extends, closes = plan_steps(seg, model)
    except ValueError:
        return None
    est_rows = 1.0
    if model is not None:
        node_preds = dict(seg.node_preds)

        def sel(var: str) -> float:
            return model.selectivity(node_preds.get(var, ()),
                                     seg.labels_of(var))

        ext_desc = []
        for s in extends:
            d = Direction.OUTGOING if s.forward else Direction.INCOMING
            checks = tuple(c.rel_types for c in s.checks)
            ext_desc.append((s.anchor.rel_types, d,
                             seg.labels_of(s.var), sel(s.var), checks))
        close_desc = [c.edge.rel_types for c in closes]
        use, est_rows, _info = model.wcoj_vs_cascade(
            seg.labels_of(seg.seed), sel(seg.seed), ext_desc, close_desc)
        if not use:
            return None
    registry = getattr(session, "metrics_registry", None)
    if registry is not None:
        registry.counter("wcoj.substituted").inc()
    out = MultiwayJoinOp(planner.context, build_fallback(),
                         planner.current_graph,
                         seg, tuple(extends), tuple(closes))
    out.planned_rows = max(1.0, float(est_rows))
    return out


class MultiwayJoinOp(RelationalOperator):
    """Enumerate all bindings of a cyclic pattern in one pass over
    sorted edge keys (module docstring).  Child 0 is the binary join
    cascade, evaluated lazily ONLY when the device path is unsuitable
    or faults — the degraded-mode contract."""

    def __init__(self, context, fallback: RelationalOperator, graph,
                 seg: CyclicSegment, extends: Tuple[ExtendStep, ...],
                 closes: Tuple[CloseStep, ...]):
        super().__init__(context, [fallback])
        self.graph = graph
        self.seg = seg
        self.extends = extends
        self.closes = closes
        self.strategy = "unplanned"
        self.planned_rows: float = 1.0

    # -- dispatch ----------------------------------------------------------

    def _compute(self):
        registry = self._registry()
        try:
            out = self._compute_wcoj()
            self.strategy = "wcoj"
            if registry is not None:
                registry.counter("wcoj.executions").inc()
        except _Unsuitable:
            # unsuitable shape/backend (host tables, mesh session,
            # oversized domain): served by the cascade — counted, so
            # a monitor can see the fast path is not running
            if registry is not None:
                registry.counter("wcoj.fallbacks").inc()
            self.strategy = "fallback-cascade"
            out = self.children[0].result
        except _CancellationError:
            raise  # budget expiry is the request's outcome, not a fault
        except Exception:
            # degraded mode: a faulting WCOJ path (injected or real)
            # falls back to the binary cascade — the same answer, none
            # of the suspect fast-path state
            if registry is not None:
                registry.counter("wcoj.fallbacks").inc()
            self.strategy = "fallback-cascade"
            out = self.children[0].result
        self._metric_extra = {"strategy": self.strategy}
        return out

    def _registry(self):
        session = getattr(self.context, "session", None)
        return getattr(session, "metrics_registry", None)

    # -- scan plumbing -----------------------------------------------------

    def _filtered_scan(self, header, table, preds):
        for pred in preds:
            table = table.filter(resolve_expr(pred, header), header,
                                 self.parameters)
        return table

    def _node_scan(self, var: str):
        preds = dict(self.seg.node_preds).get(var, ())
        header, t = self.graph.scan_node(var, self.seg.labels_of(var))
        return header, t, self._filtered_scan(header, t, preds)

    def _rel_scan(self, e: EdgeRef):
        preds = dict(self.seg.rel_preds).get(e.rel, ())
        header, t = self.graph.scan_rel(e.rel, e.rel_types)
        return header, self._filtered_scan(header, t, preds)

    # -- device path -------------------------------------------------------

    def _compute_wcoj(self):
        import jax.numpy as jnp
        from caps_tpu import ops as OPS
        from caps_tpu.backends.tpu import kernels as K
        from caps_tpu.backends.tpu.table import DeviceTable, _gather_cols
        from caps_tpu.ops import wcoj as W

        backend = getattr(self.context.factory, "backend", None)
        if backend is None:
            raise _Unsuitable("no device backend")
        if backend.mesh is not None:
            # mesh-sharded (cross-shard) session: the okapi distributed
            # joins own this layout — the cascade stays the executed
            # plan there, digest-equal by construction
            raise _Unsuitable("mesh-sharded session")
        if not backend.config.use_wcoj:
            raise _Unsuitable("use_wcoj disabled")
        config = backend.config
        use_pallas = bool(config.use_pallas and OPS.pallas_usable("prefetch"))
        interpret = OPS.default_interpret()
        seg = self.seg

        def need_device(t):
            if not isinstance(t, DeviceTable) or t.is_local:
                raise _Unsuitable("host-fallback table")
            return t

        # scans: the one seam that already overlays snapshot deltas
        node_parts: Dict[str, tuple] = {}
        for var in seg.order:
            header, _raw, t = self._node_scan(var)
            need_device(t)
            node_parts[var] = (header, t,
                               t._cols[header.column(E.Var(var))])
        rel_parts: Dict[str, tuple] = {}
        for e in seg.edges:
            header, t = self._rel_scan(e)
            need_device(t)
            v = E.Var(e.rel)
            rel_parts[e.rel] = (
                header, t,
                t._cols[header.column(E.StartNode(v))],
                t._cols[header.column(E.EndNode(v))],
                t._cols[header.column(v)])

        # id domain over everything the pattern touches
        mx = jnp.int64(-1)
        for _h, t, col in node_parts.values():
            mx = jnp.maximum(mx, jnp.max(jnp.where(
                col.valid & t.row_ok, col.data.astype(jnp.int64), -1)))
        for _h, t, src, tgt, _idc in rel_parts.values():
            ok = src.valid & tgt.valid & t.row_ok
            mx = jnp.maximum(mx, jnp.max(jnp.where(
                ok, src.data.astype(jnp.int64), -1)))
            mx = jnp.maximum(mx, jnp.max(jnp.where(
                ok, tgt.data.astype(jnp.int64), -1)))
        n = backend.consume_count(mx, relation="cap") + 1
        if n <= 0:
            n = 1
        if n > _MAX_DOMAIN:
            raise _Unsuitable(f"node-id domain {n} too large")

        def charged_shape(sig, fn):
            """Compile-ledger seam: the FIRST launch of a wcoj step at a
            new shape traces + XLA-compiles its programs — charge that
            wall time under the ``wcoj`` kind; warmed shapes (and every
            fused replay) charge nothing."""
            seen = getattr(backend, "wcoj_compiled_shapes", None)
            if seen is None:
                seen = backend.wcoj_compiled_shapes = set()
            if sig in seen:
                return fn()
            with _compile_charged("wcoj", shape=sig):
                out = fn()
            seen.add(sig)
            return out

        # sorted structures (memoized on stable scan columns — static
        # graphs sort once, snapshot overlays and predicate-filtered
        # scans rebuild per execution on their fresh columns)
        def edge_structure(e: EdgeRef, forward: bool):
            _h, t, src, tgt, _idc = rel_parts[e.rel]
            frm_col, to_col = (src, tgt) if forward else (tgt, src)
            key = (t._n, int(n), forward)
            memo = getattr(frm_col, "_wcoj_edges", None)
            if memo is not None and key in memo:
                return memo[key]
            ok = src.valid & tgt.valid & t.row_ok
            res = charged_shape(
                f"sort:b{t.capacity}",
                lambda: W.sorted_edges(frm_col.data, to_col.data, ok, n,
                                       t._sort_perm))
            if memo is None:
                memo = {}
                try:
                    frm_col._wcoj_edges = memo
                except Exception:  # pragma: no cover — frozen columns
                    return res
            if len(memo) < 8:
                memo[key] = res
            return res

        def node_structure(var: str):
            _h, t, col = node_parts[var]
            key = (t._n, int(n))
            memo = getattr(col, "_wcoj_ids", None)
            if memo is not None and memo[0] == key:
                return memo[1]
            keys = W.sorted_ids(col.data, col.valid & t.row_ok)
            perm = charged_shape(f"sort:b{t.capacity}",
                                 lambda: t._sort_perm([keys]))
            ids_sorted = keys[perm]
            dup = bool(np.asarray(
                ((ids_sorted[:-1] == ids_sorted[1:])
                 & (ids_sorted[:-1] < W.PAD_KEY)).any()))
            res = (ids_sorted, perm, dup)
            try:
                col._wcoj_ids = (key, res)
            except Exception:  # pragma: no cover
                pass
            return res

        # frontier: per bound node var its id + scan row, per bound rel
        # var its scan row — narrow int columns, the full-width gather
        # happens exactly once, at the end
        seed = seg.seed
        _sh, st_, scol = node_parts[seed]
        cap = st_.capacity
        n_rows, live = st_._n, st_._live
        state: Dict[tuple, object] = {
            ("id", seed): jnp.where(scol.valid,
                                    scol.data.astype(jnp.int64), -1),
            ("row", seed): jnp.arange(cap, dtype=jnp.int32),
        }

        def prefix_mask():
            m = jnp.arange(cap) < n_rows
            if live is not None:
                m = m & (jnp.arange(cap) < live)
            return m

        def compact(mask):
            nonlocal state, cap, n_rows, live
            count = K.mask_count(mask)
            n_rows, live = backend.consume_rows(count)
            out_cap = backend.bucket(n_rows)
            idx = charged_shape(
                f"compact:b{cap}x{out_cap}",
                lambda: K.compact_indices(mask, out_cap)[0])
            state = {k: v[idx] for k, v in state.items()}
            cap = out_cap

        for step in self.extends:
            S, P = edge_structure(step.anchor, step.forward)
            u_ids = state[("id", step.probe)]
            valid = prefix_mask()
            # the sizing probe is charged under its own shape (the first
            # dispatch traces + compiles it) and its results feed the
            # extend, which never probes the same adjacency twice
            counts, lo_a = charged_shape(
                f"adj:e{S.shape[0]}xb{cap}",
                lambda: W.probe_adj(S, u_ids, valid, jnp.int64(n)))
            total, t_live = backend.consume_rows(W.adj_total(counts))
            out_cap = backend.bucket(total)
            l_idx, cand, erow, ok = charged_shape(
                f"extend:e{S.shape[0]}b{cap}x{out_cap}",
                lambda: W.extend(S, P, u_ids, valid, n, out_cap,
                                 counts=counts, lo=lo_a,
                                 use_pallas=use_pallas,
                                 interpret=interpret))
            state = {k: v[l_idx] for k, v in state.items()}
            state[("erow", step.anchor.rel)] = erow
            cap, n_rows, live = out_cap, total, t_live
            # node membership = existence + labels + predicates (the
            # scan is pre-filtered); the sort perm doubles as id -> row
            ids_sorted, perm_v, dup = node_structure(step.var)
            if dup:
                raise _Unsuitable("duplicate node ids in scan")
            cnt_v, lo_v = charged_shape(
                f"nid:n{ids_sorted.shape[0]}xb{cap}",
                lambda: W.probe_id(ids_sorted, cand, ok))
            keep = ok & (cnt_v > 0)
            state[("id", step.var)] = cand
            state[("row", step.var)] = perm_v[
                jnp.clip(lo_v, 0, perm_v.shape[0] - 1)]
            # leapfrog semi-filters: every other incident pattern edge
            # must have at least one instance between the bound pair
            for c in step.checks:
                Sc, _Pc = edge_structure(c, True)
                cntc, _ = charged_shape(
                    f"pair:e{Sc.shape[0]}xb{cap}",
                    lambda: W.probe_pair(Sc, state[("id", c.frm)],
                                         state[("id", c.to)], keep,
                                         jnp.int64(n)))
                keep = keep & (cntc > 0)
            compact(keep)

        for step in self.closes:
            e = step.edge
            S, P = edge_structure(e, True)
            valid = prefix_mask()
            counts, lo_c = charged_shape(
                f"pair:e{S.shape[0]}xb{cap}",
                lambda: W.probe_pair(S, state[("id", e.frm)],
                                     state[("id", e.to)], valid,
                                     jnp.int64(n)))
            total, t_live = backend.consume_rows(W.adj_total(counts))
            out_cap = backend.bucket(total)
            l_idx, erow, _ok = charged_shape(
                f"close:e{S.shape[0]}b{cap}x{out_cap}",
                lambda: W.close(S, P, state[("id", e.frm)],
                                state[("id", e.to)], valid, n, out_cap,
                                counts=counts, lo=lo_c,
                                use_pallas=use_pallas,
                                interpret=interpret))
            state = {k: v[l_idx] for k, v in state.items()}
            state[("erow", e.rel)] = erow
            cap, n_rows, live = out_cap, total, t_live

        if self.seg.uniq_pairs:
            # relationship isomorphism absorbed from the segment's
            # filters: rel bindings of the named pairs must differ
            mask = prefix_mask()
            for r1, r2 in self.seg.uniq_pairs:
                id1 = rel_parts[r1][4].data[state[("erow", r1)]]
                id2 = rel_parts[r2][4].data[state[("erow", r2)]]
                mask = mask & (id1 != id2)
            compact(mask)

        # full-width materialization: gather each scan's columns once,
        # headers concatenated in the cascade's own order so downstream
        # operators see an identical layout
        out_cols: Dict[str, object] = {}
        headers = [node_parts[seed][0]]
        out_cols.update(_gather_cols(node_parts[seed][1]._cols,
                                     state[("row", seed)]))
        for e in seg.edges:
            headers.append(rel_parts[e.rel][0])
            out_cols_e = _gather_cols(rel_parts[e.rel][1]._cols,
                                      state[("erow", e.rel)])
            if set(out_cols) & set(out_cols_e):
                raise _Unsuitable("output column collision")
            out_cols.update(out_cols_e)
            if not e.closing:
                headers.append(node_parts[e.intro][0])
                out_cols_v = _gather_cols(node_parts[e.intro][1]._cols,
                                          state[("row", e.intro)])
                if set(out_cols) & set(out_cols_v):
                    raise _Unsuitable("output column collision")
                out_cols.update(out_cols_v)
        out_header = headers[0]
        for h in headers[1:]:
            out_header = out_header.concat(h)
        from caps_tpu.backends.tpu.table import DeviceTable as _DT
        return out_header, _DT(backend, out_cols, n_rows, live=live)

    # -- EXPLAIN -----------------------------------------------------------

    def _pretty_args(self):
        def edge(e: EdgeRef):
            t = "|".join(e.rel_types)
            tag = "*" if e.closing else ""
            return f"({e.frm})-[{e.rel}:{t}]{tag}->({e.to})"

        anchors = ",".join(f"{s.var}<~{s.anchor.rel}" for s in self.extends)
        return (f"{' '.join(edge(e) for e in self.seg.edges)}, "
                f"anchors=[{anchors}], strategy={self.strategy}")

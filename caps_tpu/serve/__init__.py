"""caps_tpu serving tier: concurrent multi-client query service.

The layer between many client threads and one engine session
(ROADMAP north star: heavy traffic through a TPU-resident graph):

    serve/errors.py     typed failure surface (Overloaded w/ retry_after,
                        DeadlineExceeded w/ phase attribution, Cancelled)
    serve/deadline.py   per-request budgets + cooperative cancel scopes,
                        checkpointed at engine phase boundaries
    serve/request.py    Request + the client-facing QueryHandle future
    serve/admission.py  bounded priority queue: admit or shed, never
                        queue unboundedly; graceful drain
    serve/batcher.py    micro-batching of plan-cache-compatible requests
    serve/failure.py    failure taxonomy: classify(exc) ->
                        TRANSIENT | POISONED_PLAN | FATAL
    serve/retry.py      RetryPolicy: deadline-charged backoff with
                        deterministic jitter
    serve/breaker.py    per-plan-family circuit breakers (quarantine +
                        degraded-ladder gating, health summary)
    serve/devices.py    device fault domains: per-device replica
                        sessions + replicated graphs, the health ladder
                        (healthy -> quarantined -> probing), background
                        canary probes, graph replication
    serve/shards.py     shard groups: one hash-partitioned graph behind
                        N member devices (capacity members mixed into
                        the ReplicaSet) — single-shard routing,
                        mesh-sharded cross-shard execution, group-level
                        health ladder with background member rebuild,
                        host-memory partition paging
    serve/server.py     QueryServer: worker pool (one worker per device
                        replica, or one serialized stream), serve.*
                        metrics, containment ladder, device failover,
                        snapshot pinning for versioned graphs
    serve/compaction.py background compaction of a versioned default
                        graph (delta-store backlog folding), health in
                        stats()["compaction"]
    serve/warmup.py     AOT server warmup: precompile the hot plan
                        families at start (explicit list or persistent
                        plan store — relational/plan_store.py), outcome
                        in stats()["warmup"] / health_report()
    serve/wire.py       fleet wire protocol: length-prefixed JSON
                        frames, typed-error round trip, WireClient
    serve/fleet.py      fleet backends: one QueryServer per process
                        behind a socket listener (in-process threads or
                        spawned interpreters), snapshot export/install
    serve/router.py     stateless consistent-hash router: plan-family
                        affinity, load-aware spill, ring-degrading
                        failover, snapshot shipping, fleet-wide scrape,
                        end-to-end deadline budgets, hedged reads
    serve/ha.py         router high availability: epoch-fenced
                        active/standby routers on a second lease
                        namespace, zombie-router fencing, the
                        RouterSet client facade

Engine hooks this package owns: ``RelationalCypherSession.cypher_batch``
(one batched pass over a cached plan), the deadline checkpoints in
``relational/session.py`` / ``relational/ops.py``, and the fused
executor's batched-replay accounting (``backends/tpu/fused.py``).

``errors`` and ``deadline`` load eagerly (the engine imports them);
the server stack loads on first attribute access so importing the
relational layer never pulls in the whole tier.
"""
from caps_tpu.serve.deadline import (CancelScope, cancel_scope, checkpoint,
                                     current_scope)
from caps_tpu.serve.errors import (Cancelled, CancellationError, CircuitOpen,
                                   CompactionFailed, DeadlineExceeded,
                                   Overloaded, QueryFailed, ServeError,
                                   ServerClosed, WaitTimeout)
from caps_tpu.serve.failure import (FATAL, POISONED_PLAN, TRANSIENT,
                                    attribute_device, classify, device_fault,
                                    device_of)

_LAZY = {
    "QueryServer": "caps_tpu.serve.server",
    "ServerConfig": "caps_tpu.serve.server",
    "AdmissionController": "caps_tpu.serve.admission",
    "MicroBatcher": "caps_tpu.serve.batcher",
    "batch_key": "caps_tpu.serve.batcher",
    "QueryHandle": "caps_tpu.serve.request",
    "Request": "caps_tpu.serve.request",
    "INTERACTIVE": "caps_tpu.serve.request",
    "BATCH": "caps_tpu.serve.request",
    "RetryPolicy": "caps_tpu.serve.retry",
    "CircuitBreaker": "caps_tpu.serve.breaker",
    # re-exported from obs/telemetry.py: the serving SLO config rides
    # ServerConfig, so clients naturally look for it here
    "SLOConfig": "caps_tpu.obs.telemetry",
    "Compactor": "caps_tpu.serve.compaction",
    "WarmupConfig": "caps_tpu.serve.warmup",
    "ServerWarmup": "caps_tpu.serve.warmup",
    "ReplicaSet": "caps_tpu.serve.devices",
    "DeviceReplica": "caps_tpu.serve.devices",
    "replicate_graph": "caps_tpu.serve.devices",
    "executing_device_index": "caps_tpu.serve.devices",
    # sharded serving (serve/shards.py): partitioned graphs behind the
    # same QueryServer — shard-group capacity members next to replicas
    "ShardGroup": "caps_tpu.serve.shards",
    "ShardGroupConfig": "caps_tpu.serve.shards",
    "GraphPartition": "caps_tpu.serve.shards",
    "partition_graph": "caps_tpu.serve.shards",
    "executing_shard": "caps_tpu.serve.shards",
    "ShardingUnsupported": "caps_tpu.serve.errors",
    "ShardMemberDown": "caps_tpu.serve.errors",
    # fleet serving (serve/wire.py, serve/fleet.py, serve/router.py):
    # multi-process scale-out behind a consistent-hash router
    "WireError": "caps_tpu.serve.errors",
    "FleetUnavailable": "caps_tpu.serve.errors",
    "error_from_payload": "caps_tpu.serve.errors",
    "WireClient": "caps_tpu.serve.wire",
    "BackendSpec": "caps_tpu.serve.fleet",
    "FleetBackend": "caps_tpu.serve.fleet",
    "spawn_backend": "caps_tpu.serve.fleet",
    "rows_digest": "caps_tpu.serve.fleet",
    "HashRing": "caps_tpu.serve.router",
    "RouterConfig": "caps_tpu.serve.router",
    "FleetRouter": "caps_tpu.serve.router",
    # router HA (serve/ha.py): replicated routers behind one lease
    "HARouter": "caps_tpu.serve.ha",
    "RouterSet": "caps_tpu.serve.ha",
    "RouterSpec": "caps_tpu.serve.ha",
    "spawn_router": "caps_tpu.serve.ha",
}

__all__ = [
    "ServeError", "ServerClosed", "Overloaded", "CancellationError",
    "DeadlineExceeded", "Cancelled", "CircuitOpen", "QueryFailed",
    "WaitTimeout", "CompactionFailed", "CancelScope", "cancel_scope",
    "checkpoint",
    "current_scope", "classify", "TRANSIENT", "POISONED_PLAN", "FATAL",
    "device_fault", "attribute_device", "device_of",
    *sorted(_LAZY),
]


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    return getattr(importlib.import_module(mod), name)

"""Admission control: a bounded, priority-classed request queue.

The server never queues unboundedly.  ``offer`` either admits a request
or sheds it with a typed :class:`~caps_tpu.serve.errors.Overloaded`
carrying a ``retry_after_s`` hint (queue depth x recent per-request
service time / worker count — the telemetry window's mean when it has
samples, the running EMA as fallback).  Two bounds apply:

* a global capacity (``max_queue``) across all priorities;
* optional per-priority limits, so background/batch traffic cannot
  starve interactive requests of queue space (interactive work can
  still use the whole queue when it is alone).

``take`` serves strict priority order (lower value first), FIFO within
a class.  ``take_compatible`` is the micro-batcher's entry: it removes
up to ``n`` further requests sharing a batch key, scanning every
priority class — a follower admitted at low priority rides an
interactive leader's batch for free.

All state lives behind one condition variable; the queue-depth gauge
and the admitted/shed counters land in the server's metrics registry
(``serve.*`` in ``session.metrics_snapshot()``).
"""
from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from caps_tpu.obs import clock
from caps_tpu.obs.lockgraph import make_condition, make_lock
from caps_tpu.obs.metrics import MetricsRegistry
from caps_tpu.serve.errors import Overloaded, ServerClosed
from caps_tpu.serve.request import Request

#: retry_after floor: even an empty estimate asks clients to back off a
#: scheduling quantum rather than hot-loop on the server.
_MIN_RETRY_S = 0.001

_gauge_guard = make_lock("admission._gauge_guard")


def _register_depth_gauge(registry: MetricsRegistry,
                          controller: "AdmissionController") -> None:
    """``serve.queue_depth`` reports the TOTAL queued across every live
    controller on this registry (a session may run several servers —
    bench.py's serve mode does): controllers join the set here and
    leave it in :meth:`AdmissionController.close`, so the gauge never
    gets hijacked by the newest server or pinned by a dead one."""
    with _gauge_guard:
        live = getattr(registry, "_serve_live_controllers", None)
        if live is None:
            live = registry._serve_live_controllers = []
            registry.gauge("serve.queue_depth",
                           fn=lambda: sum(c._depth for c in live))
        live.append(controller)


def _deregister_depth_gauge(registry: MetricsRegistry,
                            controller: "AdmissionController") -> None:
    with _gauge_guard:
        live = getattr(registry, "_serve_live_controllers", [])
        if controller in live:
            live.remove(controller)


class AdmissionController:
    def __init__(self, registry: MetricsRegistry, max_queue: int = 64,
                 per_priority_limits: Optional[Dict[int, int]] = None,
                 workers: int = 1, telemetry=None):
        self.max_queue = max(1, int(max_queue))
        self.per_priority_limits = dict(per_priority_limits or {})
        self.workers = max(1, int(workers))
        self._cond = make_condition("admission.AdmissionController._cond")
        self._queues: Dict[int, Deque[Request]] = {}
        self._depth = 0
        self._closed = False
        #: EMA of per-request service seconds, updated by the server
        #: after each batch — the retry_after estimator's FALLBACK rate
        #: term (see retry_after_s).
        self.ema_service_s = 0.0
        #: optional windowed-telemetry handle (obs/telemetry.py
        #: ServingTelemetry): sheds are noted into the rolling window,
        #: and retry_after's rate term prefers the window's recent mean
        #: service time over the forever-EMA.
        self._telemetry = telemetry
        self._admitted = registry.counter("serve.admitted")
        self._shed = registry.counter("serve.shed")
        self._requeued = registry.counter("serve.requeued")
        self._registry = registry
        _register_depth_gauge(registry, self)

    # -- producer side -------------------------------------------------

    def depth(self, priority: Optional[int] = None) -> int:
        with self._cond:
            if priority is None:
                return self._depth
            q = self._queues.get(priority)
            return len(q) if q else 0

    def retry_after_s(self, depth: Optional[int] = None) -> float:
        """Back-off hint: queue depth × per-request service time /
        parallel streams.  The rate term prefers the telemetry window's
        recent mean service time; the forever-EMA is only the fallback
        for windows with no samples (cold start, long idle) — a one-off
        slow burst therefore stops inflating shed hints as soon as it
        rotates out of the window, instead of lingering in the EMA."""
        d = self._depth if depth is None else depth
        rate = self.ema_service_s
        if self._telemetry is not None:
            recent = self._telemetry.recent_service_s()
            if recent is not None:
                rate = recent
        return max(_MIN_RETRY_S, d * rate / self.workers)

    def observe_service(self, per_request_s: float) -> None:
        """Fold one batch's per-request service time into the EMA
        (locked: concurrent workers must not lose each other's
        updates)."""
        with self._cond:
            ema = self.ema_service_s
            self.ema_service_s = per_request_s if ema == 0.0 \
                else 0.8 * ema + 0.2 * per_request_s

    def set_active_workers(self, n: int) -> None:
        """Degraded-capacity accounting (device fault domains): the
        ``retry_after_s`` estimator divides queue depth by the number of
        PARALLEL streams actually draining it, so a quarantined device
        must fall out of the denominator — with W-1 of W devices live,
        clients are told to back off proportionally longer.  The server
        calls this on every quarantine/reinstate transition."""
        with self._cond:
            self.workers = max(1, int(n))

    def requeue(self, request: Request) -> None:
        """Return a CLAIMED request to the front of its priority class —
        the device-quarantine drain path: a worker whose device was just
        quarantined hands its unexecuted batch back to the dispatcher so
        another device's worker serves it.  Never sheds (the request was
        already admitted once) and works after ``close()`` (a graceful
        drain must still complete requeued work)."""
        with self._cond:
            q = self._queues.get(request.priority)
            if q is None:
                q = self._queues[request.priority] = deque()
            q.appendleft(request)
            self._depth += 1
            self._requeued.inc()
            self._cond.notify_all()

    def offer(self, request: Request) -> None:
        """Admit or shed.  Raises ServerClosed / Overloaded."""
        with self._cond:
            if self._closed:
                raise ServerClosed("server is shutting down")
            prio = request.priority
            limit = self.per_priority_limits.get(prio)
            q = self._queues.get(prio)
            prio_depth = len(q) if q else 0
            if self._depth >= self.max_queue or \
                    (limit is not None and prio_depth >= limit):
                self._shed.inc()
                if self._telemetry is not None:
                    self._telemetry.note_shed()
                raise Overloaded(
                    f"queue full (depth {self._depth}/{self.max_queue}, "
                    f"priority {prio}: {prio_depth}"
                    f"{'' if limit is None else '/%d' % limit})",
                    retry_after_s=self.retry_after_s(),
                    queue_depth=self._depth, priority=prio)
            if q is None:
                q = self._queues[prio] = deque()
            request.enqueued_t = clock.now()
            q.append(request)
            self._depth += 1
            self._admitted.inc()
            # notify_all, not notify: the condition is shared by idle
            # take() waiters AND batch-window wait_for_compatible()
            # waiters — a single wakeup could be swallowed by a window
            # waiter the new request doesn't match while an idle worker
            # sleeps through it
            self._cond.notify_all()

    # -- consumer side (workers) ---------------------------------------

    def _pop_next_locked(self) -> Optional[Request]:
        for prio in sorted(self._queues):
            q = self._queues[prio]
            if q:
                self._depth -= 1
                return q.popleft()
        return None

    def take(self, timeout: Optional[float] = None) -> Optional[Request]:
        """Next request in priority order, waiting up to ``timeout``.
        Returns None on timeout or when closed with an empty queue."""
        deadline = None if timeout is None else clock.now() + timeout
        with self._cond:
            while True:
                req = self._pop_next_locked()
                if req is not None:
                    return req
                if self._closed:
                    return None
                wait = None if deadline is None else deadline - clock.now()
                if wait is not None and wait <= 0:
                    return None
                self._cond.wait(wait)

    def take_compatible(self, batch_key: Tuple, n: int) -> List[Request]:
        """Remove up to ``n`` queued requests with this batch key (any
        priority, FIFO within each class, priority order across)."""
        out: List[Request] = []
        if n <= 0 or batch_key is None:
            return out
        with self._cond:
            for prio in sorted(self._queues):
                q = self._queues[prio]
                if not q:
                    continue
                keep: Deque[Request] = deque()
                while q:
                    r = q.popleft()
                    if len(out) < n and r.batch_key == batch_key:
                        out.append(r)
                    else:
                        keep.append(r)
                self._queues[prio] = keep
                if len(out) >= n:
                    break
            self._depth -= len(out)
        return out

    def wait_for_compatible(self, batch_key: Tuple, want: int,
                            window_s: float) -> None:
        """Block up to ``window_s`` for ``want`` compatible requests to
        be queued (the batching window).  Wakes early when satisfied."""
        if window_s <= 0 or want <= 0 or batch_key is None:
            return
        deadline = clock.now() + window_s
        with self._cond:
            while True:
                have = sum(1 for q in self._queues.values()
                           for r in q if r.batch_key == batch_key)
                if have >= want or self._closed:
                    return
                wait = deadline - clock.now()
                if wait <= 0:
                    return
                self._cond.wait(wait)

    # -- lifecycle -----------------------------------------------------

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        # leave the queue-depth gauge's live set: a closed controller
        # must not report stale depth or stay pinned by the callback
        _deregister_depth_gauge(self._registry, self)

    @property
    def closed(self) -> bool:
        return self._closed

    def drain_remaining(self) -> List[Request]:
        """Remove and return every queued request (non-drain shutdown
        completes them with Cancelled)."""
        with self._cond:
            out = [r for prio in sorted(self._queues)
                   for r in self._queues[prio]]
            self._queues.clear()
            self._depth = 0
            self._cond.notify_all()
        return out

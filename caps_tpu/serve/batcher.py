"""Micro-batching: group compatible in-flight requests into one run.

The serving analogue of continuous batching in TPU LLM inference
(PAPERS.md, *Ragged Paged Attention*): throughput comes from pushing
many small requests through one compiled program.  Here the compiled
program is a cached prepared plan — requests are compatible when they
would hit the SAME plan-cache entry family, i.e. share

    (graph plan token, normalized query text, parameter signature)

which is exactly the session plan cache's value-independent key minus
the catalog fingerprint (the batch executes at one instant, so all
members see the same catalog).  A batch executes as one pass over the
cached operator tree — one cache lookup, one plan lock, one tracer
span — with per-member parameter rebinding; on the TPU backend the
members' fused replays dispatch back-to-back as one uninterrupted
async stream (backends/tpu/fused.py ``batch``).

Never batched (batch key None): EXPLAIN/PROFILE requests (PROFILE
mutates session profiling state and must run alone), queries against
graphs that cannot anchor a plan-cache entry, and parameter sets whose
signatures diverge — those fall back to per-request execution.

**Ragged bucket batching** (``ServerConfig.ragged_batching``): the
batch key widens from the exact plan-key family to a (graph, parameter
shape-bucket signature) — see ``relational/shapes.py`` — so *different*
queries whose operator launches are shape-compatible pack into one
shared device launch window.  Exactness is untouched: every member
still executes its OWN cached plan with per-member parameter rebinding
(and, on device backends, bucket-padded tables with validity masks —
the exact-row masks of the pad-and-pack scheme), and per-member
exception isolation is the same ``cypher_batch`` contract as before.
The request keeps its exact plan key alongside (``Request.plan_key``)
for everything that must stay per-family: circuit breakers, plan
quarantine, and telemetry labels.
"""
from __future__ import annotations

from typing import Any, List, Mapping, Optional, Tuple

from caps_tpu.serve.admission import AdmissionController
from caps_tpu.serve.request import Request


def request_keys(graph: Any, query: str, params: Mapping[str, Any],
                 ragged: bool = False, lattice: Any = None
                 ) -> Tuple[Optional[str], Optional[Tuple],
                            Optional[Tuple]]:
    """(query mode, plan key, batch key).  Plan key None = the request
    can never anchor shared cached state (EXPLAIN/PROFILE, writes,
    uncacheable graphs); batch key None = never batch.  Update
    statements report mode ``"write"``: they never coalesce (each is one
    atomic commit with its own read half) and the server routes them to
    the versioned handle instead of a pinned snapshot.  With ``ragged``
    the batch key is the shape-bucket signature instead of the exact
    plan family."""
    from caps_tpu.frontend.parser import normalize_query, query_mode
    from caps_tpu.relational.plan_cache import (graph_plan_token,
                                                param_signature)
    from caps_tpu.relational.updates import is_update_query
    mode, body = query_mode(query)
    if mode is not None:
        return mode, None, None
    if is_update_query(body):
        return "write", None, None
    gtok = graph_plan_token(graph)
    if gtok is None:
        return None, None, None
    try:
        sig = param_signature(params)
    except Exception:
        return None, None, None
    plan_key = (gtok, normalize_query(body), sig)
    if not ragged:
        return None, plan_key, plan_key
    # ``lattice`` should be the serving session's shape lattice so the
    # bucket key agrees with the padding ladder and compile-shape
    # labels (one boundary set); None falls back to the process default
    from caps_tpu.relational.shapes import param_shape_signature
    return None, plan_key, (gtok, "bucket",
                            param_shape_signature(params, lattice))


def batch_key(graph: Any, query: str,
              params: Mapping[str, Any]) -> Tuple[Optional[str],
                                                  Optional[Tuple]]:
    """(query mode, exact-family batch key) — the pre-ragged view, kept
    for callers that only need plan-key compatibility."""
    mode, _plan_key, key = request_keys(graph, query, params)
    return mode, key


class MicroBatcher:
    """Pulls a leader from the admission queue, then gathers compatible
    followers — everything already queued, plus (optionally) whatever
    arrives inside ``window_s``.  ``window_s`` trades leader latency
    for batch size; the default 0 batches only what is already there."""

    def __init__(self, admission: AdmissionController, max_batch: int = 8,
                 window_s: float = 0.0):
        self.admission = admission
        self.max_batch = max(1, int(max_batch))
        self.window_s = float(window_s)

    def next_batch(self, timeout: Optional[float] = None) -> List[Request]:
        leader = self.admission.take(timeout)
        if leader is None:
            return []
        if leader.batch_key is None or self.max_batch == 1:
            return [leader]
        if self.window_s > 0:
            # don't wait past the leader's own deadline
            window = self.window_s
            rem = leader.scope.remaining()
            if rem is not None:
                window = min(window, max(0.0, rem))
            self.admission.wait_for_compatible(
                leader.batch_key, self.max_batch - 1, window)
        followers = self.admission.take_compatible(
            leader.batch_key, self.max_batch - 1)
        return [leader] + followers

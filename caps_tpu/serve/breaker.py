"""Per-plan-family circuit breakers: stop burning device time on a
query family that fails deterministically.

One breaker instance guards one :class:`~caps_tpu.serve.QueryServer`;
state is per *plan family* — the same key the micro-batcher groups by
(graph plan token, normalized query, parameter signature), because that
is the granularity at which a poisoned cached plan keeps hurting.

Classic three-state machine, all transitions driven by
``caps_tpu.obs.clock`` (fake-clock testable):

* **closed** — serving normally; ``failure_threshold`` CONSECUTIVE
  request-level failures (a request that exhausted the worker's whole
  containment ladder) trip it to open.  Any success resets the count.
* **open** — requests of the family fast-fail with
  :class:`~caps_tpu.serve.errors.CircuitOpen` carrying the remaining
  cooldown as ``retry_after_s``; the device never sees them.  Other
  families are untouched — that is the containment property the soak
  test asserts.
* **half-open** — after ``cooldown_s``, exactly ONE trial request is
  let through (concurrent arrivals keep fast-failing); its success
  closes the breaker, its failure re-opens it for another cooldown.

``serve.breaker.*`` metrics land in the server's registry; the summary
feeds ``QueryServer.stats()["health"]``.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from caps_tpu.obs import clock
from caps_tpu.obs.lockgraph import make_lock

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

#: admit() verdicts
ALLOW = "allow"          # closed: execute normally
TRIAL = "trial"          # half-open probe: execute degraded, one at a time
REJECT = "reject"        # open: fast-fail with CircuitOpen


class _Family:
    __slots__ = ("state", "failures", "opened_t", "trial_in_flight",
                 "trips", "last_error")

    def __init__(self):
        self.state = CLOSED
        self.failures = 0
        self.opened_t = 0.0
        self.trial_in_flight = False
        self.trips = 0
        self.last_error: Optional[str] = None


class CircuitBreaker:
    """``metric_prefix`` scopes the counters: the plan-family breaker
    reports under ``serve.breaker.*`` (the default), while the device
    health ladder (serve/devices.py) reuses this exact state machine
    device-scoped under ``serve.device_breaker.*`` — quarantined is
    open, probing is half-open, one background canary per trial slot."""

    def __init__(self, registry, failure_threshold: int = 3,
                 cooldown_s: float = 5.0,
                 metric_prefix: str = "serve.breaker"):
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_s = float(cooldown_s)
        self._lock = make_lock("breaker.CircuitBreaker._lock")
        self._families: Dict[Any, _Family] = {}
        self._opened = registry.counter(f"{metric_prefix}.opened")
        self._closed_again = registry.counter(f"{metric_prefix}.closed")
        self._fast_fails = registry.counter(f"{metric_prefix}.fast_fail")
        registry.gauge(f"{metric_prefix}.open", fn=self.open_count)

    # -- serving-path API ----------------------------------------------

    def admit(self, key: Any) -> Tuple[str, float]:
        """Decide how a request of this family may execute.

        Returns ``(ALLOW, 0)``, ``(TRIAL, 0)`` (caller MUST report the
        outcome via record_success/record_failure so the trial slot
        frees), or ``(REJECT, retry_after_s)``."""
        now = clock.now()
        with self._lock:
            fam = self._families.get(key)
            if fam is None or fam.state == CLOSED:
                return ALLOW, 0.0
            if fam.state == OPEN:
                waited = now - fam.opened_t
                if waited < self.cooldown_s:
                    self._fast_fails.inc()
                    return REJECT, max(0.0, self.cooldown_s - waited)
                fam.state = HALF_OPEN
                fam.trial_in_flight = True
                return TRIAL, 0.0
            # HALF_OPEN: one probe at a time
            if fam.trial_in_flight:
                self._fast_fails.inc()
                return REJECT, self.cooldown_s
            fam.trial_in_flight = True
            return TRIAL, 0.0

    def record_success(self, key: Any) -> None:
        with self._lock:
            fam = self._families.get(key)
            if fam is None:
                return
            if fam.state in (HALF_OPEN, OPEN):
                self._closed_again.inc()
            fam.state = CLOSED
            fam.failures = 0
            fam.trial_in_flight = False
            fam.last_error = None

    def record_failure(self, key: Any,
                       error: Optional[BaseException] = None) -> bool:
        """Fold one request-level failure in.  Returns True when THIS
        failure tripped the family open (the caller then quarantines the
        cached plan — see server._recover)."""
        with self._lock:
            fam = self._families.setdefault(key, _Family())
            if error is not None:
                fam.last_error = type(error).__name__
            if fam.state == HALF_OPEN:
                # failed probe: straight back to open, fresh cooldown
                fam.state = OPEN
                fam.opened_t = clock.now()
                fam.trial_in_flight = False
                fam.trips += 1
                self._opened.inc()
                return True
            fam.failures += 1
            if fam.state == CLOSED and \
                    fam.failures >= self.failure_threshold:
                fam.state = OPEN
                fam.opened_t = clock.now()
                fam.trips += 1
                self._opened.inc()
                return True
            return False

    def abort_trial(self, key: Any) -> None:
        """Free a half-open trial slot without a verdict (the trial
        request was cancelled / expired before executing) — the next
        arrival gets the probe instead."""
        with self._lock:
            fam = self._families.get(key)
            if fam is not None and fam.state == HALF_OPEN:
                fam.trial_in_flight = False

    # -- inspection ----------------------------------------------------

    def state(self, key: Any) -> str:
        with self._lock:
            fam = self._families.get(key)
            return fam.state if fam is not None else CLOSED

    def open_count(self) -> int:
        with self._lock:
            return sum(1 for f in self._families.values()
                       if f.state != CLOSED)

    def summary(self) -> Dict[str, Any]:
        """Aggregate view for ``server.stats()``: state counts plus the
        non-closed families (key repr truncated — keys embed query
        text)."""
        with self._lock:
            counts = {CLOSED: 0, OPEN: 0, HALF_OPEN: 0}
            broken = []
            for key, fam in self._families.items():
                counts[fam.state] += 1
                if fam.state != CLOSED:
                    broken.append({"family": repr(key)[:120],
                                   "state": fam.state,
                                   "failures": fam.failures,
                                   "trips": fam.trips,
                                   "last_error": fam.last_error})
            return {"counts": counts, "broken": broken}

"""Background compaction for versioned graphs behind the serving tier.

A versioned graph's delta store (relational/updates.py) is bounded by
design — scans overlay a small ragged delta on the fixed-shape base —
but only compaction keeps it that way: folding base + delta into a
fresh base snapshot resets the tombstone masks and the delta CSR to
empty.  Under serving load that fold must happen in the background,
off the request path, and its health must be *visible*: a compactor
that silently died turns a bounded overlay into an unbounded one.

:class:`Compactor` is that background task.  It watches one
``VersionedGraph``'s backlog (``delta_rows``) and folds whenever the
configured threshold is crossed; :class:`~caps_tpu.serve.QueryServer`
starts one automatically when its default graph is versioned and a
threshold is configured, stops it on shutdown, and surfaces
:meth:`summary` under ``stats()["compaction"]`` (a failing compactor
degrades ``health()``).

Failure containment: a failed fold (device OOM mid-re-ingest, an
injected ``flaky_compaction`` fault) rolls back via the same
string-pool mark machinery as writes, counts ``compaction.failures``,
keeps the last error for ``summary()``, and retries on the next tick —
serving is never affected (readers keep their snapshots; writers keep
committing deltas)."""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from caps_tpu.obs import clock
from caps_tpu.serve.errors import CompactionFailed

#: idle states the summary reports
IDLE = "idle"
RUNNING = "running"
FAILING = "failing"
STOPPED = "stopped"


class Compactor:
    """Threshold-driven background compaction of one versioned graph."""

    def __init__(self, graph, registry, threshold_rows: Optional[int] = 512,
                 interval_s: float = 0.05, on_failure=None,
                 threshold_bytes: Optional[int] = None):
        if not getattr(graph, "graph_is_versioned", False):
            raise CompactionFailed(
                f"compaction needs a versioned graph, got "
                f"{type(graph).__name__}")
        self.graph = graph
        #: either trigger may be None (disabled); crossing EITHER live
        #: threshold folds.  Bytes come from ``graph.delta_nbytes()``
        #: (relational/updates.py) — a few huge property rows can now
        #: trigger compaction long before the row count would.
        self.threshold_rows = (max(1, int(threshold_rows))
                               if threshold_rows is not None else None)
        self.threshold_bytes = (max(1, int(threshold_bytes))
                                if threshold_bytes is not None else None)
        self.interval_s = float(interval_s)
        #: optional incident hook called with the exception after every
        #: failed fold — the server wires the telemetry flight-recorder
        #: auto-dump here (a dying compactor is a postmortem trigger)
        self._on_failure = on_failure
        self._failures = registry.counter("compaction.failures")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._state = IDLE
        self._last_error: Optional[str] = None
        self._consecutive_failures = 0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "Compactor":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="caps-tpu-compactor", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._state = STOPPED

    # -- the loop ------------------------------------------------------

    def _over_threshold(self) -> bool:
        if self.threshold_rows is not None \
                and self.graph.delta_rows() >= self.threshold_rows:
            return True
        if self.threshold_bytes is not None \
                and self.graph.delta_nbytes() >= self.threshold_bytes:
            return True
        return False

    def _loop(self) -> None:
        while not self._stop.is_set():
            if self._over_threshold():
                self._state = RUNNING
                try:
                    self.graph.compact()
                except Exception as ex:
                    # a failed fold never hurts serving: count it, keep
                    # the error visible, retry next tick (the rollback
                    # already ran inside compact())
                    self._failures.inc()
                    self._consecutive_failures += 1
                    self._last_error = f"{type(ex).__name__}: {ex}"
                    self._state = FAILING
                    if self._on_failure is not None:
                        try:
                            self._on_failure(ex)
                        except Exception:  # pragma: no cover — hook only
                            pass
                else:
                    self._consecutive_failures = 0
                    self._last_error = None
                    self._state = IDLE
            elif self._state != FAILING:
                self._state = IDLE
            # interruptible nap: stop() wakes the thread immediately
            clock.wait(self._stop, self.interval_s)

    # -- health --------------------------------------------------------

    @property
    def failing(self) -> bool:
        """True after a failed fold with no success since — the server's
        health() reports degraded while this holds."""
        return self._state == FAILING

    def summary(self) -> Dict[str, Any]:
        return {
            "state": self._state,
            "backlog_rows": self.graph.delta_rows(),
            "threshold_rows": self.threshold_rows,
            "backlog_bytes": self.graph.delta_nbytes(),
            "threshold_bytes": self.threshold_bytes,
            "consecutive_failures": self._consecutive_failures,
            "last_error": self._last_error,
        }

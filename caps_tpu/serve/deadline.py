"""Per-request deadlines and cooperative cancellation.

A :class:`CancelScope` carries one request's budget (seconds from
submission) and its cancel flag.  The serving worker installs the scope
on the executing thread (:func:`cancel_scope`), and the engine calls
:func:`checkpoint` at pipeline phase boundaries — after parse, after
planning, at every relational-operator boundary during execute, and
around result materialization.  An expired or cancelled scope raises the
typed error *at the next checkpoint*: cancellation is cooperative, a
device program already dispatched is never torn down mid-flight (the
same contract as the fused executor's async streams).

Checkpoints are free when no scope is installed (one thread-local read),
so the unserved paths — plain ``session.cypher()`` calls — pay nothing.

Expiry leaves evidence: the raising checkpoint emits a
``deadline.exceeded`` event into the active tracer (when tracing is on)
and the exception propagating through open spans marks each of them with
an ``error`` attribute, so an expired query's trace shows exactly where
the budget went.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Iterator, Optional

from caps_tpu.obs import clock
from caps_tpu.obs.tracer import active_tracer
from caps_tpu.serve.errors import Cancelled, DeadlineExceeded


class CancelScope:
    """One request's cancellation state: a start time, an optional
    budget, and a cancel flag.  Thread-safe: the flag is an Event set by
    the client thread and read by the executing worker."""

    __slots__ = ("t0", "budget_s", "phase", "_cancelled")

    def __init__(self, budget_s: Optional[float] = None,
                 t0: Optional[float] = None):
        self.t0 = clock.now() if t0 is None else t0
        self.budget_s = budget_s
        #: last phase boundary this request crossed (queued | parse |
        #: plan | execute | materialize) — updated by checkpoint()
        self.phase = "queued"
        self._cancelled = threading.Event()

    def cancel(self) -> None:
        self._cancelled.set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    @property
    def cancel_event(self) -> threading.Event:
        """The underlying cancel Event — the interruptible-wait handle
        retry backoff sleeps block on (``clock.wait``), so ``cancel()``
        wakes a backing-off worker immediately."""
        return self._cancelled

    def elapsed(self) -> float:
        return clock.now() - self.t0

    def remaining(self) -> Optional[float]:
        """Seconds of budget left (None = no deadline)."""
        if self.budget_s is None:
            return None
        return self.budget_s - self.elapsed()

    def expired(self) -> bool:
        rem = self.remaining()
        return rem is not None and rem <= 0.0

    def raise_if_done(self, phase: str) -> None:
        """Raise the typed error if this scope is cancelled or expired,
        attributing it to ``phase``; otherwise record the boundary."""
        self.phase = phase
        if self._cancelled.is_set():
            raise Cancelled(phase=phase)
        if self.expired():
            elapsed = self.elapsed()
            tracer = active_tracer()
            if tracer.enabled:
                tracer.event("deadline.exceeded", kind="event", phase=phase,
                             budget_s=self.budget_s, elapsed_s=elapsed)
            raise DeadlineExceeded(phase=phase, budget_s=self.budget_s,
                                   elapsed_s=elapsed)


_tls = threading.local()


def current_scope() -> Optional[CancelScope]:
    """The scope installed on the calling thread, or None."""
    return getattr(_tls, "scope", None)


@contextlib.contextmanager
def cancel_scope(scope: Optional[CancelScope]) -> Iterator[
        Optional[CancelScope]]:
    """Install ``scope`` for the duration (None = explicitly no scope,
    shadowing any outer one — nested sessions must not inherit a
    caller's budget by accident)."""
    prev = getattr(_tls, "scope", None)
    _tls.scope = scope
    try:
        yield scope
    finally:
        _tls.scope = prev


def checkpoint(phase: str) -> None:
    """Phase-boundary check the engine calls (relational/session.py,
    relational/ops.py).  No scope installed → one thread-local read and
    return."""
    scope = getattr(_tls, "scope", None)
    if scope is not None:
        scope.raise_if_done(phase)

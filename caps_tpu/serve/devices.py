"""Device fault domains: the replica set behind :class:`QueryServer`.

ROADMAP item 1 (distributed serving): the serving tier used to drive ONE
device stream behind one lock; `parallel/` already proves 8-device
execution, and PR 4's failure taxonomy is the substrate for treating a
dead device as a quarantined worker, not a dead server.  This module is
that substrate made concrete:

* :class:`DeviceReplica` — one device's worth of serving state: its own
  engine session (per-device plan cache, string pool, fused size memos —
  compiled/cached state NEVER crosses devices), its own replicated copy
  of each served graph (ingest once per device), its own execution lock
  (one dispatch stream per device), and per-device request counters.
* :class:`ReplicaSet` — placement and the per-device health ladder
  ``healthy -> quarantined -> probing -> healthy``, driven by the same
  three-state breaker machine the plan families use
  (:class:`~caps_tpu.serve.breaker.CircuitBreaker` with a
  ``serve.device_breaker`` metric prefix): ``device_failure_threshold``
  consecutive device-attributed failures quarantine the device; after
  ``device_cooldown_s`` a BACKGROUND canary probe (never a user request)
  runs half-open; its success reinstates the device, its failure buys
  another cooldown.
* :func:`replicate_graph` — backend-generic re-ingest of a ScanGraph
  into another session: columns are read back to host values and rebuilt
  through the target session's table factory, so each replica owns
  device-resident buffers placed by ITS backend.
* :func:`executing_device_index` — a thread-local stamp of which replica
  the calling thread is executing on.  The fault-injection harness
  (``testing/faults.py`` ``device_loss`` / ``sick_device``) scopes
  injected device faults to one replica's operator stream through it.

On CPU the replicas are *simulated* devices (``device=None``): distinct
sessions with distinct cached state, which is everything the failover
logic observes — the whole quarantine/probe/reinstate path is
tier-1-testable with no accelerator.  On a TPU platform each replica is
pinned to a real ``jax.devices()`` entry and all its placements and
computations run under ``jax.default_device``.
"""
from __future__ import annotations

import contextlib
import itertools
import threading
from typing import Any, Dict, List, Optional, Tuple

from caps_tpu.obs import clock
from caps_tpu.obs.lockgraph import make_lock
from caps_tpu.serve.breaker import (CLOSED, HALF_OPEN, OPEN, CircuitBreaker)
from caps_tpu.serve.deadline import cancel_scope
from caps_tpu.serve.errors import ReplicationUnsupported

#: per-device health ladder states (the rollup QueryServer.stats() shows)
HEALTHY = "healthy"
QUARANTINED = "quarantined"
PROBING = "probing"

_BREAKER_TO_HEALTH = {CLOSED: HEALTHY, OPEN: QUARANTINED,
                      HALF_OPEN: PROBING}

#: background-probe canary: must run a real operator stream on the
#: replica (a plain node scan — no count pushdown, no aggregation), so a
#: device fault scoped to this replica fails the probe too
_CANARY_QUERY = "MATCH (n) RETURN n LIMIT 1"

#: replicated graphs kept per device (LRU): each entry is a full
#: re-ingested copy holding device buffers, so the cache must not grow
#: with every short-lived graph a long-lived server ever saw
MAX_REPLICA_GRAPHS = 8

_exec_tls = threading.local()

_session_locks_guard = make_lock("devices._session_locks_guard")


def executing_device_index() -> Optional[int]:
    """The replica index the calling thread is currently executing on
    (None outside a replica's execution bracket).  The device-scoped
    fault injectors key off this."""
    return getattr(_exec_tls, "device_index", None)


# chrome-trace device lanes: spans opened inside a replica's execution
# bracket carry the device index, and obs/export.py renders it as the
# trace event's pid — multi-replica traces lay out as parallel lanes.
# The provider hook lives in obs/tracer.py (obs/ never imports serve/).
from caps_tpu.obs import tracer as _tracer_mod  # noqa: E402

_tracer_mod.set_device_index_provider(executing_device_index)


def _session_exec_lock(session) -> threading.Lock:
    """The ONE execution lock of a session, attached on first use: every
    server/replica over the same session must serialize through the same
    lock (the engine's execution state — fused record/replay activation,
    profiling flags — is per-session)."""
    lock = getattr(session, "_serve_exec_lock", None)
    if lock is None:
        with _session_locks_guard:
            lock = getattr(session, "_serve_exec_lock", None)
            if lock is None:
                lock = make_lock("devices.DeviceReplica.lock")
                session._serve_exec_lock = lock
    return lock


# -- graph replication -------------------------------------------------------

def _clone_table(factory, table):
    data = {c: table.column_values(c) for c in table.columns}
    types = {c: table.column_type(c) for c in table.columns}
    return factory.from_columns(data, types)


def supports_replication(graph) -> bool:
    """True when :func:`replicate_graph` can re-ingest this graph: scan
    graphs, the empty ambient graph, and versioned SNAPSHOTS over a scan
    base (the base re-ingests once per device; the snapshot's host-level
    delta overlay rebuilds cheaply on the replica — see
    ``DeviceReplica.graph_for``).  Requests against anything else
    (union/catalog graphs, and WRITES — which target the mutable
    versioned handle) are pinned to device 0, which serves them on the
    original session."""
    from caps_tpu.relational.graphs import EmptyGraph, ScanGraph
    from caps_tpu.relational.updates import GraphSnapshot
    if isinstance(graph, GraphSnapshot):
        return isinstance(graph.base, ScanGraph)
    return graph is None or isinstance(graph, (EmptyGraph, ScanGraph))


def replicate_graph(graph, session):
    """Re-ingest ``graph`` into ``session``: read every entity table's
    columns back to host values and rebuild them through the target
    session's table factory — the replica ends up with ITS OWN
    device-resident buffers, string-pool codes, and CSR layout, sharing
    nothing compiled or placed with the source."""
    from caps_tpu.relational.entity_tables import (NodeTable,
                                                   RelationshipTable)
    from caps_tpu.relational.graphs import EmptyGraph, ScanGraph
    if graph is None or isinstance(graph, EmptyGraph):
        return session._ambient
    if not isinstance(graph, ScanGraph):
        raise ReplicationUnsupported(
            f"cannot replicate a {type(graph).__name__} onto another "
            f"device (only scan graphs re-ingest); requests against it "
            f"serve on device 0")
    factory = session.table_factory
    node_tables = [NodeTable(nt.mapping, _clone_table(factory, nt.table))
                   for nt in graph.node_tables]
    rel_tables = [RelationshipTable(rt.mapping,
                                    _clone_table(factory, rt.table))
                  for rt in graph.rel_tables]
    return session.create_graph(node_tables, rel_tables)


def _acquire_devices(n: int) -> List[Any]:
    """Real accelerator devices when the platform has them, else
    simulated devices (None): per-session isolation is the part of the
    fault domain the failover logic observes, and it needs no
    accelerator."""
    try:
        import jax
        devs = jax.devices()
        if devs and devs[0].platform != "cpu" and len(devs) >= n:
            return list(devs[:n])
    except Exception:  # pragma: no cover — jax-less / broken platform
        pass
    return [None] * n


class DeviceReplica:
    """One device's serving state: session, graphs, lock, counters."""

    def __init__(self, index: int, session, device: Any = None):
        self.index = index
        self.session = session
        #: a real jax Device (TPU platform) or None (simulated device)
        self.device = device
        #: one dispatch stream per device: every execution on this
        #: replica (including cross-device retries and probes) holds it
        self.lock = _session_exec_lock(session)
        self._stats_lock = make_lock("devices.DeviceReplica._stats_lock")
        self.requests = 0
        self.completed = 0
        self.failed = 0
        self.quarantines = 0
        self.reinstates = 0
        self.probes = 0
        #: id(template graph) -> (template graph, replica graph); LRU
        #: bounded — insertion-ordered dict, oldest evicted past the cap
        #: so a long-lived server cycling through many short-lived
        #: graphs cannot pin dead graphs' device buffers forever
        self._graphs: Dict[int, Tuple[Any, Any]] = {}
        self._graphs_lock = make_lock("devices.DeviceReplica._graphs_lock")

    @contextlib.contextmanager
    def activate(self):
        """Execution bracket: stamps the executing-device thread-local
        (the device-scoped fault injectors key off it) and, on
        real-device replicas, pins jax's default placement so every
        array this execution creates lands on THIS device."""
        prev = getattr(_exec_tls, "device_index", None)
        _exec_tls.device_index = self.index
        try:
            if self.device is None:
                yield
            else:
                import jax
                with jax.default_device(self.device):
                    yield
        finally:
            _exec_tls.device_index = prev

    def graph_for(self, graph):
        """This replica's copy of ``graph``, re-ingested on first use
        (and eagerly at server construction for the default graph).
        Replica 0 serves the ORIGINAL objects — it owns the template
        session, so its 'copy' is the graph itself.

        Versioned snapshots (relational/updates.py) replicate in two
        parts: the immutable BASE re-ingests once per device (cached by
        identity, shared by every snapshot of the lineage), and the
        snapshot's host-level delta overlay rebuilds through this
        replica's factory — a cross-device retry of a pinned read
        therefore executes the SAME snapshot version on different
        hardware."""
        if self.index == 0 or graph is None:
            return graph if graph is not None else self.session._ambient
        from caps_tpu.relational.updates import GraphSnapshot
        if isinstance(graph, GraphSnapshot):
            # resolve the base copy FIRST (recursive call takes the
            # lock; holding it here would deadlock)
            base_copy = self.graph_for(graph.base)
            key = id(graph)
            with self._graphs_lock:
                got = self._graphs.get(key)
                if got is not None and got[0] is graph:
                    self._graphs[key] = self._graphs.pop(key)
                    return got[1]
                with self.activate():
                    replica_graph = graph.rebase(self.session, base_copy)
                self._graphs[key] = (graph, replica_graph)
                while len(self._graphs) > MAX_REPLICA_GRAPHS:
                    self._graphs.pop(next(iter(self._graphs)))
                return replica_graph
        key = id(graph)
        with self._graphs_lock:
            got = self._graphs.get(key)
            if got is not None and got[0] is graph:
                # LRU touch: re-insert at the newest position
                self._graphs[key] = self._graphs.pop(key)
                return got[1]
            with self.activate():
                replica_graph = replicate_graph(graph, self.session)
            self._graphs[key] = (graph, replica_graph)
            while len(self._graphs) > MAX_REPLICA_GRAPHS:
                self._graphs.pop(next(iter(self._graphs)))
            return replica_graph

    def first_graph(self):
        """A replicated scan graph to canary-probe with (None when this
        replica has never served one)."""
        if self.index == 0:
            return None
        with self._graphs_lock:
            for _tmpl, g in self._graphs.values():
                if getattr(g, "node_tables", None):
                    return g
        return None

    def note(self, *, requests: int = 0, completed: int = 0,
             failed: int = 0) -> None:
        with self._stats_lock:
            self.requests += requests
            self.completed += completed
            self.failed += failed

    def snapshot(self) -> Dict[str, Any]:
        with self._stats_lock:
            return {"device": self.index,
                    "placement": "simulated" if self.device is None
                    else str(self.device),
                    "requests": self.requests,
                    "completed": self.completed,
                    "failed": self.failed,
                    "quarantines": self.quarantines,
                    "reinstates": self.reinstates,
                    "probes": self.probes}


class ReplicaSet:
    """N device replicas + the per-device health ladder.

    ``session`` is the template: replica 0 reuses it (and the caller's
    original graph objects); replicas 1..N-1 get fresh
    ``session.clone()`` sessions with their own plan caches, string
    pools, and fused memos, plus re-ingested graph copies — compiled
    state never migrates across devices (docs/tpu.md).

    The health ladder reuses the breaker state machine, device-scoped:
    quarantined == open (the device serves nothing), probing ==
    half-open (exactly one background canary in flight).  Only
    *device-attributed* failures (``serve.failure.device_fault``) climb
    the ladder — a user's bad query must never take a device down.  With
    a single replica the ladder is disabled: there is no second device
    to fail over to, so quarantining the only one would turn a sick
    device into a dead server.
    """

    def __init__(self, session, graph=None, n_devices: int = 1,
                 registry=None, failure_threshold: int = 3,
                 cooldown_s: float = 1.0, on_change=None, groups=()):
        n = max(1, int(n_devices))
        devices = _acquire_devices(n)
        self.replicas: List[DeviceReplica] = []
        #: shard-group members (serve/shards.py): capacity members that
        #: front ONE hash-partitioned graph each, mixed behind the same
        #: server next to the throughput replicas above.  Groups keep
        #: their own (group-level) health ladder; the replica breaker
        #: below never sees them.
        self.groups = list(groups)
        for i in range(n):
            s = session if i == 0 else session.clone()
            self.replicas.append(DeviceReplica(i, s, devices[i]))
        if graph is not None and supports_replication(graph):
            # ingest once per device, up front: serving never pays a
            # surprise re-ingest, and a broken replication fails loudly
            # at construction.  Non-replicable default graphs (union /
            # catalog) are NOT an error — their requests pin to
            # device 0 (replica_for), the other replicas idle for them.
            for r in self.replicas:
                r.graph_for(graph)
        self._breaker = CircuitBreaker(
            registry, failure_threshold=failure_threshold,
            cooldown_s=cooldown_s, metric_prefix="serve.device_breaker")
        self._quarantined_c = registry.counter("serve.devices.quarantined")
        self._reinstated_c = registry.counter("serve.devices.reinstated")
        self._probes_c = registry.counter("serve.devices.probes")
        self._on_change = on_change
        self._rr = itertools.count()

    def __len__(self) -> int:
        return len(self.replicas)

    # -- shard groups (serve/shards.py) --------------------------------

    def group_for(self, graph):
        """The shard group serving this graph, or None (the graph is
        replica territory).  Claimed batches against a group graph
        redirect here whichever worker claimed them."""
        for g in self.groups:
            if g.serves(graph):
                return g
        return None

    @staticmethod
    def _is_group(member) -> bool:
        from caps_tpu.serve.shards import ShardGroup
        return isinstance(member, ShardGroup)

    # -- health --------------------------------------------------------

    def state(self, replica) -> str:
        if self._is_group(replica):
            return replica.health()
        index = replica.index if isinstance(replica, DeviceReplica) \
            else int(replica)
        if len(self.replicas) == 1:
            return HEALTHY
        return _BREAKER_TO_HEALTH[self._breaker.state(index)]

    def is_healthy(self, replica) -> bool:
        if self._is_group(replica):
            # a DEGRADED group still serves (healthy members + retry
            # ladder); only a quarantined group stops claiming work
            from caps_tpu.serve.shards import GROUP_QUARANTINED
            return replica.health() != GROUP_QUARANTINED
        return self.state(replica) == HEALTHY

    def live_count(self) -> int:
        return sum(1 for r in self.replicas if self.is_healthy(r)) \
            + sum(1 for g in self.groups if self.is_healthy(g))

    def quarantined_count(self) -> int:
        return len(self.replicas) + len(self.groups) - self.live_count()

    def health(self) -> Dict[int, str]:
        return {r.index: self.state(r) for r in self.replicas}

    def _changed(self) -> None:
        if self._on_change is not None:
            try:
                self._on_change()
            except Exception:  # pragma: no cover — bookkeeping only
                pass

    # -- outcome bookkeeping (the ladder's input) ----------------------

    def record_success(self, replica) -> None:
        if self._is_group(replica):
            replica.record_success()
            return
        replica.note(completed=1)
        if len(self.replicas) > 1:
            self._breaker.record_success(replica.index)

    def record_failure(self, replica, exc: BaseException):
        """Fold one execution failure in.  Only device-attributed errors
        count against the device; returns truthy when THIS failure
        quarantined it (the caller drains its claimed work back to the
        dispatcher and lets the background probe reinstate it).  Shard
        groups return ``"member"`` / ``"group"`` for the level that
        tripped (their ladder is group-scoped — serve/shards.py)."""
        from caps_tpu.serve.failure import device_fault
        if self._is_group(replica):
            tripped = replica.record_failure(exc)
            if tripped:
                self._changed()
            return tripped
        replica.note(failed=1)
        if len(self.replicas) == 1 or not device_fault(exc):
            return False
        tripped = self._breaker.record_failure(replica.index, exc)
        if tripped:
            with replica._stats_lock:
                replica.quarantines += 1
            self._quarantined_c.inc()
            tracer = replica.session.tracer
            if tracer.enabled:
                tracer.event("device.quarantined", device=replica.index,
                             error=type(exc).__name__)
            self._changed()
        return tripped

    # -- background probe (quarantined -> probing -> healthy) ----------

    def try_probe(self, replica):
        """Breaker admit for the background probe: ``(TRIAL, 0)`` when
        the cooldown elapsed and this caller owns the single probe slot,
        else ``(REJECT, remaining_cooldown)``.  Shard groups gate on
        their own maintenance cadence."""
        if self._is_group(replica):
            return replica.probe_gate()
        return self._breaker.admit(replica.index)

    def probe(self, replica) -> bool:
        if self._is_group(replica):
            # the group's "probe" is one maintenance pass: per-member
            # canaries + background rebuild onto a spare session
            ok = replica.maintenance_tick()
            self._changed()
            return ok
        return self._probe_replica(replica)

    def _probe_replica(self, replica: DeviceReplica) -> bool:
        """Run the health canary on the replica's own session/device —
        a replicated-graph scan when one exists (so operator-stream
        faults scoped to this device fail the probe), else a tiny
        arithmetic program.  Success reinstates the device; failure
        re-opens the quarantine for another cooldown."""
        replica.note()
        with replica._stats_lock:
            replica.probes += 1
        self._probes_c.inc()
        tracer = replica.session.tracer
        try:
            with replica.lock, cancel_scope(None), replica.activate():
                g = replica.first_graph()
                if g is not None:
                    g.cypher(_CANARY_QUERY)
                else:
                    self._arith_canary(replica.device)
            ok = True
        except BaseException:
            ok = False
        if ok:
            was = self._breaker.state(replica.index)
            self._breaker.record_success(replica.index)
            if was != CLOSED:
                with replica._stats_lock:
                    replica.reinstates += 1
                self._reinstated_c.inc()
                if tracer.enabled:
                    tracer.event("device.reinstated", device=replica.index)
        else:
            self._breaker.record_failure(replica.index)
            if tracer.enabled:
                tracer.event("device.probe_failed", device=replica.index)
        self._changed()
        return ok

    @staticmethod
    def _arith_canary(device) -> None:
        import jax
        import jax.numpy as jnp
        x = jnp.arange(8, dtype=jnp.int32)
        if device is not None:
            x = jax.device_put(x, device)
        got = int((x * 2 + 1).sum())
        if got != 64:  # pragma: no cover — silent corruption
            raise ReplicationUnsupported(
                f"device canary arithmetic returned {got}, expected 64")

    # -- placement -----------------------------------------------------

    def replica_for(self, replica, graph):
        """Where a claimed batch actually executes: a shard-group graph
        always executes on its group (whichever worker claimed it);
        otherwise the claiming worker's own device, except
        non-replicable graphs (union/catalog graphs) which pin to
        device 0 — the template session is the only one that can
        resolve them.  A group worker that claimed a non-group batch
        hands it to device 0 the same way."""
        group = self.group_for(graph)
        if group is not None:
            return group
        if self._is_group(replica):
            return self.replicas[0]
        if replica.index != 0 and not supports_replication(graph):
            return self.replicas[0]
        return replica

    def retry_target(self, exclude_index) -> DeviceReplica:
        """A DIFFERENT healthy device for a transient retry (round-robin
        over the healthy survivors).  ``exclude_index`` is one index or
        an ordered collection of EVERY index that already failed this
        request — with more than one member unhealthy mid-window a
        second retry must not land back on the first failed device.
        Falls back to the most recently excluded device when no healthy
        candidate remains — a same-device retry is still better than
        giving up."""
        if isinstance(exclude_index, int):
            excluded = [exclude_index]
        else:
            excluded = list(exclude_index)
        excluded_set = set(excluded)
        cands = [r for r in self.replicas
                 if r.index not in excluded_set and self.is_healthy(r)]
        if not cands:
            # prefer the most recent failure that actually names a
            # replica (a shard group's index is not in this list)
            for idx in reversed(excluded):
                if 0 <= idx < len(self.replicas):
                    return self.replicas[idx]
            return self.replicas[0]
        return cands[next(self._rr) % len(cands)]

    def summary(self) -> List[Dict[str, Any]]:
        out = []
        for r in self.replicas:
            snap = r.snapshot()
            snap["health"] = self.state(r)
            out.append(snap)
        return out

    def group_summaries(self) -> List[Dict[str, Any]]:
        """Per shard-group structured health (``stats()["shards"]``)."""
        return [g.summary() for g in self.groups]

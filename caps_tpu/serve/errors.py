"""Typed errors of the serving tier.

Every failure mode a client of :class:`caps_tpu.serve.QueryServer` can
see is a distinct exception type carrying machine-usable fields — a
load-shedding client retries after ``Overloaded.retry_after_s``, a
deadline miss reports *which pipeline phase* consumed the budget
(``DeadlineExceeded.phase``) so capacity planning can tell a planning
stall from a device stall from queue pressure.
"""
from __future__ import annotations

from typing import Optional, Tuple


class ServeError(RuntimeError):
    """Base class for all serving-tier errors.

    Invariant (enforced by ``scripts/check_serve_errors.py``): every
    exception *constructed and raised* inside ``caps_tpu/serve/``
    inherits from this class, so a client needs exactly one except
    clause to catch everything the serving tier itself can signal."""


class ServerClosed(ServeError):
    """submit() after shutdown() began: the server accepts no new work."""


class Overloaded(ServeError):
    """Admission control shed this request instead of queuing unboundedly.

    ``retry_after_s`` is the server's estimate of when capacity frees up
    (queue depth x recent per-request service time / workers) — the
    back-off hint a well-behaved client honors."""

    def __init__(self, message: str, retry_after_s: float = 0.0,
                 queue_depth: int = 0, priority: int = 0):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.queue_depth = queue_depth
        self.priority = priority


class WaitTimeout(ServeError, TimeoutError):
    """A *client wait* on a handle ran out (``QueryHandle.result(timeout)``)
    — says nothing about the request itself, which is still in flight.
    Subclasses :class:`TimeoutError` so pre-existing ``except
    TimeoutError`` call sites keep working."""


class QueryFailed(ServeError):
    """Terminal failure after the server exhausted its containment
    ladder (transient retries, plan quarantine, degraded re-execution).

    ``attempts`` is the machine-readable attempt history — one dict per
    execution with the mode it ran in (``fused`` / ``replan`` /
    ``unfused``), the error type/classification observed, and any backoff
    charged — so a client (or the soak test) can reconstruct exactly
    what the server tried.  ``retry_after_s`` reuses the
    :class:`Overloaded` hint semantics: when the give-up was budget- or
    breaker-driven, it is the earliest time a retry could behave
    differently (0.0 = retrying will not help)."""

    def __init__(self, message: str, attempts: Tuple[dict, ...] = (),
                 retry_after_s: float = 0.0):
        super().__init__(message)
        self.attempts = tuple(attempts)
        self.retry_after_s = retry_after_s


class CircuitOpen(QueryFailed):
    """Fast-fail: this request's plan family tripped its circuit breaker
    and the cooldown has not elapsed — the server refuses to burn device
    time on a family that is failing deterministically.  ``retry_after_s``
    is the remaining cooldown (after it, one half-open trial runs)."""

    def __init__(self, message: str, retry_after_s: float = 0.0):
        super().__init__(message, attempts=(), retry_after_s=retry_after_s)


class CompactionFailed(ServeError):
    """The background compactor (serve/compaction.py) could not run —
    misconfiguration (a non-versioned graph) or a fold failure surfaced
    to a caller.  Routine fold failures are NOT raised: they roll back,
    count ``compaction.failures``, and retry on the next tick."""


class ReplicationUnsupported(ServeError):
    """A graph that cannot be re-ingested onto another device replica
    (only scan graphs and the empty ambient graph replicate — see
    ``serve/devices.py``).  The server never surfaces this to clients:
    requests against such graphs are pinned to device 0."""


class ShardingUnsupported(ServeError):
    """A graph or query that cannot be served by a shard group
    (serve/shards.py): non-scan graphs cannot partition, and writes
    against a partitioned graph are rejected — the commit lock does
    not shard.  Classified FATAL: retrying cannot change it."""


class ShardMemberDown(ServeError):
    """A single-shard-routed query's owning member is quarantined and
    its background rebuild has not finished.  Marked ``caps_transient``
    at construction: the serving tier's retry ladder backs off and
    re-executes — by then the rebuild may have reinstated the member —
    instead of walking the poisoned-plan ladder."""

    def __init__(self, message: str, member: Optional[int] = None):
        super().__init__(message)
        self.caps_transient = True
        if member is not None:
            #: member attribution for the group ladder (serve/shards.py)
            self.caps_shard_member = member


class CancellationError(ServeError):
    """Base of the two cooperative-cancel outcomes (deadline, explicit).

    The fused executor re-raises these immediately instead of treating
    them as replay divergence: a query killed by its budget must not be
    transparently re-executed."""

    def __init__(self, message: str, phase: str = "?"):
        super().__init__(message)
        #: pipeline phase at which the cancellation was observed
        #: (queued | parse | plan | execute | materialize)
        self.phase = phase


class DeadlineExceeded(CancellationError):
    """The request's deadline expired; ``phase`` attributes the budget."""

    def __init__(self, phase: str, budget_s: Optional[float],
                 elapsed_s: float):
        super().__init__(
            f"deadline exceeded in phase {phase!r} "
            f"(budget {budget_s if budget_s is not None else '?'} s, "
            f"elapsed {elapsed_s:.4f} s)", phase=phase)
        self.budget_s = budget_s
        self.elapsed_s = elapsed_s


class Cancelled(CancellationError):
    """The client cancelled the request (``QueryHandle.cancel()``)."""

    def __init__(self, phase: str = "queued"):
        super().__init__(f"request cancelled in phase {phase!r}",
                         phase=phase)

"""Typed errors of the serving tier.

Every failure mode a client of :class:`caps_tpu.serve.QueryServer` can
see is a distinct exception type carrying machine-usable fields — a
load-shedding client retries after ``Overloaded.retry_after_s``, a
deadline miss reports *which pipeline phase* consumed the budget
(``DeadlineExceeded.phase``) so capacity planning can tell a planning
stall from a device stall from queue pressure.

**Wire fidelity.**  The fleet tier (serve/wire.py, serve/router.py)
carries these errors between processes.  Every class serializes with
:meth:`ServeError.to_payload` and reconstructs with
:func:`error_from_payload` — EXACTLY: message, ``retry_after_s``,
``attempts`` histories, phases, and budget fields all survive the JSON
round trip, so a remote client's backoff and retry decisions are made
from the same machine-usable fields a local caller would see
(tests/test_fleet.py runs the parity matrix over every class here)."""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple


class ServeError(RuntimeError):
    """Base class for all serving-tier errors.

    Invariant (enforced by the capslint error-taxonomy pass): every
    exception *constructed and raised* inside ``caps_tpu/serve/``
    inherits from this class, so a client needs exactly one except
    clause to catch everything the serving tier itself can signal."""

    def to_payload(self) -> Dict[str, Any]:
        """JSON-able wire form: class name, message, and every
        machine-usable field (:meth:`_payload_fields`).  The inverse is
        :func:`error_from_payload`."""
        out: Dict[str, Any] = {"error": type(self).__name__,
                               "message": str(self)}
        out.update(self._payload_fields())
        return out

    def _payload_fields(self) -> Dict[str, Any]:
        """Subclass hook: the constructor-relevant fields beyond the
        message (must round-trip through JSON exactly)."""
        return {}

    @classmethod
    def _rebuild(cls, payload: Dict[str, Any]) -> "ServeError":
        """Reconstruct from :meth:`to_payload` output.  The default
        covers message-only constructors; field-carrying subclasses
        override it to restore their exact machine-usable state."""
        return cls(str(payload.get("message", "")))


class ServerClosed(ServeError):
    """submit() after shutdown() began: the server accepts no new work."""


class Overloaded(ServeError):
    """Admission control shed this request instead of queuing unboundedly.

    ``retry_after_s`` is the server's estimate of when capacity frees up
    (queue depth x recent per-request service time / workers) — the
    back-off hint a well-behaved client honors."""

    def __init__(self, message: str, retry_after_s: float = 0.0,
                 queue_depth: int = 0, priority: int = 0):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.queue_depth = queue_depth
        self.priority = priority

    def _payload_fields(self) -> Dict[str, Any]:
        return {"retry_after_s": self.retry_after_s,
                "queue_depth": self.queue_depth,
                "priority": self.priority}

    @classmethod
    def _rebuild(cls, payload: Dict[str, Any]) -> "Overloaded":
        return cls(str(payload.get("message", "")),
                   retry_after_s=float(payload.get("retry_after_s", 0.0)),
                   queue_depth=int(payload.get("queue_depth", 0)),
                   priority=int(payload.get("priority", 0)))


class WaitTimeout(ServeError, TimeoutError):
    """A *client wait* on a handle ran out (``QueryHandle.result(timeout)``)
    — says nothing about the request itself, which is still in flight.
    Subclasses :class:`TimeoutError` so pre-existing ``except
    TimeoutError`` call sites keep working."""


class QueryFailed(ServeError):
    """Terminal failure after the server exhausted its containment
    ladder (transient retries, plan quarantine, degraded re-execution).

    ``attempts`` is the machine-readable attempt history — one dict per
    execution with the mode it ran in (``fused`` / ``replan`` /
    ``unfused``), the error type/classification observed, and any backoff
    charged — so a client (or the soak test) can reconstruct exactly
    what the server tried.  ``retry_after_s`` reuses the
    :class:`Overloaded` hint semantics: when the give-up was budget- or
    breaker-driven, it is the earliest time a retry could behave
    differently (0.0 = retrying will not help)."""

    def __init__(self, message: str, attempts: Tuple[dict, ...] = (),
                 retry_after_s: float = 0.0):
        super().__init__(message)
        self.attempts = tuple(attempts)
        self.retry_after_s = retry_after_s

    def _payload_fields(self) -> Dict[str, Any]:
        return {"attempts": [dict(a) for a in self.attempts],
                "retry_after_s": self.retry_after_s}

    @classmethod
    def _rebuild(cls, payload: Dict[str, Any]) -> "QueryFailed":
        return cls(str(payload.get("message", "")),
                   attempts=tuple(dict(a) for a in
                                  payload.get("attempts", ())),
                   retry_after_s=float(payload.get("retry_after_s", 0.0)))


class CircuitOpen(QueryFailed):
    """Fast-fail: this request's plan family tripped its circuit breaker
    and the cooldown has not elapsed — the server refuses to burn device
    time on a family that is failing deterministically.  ``retry_after_s``
    is the remaining cooldown (after it, one half-open trial runs)."""

    def __init__(self, message: str, retry_after_s: float = 0.0):
        super().__init__(message, attempts=(), retry_after_s=retry_after_s)

    def _payload_fields(self) -> Dict[str, Any]:
        return {"retry_after_s": self.retry_after_s}

    @classmethod
    def _rebuild(cls, payload: Dict[str, Any]) -> "CircuitOpen":
        return cls(str(payload.get("message", "")),
                   retry_after_s=float(payload.get("retry_after_s", 0.0)))


class CompactionFailed(ServeError):
    """The background compactor (serve/compaction.py) could not run —
    misconfiguration (a non-versioned graph) or a fold failure surfaced
    to a caller.  Routine fold failures are NOT raised: they roll back,
    count ``compaction.failures``, and retry on the next tick."""


class ReplicationUnsupported(ServeError):
    """A graph that cannot be re-ingested onto another device replica
    (only scan graphs and the empty ambient graph replicate — see
    ``serve/devices.py``).  The server never surfaces this to clients:
    requests against such graphs are pinned to device 0."""


class ShardingUnsupported(ServeError):
    """A graph that cannot be served by a shard group (serve/shards.py):
    only scan-backed graphs partition, and a group manages its OWN
    versioned write lineage — handing it an externally versioned graph
    would split the commit history two ways.  Writes themselves are
    served: the sharded commit protocol splits staged ops per shard and
    commits them atomically at the group's WAL append.  Classified
    FATAL: retrying cannot change it."""


class ShardMemberDown(ServeError):
    """A single-shard-routed query's owning member is quarantined and
    its background rebuild has not finished.  Marked ``caps_transient``
    at construction: the serving tier's retry ladder backs off and
    re-executes — by then the rebuild may have reinstated the member —
    instead of walking the poisoned-plan ladder."""

    def __init__(self, message: str, member: Optional[int] = None):
        super().__init__(message)
        self.caps_transient = True
        if member is not None:
            #: member attribution for the group ladder (serve/shards.py)
            self.caps_shard_member = member

    def _payload_fields(self) -> Dict[str, Any]:
        return {"member": getattr(self, "caps_shard_member", None)}

    @classmethod
    def _rebuild(cls, payload: Dict[str, Any]) -> "ShardMemberDown":
        member = payload.get("member")
        return cls(str(payload.get("message", "")),
                   member=None if member is None else int(member))


class CancellationError(ServeError):
    """Base of the two cooperative-cancel outcomes (deadline, explicit).

    The fused executor re-raises these immediately instead of treating
    them as replay divergence: a query killed by its budget must not be
    transparently re-executed."""

    def __init__(self, message: str, phase: str = "?"):
        super().__init__(message)
        #: pipeline phase at which the cancellation was observed
        #: (queued | parse | plan | execute | materialize)
        self.phase = phase

    def _payload_fields(self) -> Dict[str, Any]:
        return {"phase": self.phase}

    @classmethod
    def _rebuild(cls, payload: Dict[str, Any]) -> "CancellationError":
        return cls(str(payload.get("message", "")),
                   phase=str(payload.get("phase", "?")))


class DeadlineExceeded(CancellationError):
    """The request's deadline expired; ``phase`` attributes the budget."""

    def __init__(self, phase: str, budget_s: Optional[float],
                 elapsed_s: float):
        super().__init__(
            f"deadline exceeded in phase {phase!r} "
            f"(budget {budget_s if budget_s is not None else '?'} s, "
            f"elapsed {elapsed_s:.4f} s)", phase=phase)
        self.budget_s = budget_s
        self.elapsed_s = elapsed_s

    def _payload_fields(self) -> Dict[str, Any]:
        return {"phase": self.phase, "budget_s": self.budget_s,
                "elapsed_s": self.elapsed_s}

    @classmethod
    def _rebuild(cls, payload: Dict[str, Any]) -> "DeadlineExceeded":
        # the message is deterministic from the fields, so rebuilding
        # through the constructor reproduces it byte-for-byte
        budget = payload.get("budget_s")
        return cls(str(payload.get("phase", "?")),
                   None if budget is None else float(budget),
                   float(payload.get("elapsed_s", 0.0)))


class Cancelled(CancellationError):
    """The client cancelled the request (``QueryHandle.cancel()``)."""

    def __init__(self, phase: str = "queued"):
        super().__init__(f"request cancelled in phase {phase!r}",
                         phase=phase)

    @classmethod
    def _rebuild(cls, payload: Dict[str, Any]) -> "Cancelled":
        # message is derived from the phase — reconstruct, don't pass
        return cls(phase=str(payload.get("phase", "queued")))


class WireError(ServeError):
    """A fleet wire-protocol transport failure (serve/wire.py): the
    connection dropped mid-call, a frame was malformed or oversized, or
    the peer closed before replying.  Marked ``caps_transient`` at
    construction — the router's obligation under this error is to
    degrade the backend's ring segment and retry the request on the
    next ring node, exactly like the device ladder retries on a
    different replica."""

    def __init__(self, message: str):
        super().__init__(message)
        self.caps_transient = True


class FleetUnavailable(ServeError):
    """The router exhausted every live ring node for a request (all
    backends dead or overloaded).  ``retry_after_s`` carries the best
    backoff hint observed along the way (the largest ``Overloaded``
    hint, or 0.0 when the failures were connection-level)."""

    def __init__(self, message: str, retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s

    def _payload_fields(self) -> Dict[str, Any]:
        return {"retry_after_s": self.retry_after_s}

    @classmethod
    def _rebuild(cls, payload: Dict[str, Any]) -> "FleetUnavailable":
        return cls(str(payload.get("message", "")),
                   retry_after_s=float(payload.get("retry_after_s", 0.0)))


class WalWriteError(ServeError):
    """A write-ahead-log append (or its fsync) failed BEFORE the commit
    acknowledged (caps_tpu/durability/wal.py).  The commit rolls back
    through the string-pool mark and this error surfaces to the writer —
    a durability failure is NEVER a silent ack.  Marked
    ``caps_transient``: disk pressure and injected fsync faults are
    retryable; the graph itself is untouched."""

    def __init__(self, message: str):
        super().__init__(message)
        self.caps_transient = True


class StaleEpoch(ServeError):
    """An epoch-fenced write frame was refused (caps_tpu/durability):
    the backend no longer holds the write lease, or the frame carries an
    epoch older than the lease's.  This is the split-brain fence — a
    zombie owner (or a router with a stale ownership view) learns who
    actually owns writes from the carried fields and re-routes.
    Classified FATAL on purpose: blind retry against the same backend
    cannot succeed; the caller must re-elect."""

    def __init__(self, message: str, epoch: Optional[int] = None,
                 lease_epoch: Optional[int] = None,
                 owner: Optional[str] = None):
        super().__init__(message)
        #: the epoch the refused frame carried (None = frame had none)
        self.epoch = epoch
        #: the live lease's epoch at refusal time
        self.lease_epoch = lease_epoch
        #: the live lease's owner — where writes actually go now
        self.owner = owner

    def _payload_fields(self) -> Dict[str, Any]:
        return {"epoch": self.epoch, "lease_epoch": self.lease_epoch,
                "owner": self.owner}

    @classmethod
    def _rebuild(cls, payload: Dict[str, Any]) -> "StaleEpoch":
        epoch = payload.get("epoch")
        lease_epoch = payload.get("lease_epoch")
        owner = payload.get("owner")
        return cls(str(payload.get("message", "")),
                   epoch=None if epoch is None else int(epoch),
                   lease_epoch=(None if lease_epoch is None
                                else int(lease_epoch)),
                   owner=None if owner is None else str(owner))


def _error_classes() -> Dict[str, type]:
    """Every ServeError subclass reachable from the base (this module
    defines them all; subclasses registered elsewhere resolve too)."""
    out: Dict[str, type] = {"ServeError": ServeError}
    stack = [ServeError]
    while stack:
        cls = stack.pop()
        for sub in cls.__subclasses__():
            if sub.__name__ not in out:
                out[sub.__name__] = sub
                stack.append(sub)
    return out


def error_from_payload(payload: Dict[str, Any]) -> ServeError:
    """The inverse of :meth:`ServeError.to_payload`: reconstruct the
    exact typed error a remote process raised.  An unknown class name
    (version skew across the fleet) degrades to a :class:`QueryFailed`
    carrying the original class name in its message — never an
    exception from here."""
    if not isinstance(payload, dict):
        return QueryFailed(f"malformed wire error payload: {payload!r}")
    name = payload.get("error")
    cls = _error_classes().get(name) if isinstance(name, str) else None
    if cls is None:
        return QueryFailed(f"unrecognized wire error {name!r}: "
                           f"{payload.get('message', '')}")
    return cls._rebuild(payload)

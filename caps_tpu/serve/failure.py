"""Failure taxonomy for the serving tier.

SURVEY.md §5.3: the reference engine inherited all failure handling from
Spark (lineage re-execution, executor blacklisting); single-controller
JAX has none, so the serving tier must decide *on its own* what a raised
exception means for the request and for the shared engine state.  One
function owns that decision:

    classify(exc) -> TRANSIENT | POISONED_PLAN | FATAL

* ``TRANSIENT`` — the execution environment hiccuped; the SAME
  execution path is expected to succeed on a retry.  Device-runtime
  errors with retryable status words (``RESOURCE_EXHAUSTED`` from an
  HBM allocator under pressure, ``UNAVAILABLE``/``ABORTED`` from a
  flapping transport), connection/timeout errors from remote-device
  tunnels, and anything explicitly marked ``caps_transient = True``
  (the fault-injection harness and backend code use the marker).
  The worker retries these with exponential backoff
  (:mod:`caps_tpu.serve.retry`), charging the request's deadline.

* ``FATAL`` — the *request* is wrong or already resolved: syntax /
  semantic errors, missing parameters, cooperative cancellation and
  deadline expiry, and every :class:`~caps_tpu.serve.errors.ServeError`.
  Retrying cannot change the outcome; the error completes the handle
  as-is.

* ``POISONED_PLAN`` — everything else.  The deliberate default: an
  unexplained execution error while serving from shared cached state
  (a cached operator tree, a fused size memo) must be treated as
  possible corruption of that state, because a poisoned entry fails
  every future hit on its key.  The worker quarantines the plan-cache
  entry, drops the fused memos, and walks the degraded ladder (fresh
  fused re-record → per-operator unfused execution); a query that is
  simply broken deterministically costs two extra executions once and
  then trips its family's circuit breaker.

The classifier is import-light on purpose: it never imports jax —
device-runtime exceptions are recognized by MRO class *name*
(``XlaRuntimeError`` moved modules across jaxlib versions) plus status
words in the message.
"""
from __future__ import annotations

from caps_tpu.serve.errors import CancellationError, ServeError

#: Classification outcomes (strings, not an Enum: they flow straight
#: into attempt-history dicts, metrics labels, and trace events).
TRANSIENT = "transient"
POISONED_PLAN = "poisoned_plan"
FATAL = "fatal"

#: Device-runtime exception class names treated as device errors
#: regardless of which module currently defines them.
_DEVICE_ERROR_NAMES = frozenset({"XlaRuntimeError", "JaxRuntimeError"})

#: Status words (gRPC / XLA canonical codes) that mark a device error
#: as retryable.  ``INTERNAL`` is included: on TPU transports it is the
#: catch-all for preempted/restarted device servers.
_RETRYABLE_STATUS = ("RESOURCE_EXHAUSTED", "UNAVAILABLE", "ABORTED",
                     "CANCELLED", "INTERNAL", "DEADLINE_EXCEEDED")

#: Frontend / user-error exception class names (by name: the frontend
#: must stay importable without pulling the serving tier and vice
#: versa).
_FATAL_NAMES = frozenset({"CypherSyntaxError", "SemanticError",
                          "HeaderError", "NondeterministicResultError"})


def is_device_error(exc: BaseException) -> bool:
    """True when ``exc`` is (or wraps, via its MRO) an XLA runtime
    error — recognized by class name so no jax import is needed."""
    return any(c.__name__ in _DEVICE_ERROR_NAMES
               for c in type(exc).__mro__)


def device_fault(exc: BaseException) -> bool:
    """True when the error indicts the DEVICE rather than the query or
    the cached plan — the only failures the per-device health ladder
    (serve/devices.py) counts.  An explicit ``caps_device_fault`` marker
    wins (the device-scoped fault injectors stamp it); otherwise
    device-runtime errors by MRO name and connection failures (a dead
    device tunnel) qualify.  A user's bad query must never take a
    device down."""
    marker = getattr(exc, "caps_device_fault", None)
    if marker is not None:
        return bool(marker)
    return is_device_error(exc) or isinstance(exc, ConnectionError)


def attribute_device(exc: BaseException, device_index: int) -> None:
    """Stamp the replica index an execution error was observed on —
    first-writer-wins, like ``caps_failed_op`` (relational/ops.py): the
    device CLOSEST to the failure keeps the attribution through retries
    on other devices."""
    try:
        if getattr(exc, "caps_device_index", None) is None:
            exc.caps_device_index = device_index
    except Exception:  # pragma: no cover — immutable exception types
        pass


def device_of(exc: BaseException):
    """The replica index stamped by :func:`attribute_device` (None when
    the error never crossed a device execution bracket)."""
    return getattr(exc, "caps_device_index", None)


def quarantine_plan_state(session, graph, query, params,
                          exec_lock=None) -> None:
    """Evict one family's shared cached state on ``session``: the
    plan-cache entry anchored by (graph, query, params) and, on
    backends with a fused executor, its size memos.  The ONE
    poisoned-plan eviction sequence — the server's device path and the
    shard-group path both call here, so containment semantics cannot
    drift apart.  ``exec_lock`` (the owning execution stream's lock) is
    held around the fused eviction: memo maps must not shrink under an
    in-flight fused run.  Never raises — containment must not fail."""
    import contextlib
    try:
        key_fn = getattr(session, "_plan_cache_key", None)
        if key_fn is not None:
            key = key_fn(graph, query, params)
            if key is not None:
                session.plan_cache.quarantine(key)
    except Exception:  # pragma: no cover — containment must not fail
        pass
    fused = getattr(session, "fused", None)
    if fused is not None:
        try:
            with (exec_lock if exec_lock is not None
                  else contextlib.nullcontext()):
                fused.forget(graph, query)
        except Exception:  # pragma: no cover — containment must not fail
            pass


def classify(exc: BaseException) -> str:
    """Map one raised exception to its containment treatment."""
    # explicit marker wins: the fault harness and backend code stamp
    # exceptions they KNOW are retryable / know are not
    marker = getattr(exc, "caps_transient", None)
    if marker is True:
        return TRANSIENT
    if marker is False:
        return FATAL
    # the serving tier's own errors are never retried by the serving
    # tier (cancellation, shedding, give-ups — all terminal here)
    if isinstance(exc, (CancellationError, ServeError)):
        return FATAL
    if is_device_error(exc):
        msg = str(exc)
        if any(s in msg for s in _RETRYABLE_STATUS):
            return TRANSIENT
        # device error without a retryable status (e.g. INVALID_ARGUMENT
        # out of a stale compiled program): suspect the cached state
        return POISONED_PLAN
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return TRANSIENT
    if isinstance(exc, (SyntaxError, KeyError, NotImplementedError)) \
            or type(exc).__name__ in _FATAL_NAMES:
        # user error (bad query text / missing $param / unsupported
        # feature): deterministic, never the cache's fault
        return FATAL
    return POISONED_PLAN

"""Fleet backends: one ``QueryServer`` per process behind a socket.

A :class:`FleetBackend` wraps one server (its own session, graph, plan
cache, warm-path store) in a TCP listener speaking the frame protocol
of ``serve/wire.py``.  The router (serve/router.py) holds a
:class:`~caps_tpu.serve.wire.WireClient` per backend and routes by
consistent hash — compiled state never migrates between processes
(docs/tpu.md), so scale-out ships *queries to the process whose caches
are hot* and *snapshots to the processes whose graphs are stale*, never
compiled artifacts.

Two deployment shapes share this class:

* **in-process** (tests, docs): ``FleetBackend(spec)`` starts the
  server and listener on threads in the caller's process — real
  sockets, real wire frames, deterministic and fast;
* **multi-process** (bench, production shape): ``spawn_backend(spec)``
  launches ``python -m caps_tpu.serve.fleet '<spec json>'`` — each
  child owns a full interpreter (its own GIL), prints
  ``CAPS_FLEET_PORT <port>`` on stdout, and serves until killed.

Both build their graph from :class:`BackendSpec.graph` — a declarative
spec (not a pickled object), so every process reconstructs an
IDENTICAL base graph from the same JSON and snapshot shipping only has
to move deltas (``relational/updates.py delta_state_to_payload``).
"""
from __future__ import annotations

import dataclasses
import json
import hashlib
import os
import random
import socket
import sys
import threading
from typing import Any, Dict, Optional, Tuple

from caps_tpu.durability.lease import ROUTER_LEASE_NAME, LeaseStore
from caps_tpu.durability.wal import (CommitLog, compose_delta_payloads,
                                     empty_payload, scan_durable_dir)
from caps_tpu.obs import clock
from caps_tpu.obs.lockgraph import make_lock
from caps_tpu.serve import wire
from caps_tpu.serve.errors import (QueryFailed, ReplicationUnsupported,
                                   ServerClosed, StaleEpoch, WalWriteError)
from caps_tpu.serve.server import QueryServer, ServerConfig
from caps_tpu.serve.warmup import WarmupConfig

_UNSET = object()


@dataclasses.dataclass(frozen=True)
class BackendSpec:
    """Declarative description of one fleet backend — everything a
    fresh process needs to reconstruct the same serving state."""

    #: ring identity (stable across restarts — a rejoining process with
    #: the same name reclaims the same ring segment)
    name: str
    #: session backend ("local" oracle or "tpu"); bench uses "local"
    #: for child processes so scale-out is not dominated by per-process
    #: jax compilation
    backend: str = "local"
    #: graph spec: ``{"kind": "script", "create": "..."}`` (a CREATE
    #: statement through testing/factory), ``{"kind": "foaf",
    #: "n_people": N, "n_edges": M, "seed": S}`` (deterministic social
    #: graph — same seed → byte-identical base in every process), or
    #: None for the empty ambient graph
    graph: Optional[Dict[str, Any]] = None
    #: wrap the graph in a VersionedGraph — required for the write
    #: owner and every peer that pulls snapshots
    versioned: bool = False
    #: shared on-disk PlanStore path: a rejoining process warms from it
    #: BEFORE taking traffic, and persists back on shutdown
    plan_store_path: Optional[str] = None
    #: background (True) vs inline (False) warmup; rejoin uses inline
    #: so the port only opens once the hot set is compiled
    warm_background: bool = False
    workers: int = 2
    max_queue: int = 256
    default_deadline_s: Optional[float] = None
    #: simulated per-query device dwell (seconds, via ``obs.clock``):
    #: the CPU-smoke stand-in for a TPU-attached backend, where the
    #: process WAITS on its device for most of a query's life.  Fleet
    #: scale-out buys parallel devices, not parallel host CPUs — with a
    #: dwell configured, QPS scaling across processes measures exactly
    #: that serving-path parallelism, deterministically, even on a
    #: single-core CI host.  0.0 (default) = serve at real speed.
    service_dwell_s: float = 0.0
    #: snapshot-keyed result-cache byte budget (relational/
    #: result_cache.py); None = serve every read through the device.
    #: The hash-ring's (graph, plan-family) affinity already routes a
    #: hot family to one process, so its entries stay process-resident.
    result_cache_budget: Optional[int] = None
    #: shared durable directory (the store the PlanStore already lives
    #: in): this backend's WAL goes to ``<durable_dir>/wal-<name>/`` and
    #: the fleet's write lease to ``<durable_dir>/lease.json``.  None =
    #: memory-only serving (the pre-durability behavior).
    durable_dir: Optional[str] = None
    #: WAL fsync policy: "always" | "rotate" | "never"
    #: (caps_tpu/durability/wal.py)
    wal_fsync: str = "always"
    #: write-lease TTL: how long after the owner's last renewal a peer
    #: may steal the lease (failover detection horizon)
    lease_ttl_s: float = 5.0
    host: str = "127.0.0.1"
    #: 0 = ephemeral (the listener reports the bound port)
    port: int = 0

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "BackendSpec":
        fields = {f.name for f in dataclasses.fields(cls)}
        raw = json.loads(text)
        return cls(**{k: v for k, v in raw.items() if k in fields})


def foaf_create_script(n_people: int, n_edges: int, seed: int) -> str:
    """Deterministic friend-of-a-friend CREATE statement.  Pure
    function of its arguments (seeded Mersenne Twister — stable across
    processes and Python builds), so every backend that parses it gets
    an identical base graph."""
    rng = random.Random(seed)
    parts = [f"(p{i}:Person {{name: 'p{i}', age: {20 + (i * 7) % 50}}})"
             for i in range(n_people)]
    seen = set()
    for _ in range(n_edges):
        a = rng.randrange(n_people)
        b = rng.randrange(n_people)
        if a == b or (a, b) in seen:
            continue
        seen.add((a, b))
        parts.append(f"(p{a})-[:KNOWS {{w: {rng.randrange(100)}}}]->(p{b})")
    return "CREATE " + ",\n  ".join(parts)


def build_graph_from_spec(session, gspec: Optional[Dict[str, Any]],
                          versioned: bool):
    """Construct the spec'd graph on ``session``.  Returns None for an
    absent spec (the server then serves the ambient empty graph)."""
    from caps_tpu.testing.factory import create_graph
    if gspec is None:
        base = None
    else:
        kind = gspec.get("kind", "script")
        if kind == "script":
            create = gspec.get("create")
            if not create:
                raise QueryFailed(
                    "graph spec kind 'script' requires a non-empty "
                    "'create' statement")
            base = create_graph(session, create, gspec.get("parameters"))
        elif kind == "foaf":
            base = create_graph(session, foaf_create_script(
                int(gspec.get("n_people", 64)),
                int(gspec.get("n_edges", 256)),
                int(gspec.get("seed", 0))))
        else:
            raise QueryFailed(f"unknown graph spec kind {kind!r}")
    if versioned:
        from caps_tpu.relational.updates import versioned as make_versioned
        return make_versioned(session, base)
    return base


def rows_digest(rows) -> str:
    """Order-insensitive content digest of materialized rows — the
    cross-process read-your-writes check compares THIS, so two
    backends agree exactly when their visible graph state agrees."""
    canon = sorted(json.dumps(r, sort_keys=True, default=str)
                   for r in rows)
    return hashlib.sha256("\n".join(canon).encode("utf-8")).hexdigest()


class FleetBackend:
    """One serving process: a QueryServer behind a wire listener."""

    def __init__(self, spec: BackendSpec, session=None, start: bool = True):
        self.spec = spec
        if session is None:
            from caps_tpu.testing.sessions import make_backend_session
            session = make_backend_session(spec.backend)
        self.session = session
        self.graph = build_graph_from_spec(session, spec.graph,
                                           spec.versioned)
        warmup = None
        if spec.plan_store_path is not None:
            warmup = WarmupConfig(store_path=spec.plan_store_path,
                                  background=spec.warm_background,
                                  save_on_shutdown=True)
        rescache = None
        if spec.result_cache_budget is not None:
            from caps_tpu.relational.result_cache import ResultCacheConfig
            rescache = ResultCacheConfig(
                budget_bytes=int(spec.result_cache_budget))
        self.server = QueryServer(
            session, graph=self.graph,
            config=ServerConfig(workers=spec.workers,
                                max_queue=spec.max_queue,
                                default_deadline_s=spec.default_deadline_s,
                                warmup=warmup,
                                result_cache=rescache))
        self._registry = session.metrics_registry
        #: durability (caps_tpu/durability): WAL + lease, or None when
        #: the spec has no durable_dir / the graph is not versioned
        self.wal: Optional[CommitLog] = None
        self.lease: Optional[LeaseStore] = None
        self.router_lease: Optional[LeaseStore] = None
        #: the lease epoch this backend last wrote under (stamped on
        #: write acks so routers can fence their own staleness)
        self.write_epoch: Optional[int] = None
        self._base_overlay: Optional[Dict[str, Any]] = None
        if (spec.durable_dir is not None
                and getattr(self.graph, "graph_is_versioned", False)):
            self._init_durability()
        self._shutting_down = threading.Event()
        self._conn_threads = []
        self._conns = []
        self._lock = make_lock("fleet.FleetBackend._lock")
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        if start:
            self.start()

    # -- durability ----------------------------------------------------

    def _init_durability(self) -> None:
        """Open the WAL and lease on the shared durable store, then
        CRASH-RECOVER before serving: replay this backend's own log
        over the spec'd base (entries are cumulative, so the single
        highest intact entry IS the recovered state) and hook the
        commit path for append-before-acknowledge."""
        from caps_tpu.relational.updates import delta_state_from_payload
        spec = self.spec
        self.wal = CommitLog(
            os.path.join(spec.durable_dir, f"wal-{spec.name}"),
            fsync=spec.wal_fsync, registry=self._registry,
            event_log=getattr(self.session, "event_log", None))
        self.lease = LeaseStore(spec.durable_dir, ttl_s=spec.lease_ttl_s,
                                registry=self._registry)
        #: the ROUTER tier's lease (serve/ha.py) — read-only here: the
        #: backend fences write-coordination frames from deposed zombie
        #: routers against it, exactly like zombie owners
        self.router_lease = LeaseStore(
            spec.durable_dir, ttl_s=spec.lease_ttl_s,
            lease_name=ROUTER_LEASE_NAME, registry=self._registry)
        self._base_overlay = empty_payload()
        rec = self.wal.recover()
        if rec.version > 0:
            self.graph.install_state(
                delta_state_from_payload(rec.state), rec.version)
        self.graph.pre_publish = self._wal_append
        self.graph.on_compacted = self._wal_checkpoint

    def _cumulative_payload(self, snap) -> Dict[str, Any]:
        """``snap``'s state as a payload cumulative over the SPEC'D
        base: compaction folds the overlay into a new base, so states
        after a fold are composed back over what was folded away —
        recovery always replays onto a freshly spec-built graph."""
        from caps_tpu.relational.updates import delta_state_to_payload
        return compose_delta_payloads(self._base_overlay,
                                      delta_state_to_payload(snap.state))

    def _wal_append(self, new_snap) -> None:
        """``pre_publish`` hook: the append-before-acknowledge point.
        Runs under the commit lock before the snapshot swap; a failed
        append raises WalWriteError and the commit rolls back — the
        writer never sees an ack for a frame that did not land."""
        self.wal.append(new_snap.snapshot_version,
                        self._cumulative_payload(new_snap),
                        epoch=self.write_epoch)

    def _wal_checkpoint(self, folded_snap, new_snap) -> None:
        """``on_compacted`` hook: fold the compacted-away overlay into
        the base composition, persist it as the checkpoint, truncate
        covered segments.  A checkpoint write failure is deferred, not
        fatal: entries stay cumulative over the spec'd base, so recovery
        is exact from the un-truncated log alone."""
        from caps_tpu.relational.updates import delta_state_to_payload
        self._base_overlay = compose_delta_payloads(
            self._base_overlay, delta_state_to_payload(folded_snap.state))
        try:
            self.wal.checkpoint(new_snap.snapshot_version,
                                self._base_overlay, epoch=self.write_epoch)
        except WalWriteError:
            self._registry.counter("wal.checkpoint_failures").inc()

    def _fence_router(self, frame_router_epoch: Optional[int]) -> None:
        """The router-tier fence (serve/ha.py): a write-coordination
        frame stamped with a ROUTER epoch older than the published
        router lease's comes from a deposed zombie active router —
        refuse it exactly like a zombie owner's.  Frames without a
        router epoch pass (single-router deployments carry none), and
        TTL expiry is irrelevant here: only a SUCCESSOR bumping the
        epoch deposes the stamp's holder."""
        if frame_router_epoch is None or self.router_lease is None:
            return
        lease = self.router_lease.read()
        if lease is not None and int(frame_router_epoch) != lease["epoch"]:
            self._registry.counter("wal.fenced_writes").inc()
            raise StaleEpoch(
                f"stale ROUTER epoch fenced at backend "
                f"{self.spec.name!r} — a newer active router holds the "
                f"router lease", epoch=int(frame_router_epoch),
                lease_epoch=lease["epoch"], owner=lease["owner"])

    def _fence_write(self, frame_epoch: Optional[int]) -> None:
        """The split-brain fence, checked before EVERY durable write:
        (a) this backend must hold the live lease (a deposed zombie
        owner reads the shared lease file and learns it does not), and
        (b) the frame's epoch, when carried, must match the lease's (a
        router with a stale ownership view is told who owns writes
        now).  An unheld lease is claimed on first write — initial
        ownership needs no ceremony."""
        lease = self.lease.read()
        if lease is None or self.lease.expired(lease):
            epoch = self.lease.acquire(self.spec.name)
            if epoch is not None:
                self.write_epoch = epoch
                lease = self.lease.read()
            else:
                lease = self.lease.read()
        if lease is None or lease["owner"] != self.spec.name:
            self._registry.counter("wal.fenced_writes").inc()
            raise StaleEpoch(
                f"backend {self.spec.name!r} does not hold the write "
                f"lease", epoch=frame_epoch,
                lease_epoch=None if lease is None else lease["epoch"],
                owner=None if lease is None else lease["owner"])
        self.write_epoch = lease["epoch"]
        if frame_epoch is not None and int(frame_epoch) != lease["epoch"]:
            self._registry.counter("wal.fenced_writes").inc()
            raise StaleEpoch(
                f"stale-epoch write frame fenced at backend "
                f"{self.spec.name!r}", epoch=int(frame_epoch),
                lease_epoch=lease["epoch"], owner=lease["owner"])

    # -- listener ------------------------------------------------------

    def start(self) -> int:
        """Bind + start accepting (idempotent).  Returns the bound
        port.  When the spec asks for inline warmup the server
        constructor already blocked on it — the port only opens warm."""
        with self._lock:
            if self._listener is not None:
                return self.port
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.bind((self.spec.host, self.spec.port))
            listener.listen(64)
            self._listener = listener
            self.port = listener.getsockname()[1]
            self._registry.gauge("fleet.backend_up").set(1.0)
            t = threading.Thread(target=self._accept_loop,
                                 name=f"caps-fleet-{self.spec.name}",
                                 daemon=True)
            self._accept_thread = t
            t.start()
            return self.port

    def _accept_loop(self) -> None:
        while not self._shutting_down.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed — shutdown
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            self._registry.counter("fleet.connections").inc()
            t = threading.Thread(
                target=wire.serve_connection,
                args=(conn, self.handle, self._shutting_down),
                name=f"caps-fleet-conn-{self.spec.name}", daemon=True)
            t.start()
            self._conn_threads.append(t)

    def shutdown(self, drain: bool = True) -> None:
        """Stop the listener, then the server (persisting warm state
        when a store is configured).  Safe to call twice."""
        self._shutting_down.set()
        with self._lock:
            listener, self._listener = self._listener, None
        if listener is not None:
            # shutdown() before close(): close() alone does NOT wake a
            # thread blocked in accept() on the same socket
            for fn in (lambda: listener.shutdown(socket.SHUT_RDWR),
                       listener.close):
                try:
                    fn()
                except OSError:  # pragma: no cover — teardown must not raise
                    pass
        # sever open connections like a dying process would: blocked
        # peers observe EOF/reset (a WireError), not a hung socket
        for conn in self._conns:
            for fn in (lambda c=conn: c.shutdown(socket.SHUT_RDWR),
                       conn.close):
                try:
                    fn()
                except OSError:  # pragma: no cover — teardown must not raise
                    pass
        accept_thread = self._accept_thread
        if accept_thread is not None and \
                accept_thread is not threading.current_thread():
            accept_thread.join(timeout=5.0)
        for t in self._conn_threads:
            if t is not threading.current_thread():
                t.join(timeout=5.0)
        self._registry.gauge("fleet.backend_up").set(0.0)
        self.server.shutdown(drain=drain)

    # -- op dispatch ---------------------------------------------------

    def handle(self, msg: Dict[str, Any]) -> Any:
        """One request → one reply payload.  ServeErrors propagate (the
        wire layer serializes them typed); anything else becomes a
        QueryFailed on the wire."""
        op = msg.get("op")
        fn = getattr(self, f"_op_{op}", None)
        if fn is None:
            raise QueryFailed(f"unknown fleet op {op!r}")
        self._registry.counter(f"fleet.ops.{op}").inc()
        return fn(msg)

    def _op_ping(self, msg) -> Dict[str, Any]:
        return {"name": self.spec.name, "pid": os.getpid(),
                "health": self.server.health(),
                "snapshot_version": self._snapshot_version()}

    def _snapshot_version(self) -> Optional[int]:
        if getattr(self.graph, "graph_is_versioned", False):
            return self.graph.current().snapshot_version
        return None

    def _submit(self, msg) -> Tuple[list, Dict[str, Any]]:
        deadline = msg.get("deadline_s", _UNSET)
        kwargs: Dict[str, Any] = {}
        if deadline is not _UNSET:
            kwargs["deadline_s"] = deadline
        if msg.get("priority") is not None:
            kwargs["priority"] = int(msg["priority"])
        handle = self.server.submit(msg.get("query", ""),
                                    msg.get("params") or {}, **kwargs)
        rows = handle.rows()
        return rows, handle.info

    def _op_query(self, msg) -> Dict[str, Any]:
        if self.spec.service_dwell_s > 0.0:
            clock.sleep(self.spec.service_dwell_s)
        rows, info = self._submit(msg)
        out = {"rows": rows,
               "ledger": info.get("ledger"),
               "snapshot_version": info.get("snapshot_version"),
               "queue_depth": self.server.admission.depth()}
        if msg.get("digest"):
            out["digest"] = rows_digest(rows)
        return out

    def _op_write(self, msg) -> Dict[str, Any]:
        """An update query against the owned versioned graph; the reply
        carries the post-commit version so the router can measure
        snapshot lag per peer.  Durable backends fence the frame's
        epoch first (StaleEpoch — never execute a zombie's write) and
        acknowledge only after the WAL append landed (the pre_publish
        hook runs inside the commit)."""
        if not getattr(self.graph, "graph_is_versioned", False):
            raise ReplicationUnsupported(
                f"backend {self.spec.name!r} serves a non-versioned "
                f"graph; writes need a versioned owner")
        if self.lease is not None:
            self._fence_router(msg.get("router_epoch"))
            self._fence_write(msg.get("epoch"))
        rows, info = self._submit(msg)
        out = {"rows": rows,
               "version": self.graph.current().snapshot_version,
               "queue_depth": self.server.admission.depth()}
        if self.lease is not None:
            out["epoch"] = self.write_epoch
            self.lease.renew(self.spec.name)
        return out

    def _op_acquire_lease(self, msg) -> Dict[str, Any]:
        """Failover: make THIS backend the write owner.  First replay
        every backend's WAL under the shared store (the dead owner's
        acked-but-unshipped writes live only in ITS log — zero
        acknowledged-write loss), then claim the epoch-fenced lease,
        polling up to ``wait_s`` for the dead owner's TTL to lapse.
        Non-durable backends answer ``durable: False`` so the router
        can keep the legacy read-only-until-rejoin behavior."""
        if self.lease is None:
            return {"durable": False, "epoch": None,
                    "version": self._snapshot_version()}
        from caps_tpu.relational.updates import delta_state_from_payload
        best = scan_durable_dir(self.spec.durable_dir,
                                registry=self._registry)
        if (best is not None
                and best.version > (self._snapshot_version() or 0)):
            self.graph.install_state(
                delta_state_from_payload(best.state), best.version)
            self._registry.counter("wal.failover_replays").inc()
        deadline = clock.now() + float(msg.get("wait_s") or 0.0)
        epoch = self.lease.acquire(self.spec.name)
        while epoch is None and clock.now() < deadline:
            clock.sleep(min(0.05, max(self.spec.lease_ttl_s / 4.0, 0.005)))
            epoch = self.lease.acquire(self.spec.name)
        if epoch is not None:
            self.write_epoch = epoch
        return {"durable": True, "epoch": epoch,
                "version": self._snapshot_version()}

    def _op_export_delta(self, msg) -> Dict[str, Any]:
        """Replication source: the current snapshot's full delta state.
        Deltas are cumulative over the shared base (the spec'd graph),
        so one pull brings ANY stale peer exactly current — no
        per-version chain to replay."""
        from caps_tpu.relational.updates import delta_state_to_payload
        if not getattr(self.graph, "graph_is_versioned", False):
            raise ReplicationUnsupported(
                f"backend {self.spec.name!r} serves a non-versioned "
                f"graph; nothing to export")
        snap = self.graph.current()
        return {"version": snap.snapshot_version,
                "state": delta_state_to_payload(snap.state)}

    def _op_sync_from(self, msg) -> Dict[str, Any]:
        """Replication sink: pull the owner's delta and flip the local
        version atomically.  Monotonic — a concurrent newer local
        version wins (install_state refuses to go backwards)."""
        from caps_tpu.relational.updates import delta_state_from_payload
        if not getattr(self.graph, "graph_is_versioned", False):
            raise ReplicationUnsupported(
                f"backend {self.spec.name!r} serves a non-versioned "
                f"graph; cannot install snapshots")
        with wire.WireClient(str(msg["host"]), int(msg["port"]),
                             timeout_s=30.0) as owner:
            if self.wal is not None:
                # WAL-tail rejoin: this backend's own recovered log may
                # already be current (it held every acked write when it
                # died) — compare versions before paying for a full
                # cumulative-delta pull
                owner_version = owner.call("ping").get("snapshot_version")
                local_version = self.graph.current().snapshot_version
                if (owner_version is not None
                        and local_version >= int(owner_version)):
                    self._registry.counter("wal.catchups").inc()
                    return {"version": local_version, "wal_catchup": True}
            delta = owner.call("export_delta")
        state = delta_state_from_payload(delta["state"])

        def _publish(new_snap) -> None:
            # runs under the commit lock BEFORE the reference swap
            # (relational/updates.py install_state): superseded result-
            # cache entries retire and the version gauge updates
            # happens-before any reader can be admitted at the new
            # version — the rejoin fencing fix (no read is ever served
            # a version the gauges don't yet report)
            self._registry.counter("fleet.snapshots_installed").inc()
            self._registry.gauge("fleet.snapshot_version").set(
                float(new_snap.snapshot_version))
            if self.wal is not None:
                # best-effort peer durability: shipped snapshots land in
                # THIS backend's log too, so "longest replayed log" at
                # election time favors the most caught-up peer.  A peer
                # disk hiccup must never fail replication — the owner's
                # log still holds the entry.
                try:
                    self.wal.append(new_snap.snapshot_version,
                                    self._cumulative_payload(new_snap))
                except WalWriteError:
                    self._registry.counter(
                        "wal.peer_append_failures").inc()

        snap = self.graph.install_state(state, int(delta["version"]),
                                        on_install=_publish)
        return {"version": snap.snapshot_version}

    def _op_stats(self, msg) -> Dict[str, Any]:
        return self.server.stats()

    def _op_health(self, msg) -> Dict[str, Any]:
        return {"health": self.server.health()}

    def _op_health_report(self, msg) -> Dict[str, Any]:
        return self.server.health_report()

    def _op_metrics_snapshot(self, msg) -> Dict[str, Any]:
        return self._registry.snapshot()

    def _op_metrics_text(self, msg) -> str:
        return self.server.metrics_text()

    def _op_telemetry(self, msg) -> Dict[str, Any]:
        return self.server.telemetry.summary()

    def _op_warmup_report(self, msg) -> Dict[str, Any]:
        return self.server.warmup_report(msg.get("families"))

    def _op_warmup_wait(self, msg) -> Dict[str, Any]:
        warmer = self.server.warmer
        if warmer is None:
            return {"state": "none", "done": True}
        done = warmer.wait(msg.get("timeout"))
        return {"state": warmer.report().get("state", "?"), "done": done}

    def _op_shutdown(self, msg) -> Dict[str, Any]:
        # reply first, then tear down from another thread — the client
        # gets its ack before the socket dies
        threading.Thread(target=self.shutdown,
                         kwargs={"drain": bool(msg.get("drain", True))},
                         name=f"caps-fleet-shutdown-{self.spec.name}",
                         daemon=True).start()
        return {"closing": True}


# -- process entry point ----------------------------------------------


def backend_main(spec_json: str) -> None:  # pragma: no cover — child
    """Entry point of a spawned backend process: build the backend,
    report the bound port on stdout, serve until killed."""
    backend = FleetBackend(BackendSpec.from_json(spec_json))
    print(f"CAPS_FLEET_PORT {backend.port}", flush=True)
    try:
        backend._shutting_down.wait()
    except KeyboardInterrupt:
        pass
    backend.shutdown(drain=False)


def spawn_backend(spec: BackendSpec, env: Optional[Dict[str, str]] = None):
    """Launch ``python -m caps_tpu.serve.fleet`` with ``spec`` and wait
    for its port line.  Returns ``(process, port)``; the caller owns
    the process (terminate/kill/wait)."""
    import subprocess
    child_env = dict(os.environ)
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    # the child must import caps_tpu regardless of the caller's cwd:
    # put the package's parent dir on its PYTHONPATH explicitly
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    parent = os.path.dirname(pkg_root)
    existing = child_env.get("PYTHONPATH")
    child_env["PYTHONPATH"] = (
        parent if not existing else parent + os.pathsep + existing)
    if env:
        child_env.update(env)
    proc = subprocess.Popen(
        [sys.executable, "-m", "caps_tpu.serve.fleet", spec.to_json()],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=child_env, text=True)
    line = proc.stdout.readline()
    while line and not line.startswith("CAPS_FLEET_PORT"):
        line = proc.stdout.readline()
    if not line:
        proc.kill()
        raise QueryFailed(
            f"fleet backend {spec.name!r} exited before reporting a port")
    return proc, int(line.split()[1])


if __name__ == "__main__":  # pragma: no cover — child process
    backend_main(sys.argv[1])

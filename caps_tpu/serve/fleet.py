"""Fleet backends: one ``QueryServer`` per process behind a socket.

A :class:`FleetBackend` wraps one server (its own session, graph, plan
cache, warm-path store) in a TCP listener speaking the frame protocol
of ``serve/wire.py``.  The router (serve/router.py) holds a
:class:`~caps_tpu.serve.wire.WireClient` per backend and routes by
consistent hash — compiled state never migrates between processes
(docs/tpu.md), so scale-out ships *queries to the process whose caches
are hot* and *snapshots to the processes whose graphs are stale*, never
compiled artifacts.

Two deployment shapes share this class:

* **in-process** (tests, docs): ``FleetBackend(spec)`` starts the
  server and listener on threads in the caller's process — real
  sockets, real wire frames, deterministic and fast;
* **multi-process** (bench, production shape): ``spawn_backend(spec)``
  launches ``python -m caps_tpu.serve.fleet '<spec json>'`` — each
  child owns a full interpreter (its own GIL), prints
  ``CAPS_FLEET_PORT <port>`` on stdout, and serves until killed.

Both build their graph from :class:`BackendSpec.graph` — a declarative
spec (not a pickled object), so every process reconstructs an
IDENTICAL base graph from the same JSON and snapshot shipping only has
to move deltas (``relational/updates.py delta_state_to_payload``).
"""
from __future__ import annotations

import dataclasses
import json
import hashlib
import os
import random
import socket
import sys
import threading
from typing import Any, Dict, Optional, Tuple

from caps_tpu.obs import clock
from caps_tpu.obs.lockgraph import make_lock
from caps_tpu.serve import wire
from caps_tpu.serve.errors import (QueryFailed, ReplicationUnsupported,
                                   ServerClosed)
from caps_tpu.serve.server import QueryServer, ServerConfig
from caps_tpu.serve.warmup import WarmupConfig

_UNSET = object()


@dataclasses.dataclass(frozen=True)
class BackendSpec:
    """Declarative description of one fleet backend — everything a
    fresh process needs to reconstruct the same serving state."""

    #: ring identity (stable across restarts — a rejoining process with
    #: the same name reclaims the same ring segment)
    name: str
    #: session backend ("local" oracle or "tpu"); bench uses "local"
    #: for child processes so scale-out is not dominated by per-process
    #: jax compilation
    backend: str = "local"
    #: graph spec: ``{"kind": "script", "create": "..."}`` (a CREATE
    #: statement through testing/factory), ``{"kind": "foaf",
    #: "n_people": N, "n_edges": M, "seed": S}`` (deterministic social
    #: graph — same seed → byte-identical base in every process), or
    #: None for the empty ambient graph
    graph: Optional[Dict[str, Any]] = None
    #: wrap the graph in a VersionedGraph — required for the write
    #: owner and every peer that pulls snapshots
    versioned: bool = False
    #: shared on-disk PlanStore path: a rejoining process warms from it
    #: BEFORE taking traffic, and persists back on shutdown
    plan_store_path: Optional[str] = None
    #: background (True) vs inline (False) warmup; rejoin uses inline
    #: so the port only opens once the hot set is compiled
    warm_background: bool = False
    workers: int = 2
    max_queue: int = 256
    default_deadline_s: Optional[float] = None
    #: simulated per-query device dwell (seconds, via ``obs.clock``):
    #: the CPU-smoke stand-in for a TPU-attached backend, where the
    #: process WAITS on its device for most of a query's life.  Fleet
    #: scale-out buys parallel devices, not parallel host CPUs — with a
    #: dwell configured, QPS scaling across processes measures exactly
    #: that serving-path parallelism, deterministically, even on a
    #: single-core CI host.  0.0 (default) = serve at real speed.
    service_dwell_s: float = 0.0
    #: snapshot-keyed result-cache byte budget (relational/
    #: result_cache.py); None = serve every read through the device.
    #: The hash-ring's (graph, plan-family) affinity already routes a
    #: hot family to one process, so its entries stay process-resident.
    result_cache_budget: Optional[int] = None
    host: str = "127.0.0.1"
    #: 0 = ephemeral (the listener reports the bound port)
    port: int = 0

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "BackendSpec":
        fields = {f.name for f in dataclasses.fields(cls)}
        raw = json.loads(text)
        return cls(**{k: v for k, v in raw.items() if k in fields})


def foaf_create_script(n_people: int, n_edges: int, seed: int) -> str:
    """Deterministic friend-of-a-friend CREATE statement.  Pure
    function of its arguments (seeded Mersenne Twister — stable across
    processes and Python builds), so every backend that parses it gets
    an identical base graph."""
    rng = random.Random(seed)
    parts = [f"(p{i}:Person {{name: 'p{i}', age: {20 + (i * 7) % 50}}})"
             for i in range(n_people)]
    seen = set()
    for _ in range(n_edges):
        a = rng.randrange(n_people)
        b = rng.randrange(n_people)
        if a == b or (a, b) in seen:
            continue
        seen.add((a, b))
        parts.append(f"(p{a})-[:KNOWS {{w: {rng.randrange(100)}}}]->(p{b})")
    return "CREATE " + ",\n  ".join(parts)


def build_graph_from_spec(session, gspec: Optional[Dict[str, Any]],
                          versioned: bool):
    """Construct the spec'd graph on ``session``.  Returns None for an
    absent spec (the server then serves the ambient empty graph)."""
    from caps_tpu.testing.factory import create_graph
    if gspec is None:
        base = None
    else:
        kind = gspec.get("kind", "script")
        if kind == "script":
            create = gspec.get("create")
            if not create:
                raise QueryFailed(
                    "graph spec kind 'script' requires a non-empty "
                    "'create' statement")
            base = create_graph(session, create, gspec.get("parameters"))
        elif kind == "foaf":
            base = create_graph(session, foaf_create_script(
                int(gspec.get("n_people", 64)),
                int(gspec.get("n_edges", 256)),
                int(gspec.get("seed", 0))))
        else:
            raise QueryFailed(f"unknown graph spec kind {kind!r}")
    if versioned:
        from caps_tpu.relational.updates import versioned as make_versioned
        return make_versioned(session, base)
    return base


def rows_digest(rows) -> str:
    """Order-insensitive content digest of materialized rows — the
    cross-process read-your-writes check compares THIS, so two
    backends agree exactly when their visible graph state agrees."""
    canon = sorted(json.dumps(r, sort_keys=True, default=str)
                   for r in rows)
    return hashlib.sha256("\n".join(canon).encode("utf-8")).hexdigest()


class FleetBackend:
    """One serving process: a QueryServer behind a wire listener."""

    def __init__(self, spec: BackendSpec, session=None, start: bool = True):
        self.spec = spec
        if session is None:
            from caps_tpu.testing.sessions import make_backend_session
            session = make_backend_session(spec.backend)
        self.session = session
        self.graph = build_graph_from_spec(session, spec.graph,
                                           spec.versioned)
        warmup = None
        if spec.plan_store_path is not None:
            warmup = WarmupConfig(store_path=spec.plan_store_path,
                                  background=spec.warm_background,
                                  save_on_shutdown=True)
        rescache = None
        if spec.result_cache_budget is not None:
            from caps_tpu.relational.result_cache import ResultCacheConfig
            rescache = ResultCacheConfig(
                budget_bytes=int(spec.result_cache_budget))
        self.server = QueryServer(
            session, graph=self.graph,
            config=ServerConfig(workers=spec.workers,
                                max_queue=spec.max_queue,
                                default_deadline_s=spec.default_deadline_s,
                                warmup=warmup,
                                result_cache=rescache))
        self._registry = session.metrics_registry
        self._shutting_down = threading.Event()
        self._conn_threads = []
        self._conns = []
        self._lock = make_lock("fleet.FleetBackend._lock")
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        if start:
            self.start()

    # -- listener ------------------------------------------------------

    def start(self) -> int:
        """Bind + start accepting (idempotent).  Returns the bound
        port.  When the spec asks for inline warmup the server
        constructor already blocked on it — the port only opens warm."""
        with self._lock:
            if self._listener is not None:
                return self.port
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.bind((self.spec.host, self.spec.port))
            listener.listen(64)
            self._listener = listener
            self.port = listener.getsockname()[1]
            self._registry.gauge("fleet.backend_up").set(1.0)
            t = threading.Thread(target=self._accept_loop,
                                 name=f"caps-fleet-{self.spec.name}",
                                 daemon=True)
            self._accept_thread = t
            t.start()
            return self.port

    def _accept_loop(self) -> None:
        while not self._shutting_down.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed — shutdown
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            self._registry.counter("fleet.connections").inc()
            t = threading.Thread(
                target=wire.serve_connection,
                args=(conn, self.handle, self._shutting_down),
                name=f"caps-fleet-conn-{self.spec.name}", daemon=True)
            t.start()
            self._conn_threads.append(t)

    def shutdown(self, drain: bool = True) -> None:
        """Stop the listener, then the server (persisting warm state
        when a store is configured).  Safe to call twice."""
        self._shutting_down.set()
        with self._lock:
            listener, self._listener = self._listener, None
        if listener is not None:
            # shutdown() before close(): close() alone does NOT wake a
            # thread blocked in accept() on the same socket
            for fn in (lambda: listener.shutdown(socket.SHUT_RDWR),
                       listener.close):
                try:
                    fn()
                except OSError:  # pragma: no cover — teardown must not raise
                    pass
        # sever open connections like a dying process would: blocked
        # peers observe EOF/reset (a WireError), not a hung socket
        for conn in self._conns:
            for fn in (lambda c=conn: c.shutdown(socket.SHUT_RDWR),
                       conn.close):
                try:
                    fn()
                except OSError:  # pragma: no cover — teardown must not raise
                    pass
        accept_thread = self._accept_thread
        if accept_thread is not None and \
                accept_thread is not threading.current_thread():
            accept_thread.join(timeout=5.0)
        for t in self._conn_threads:
            if t is not threading.current_thread():
                t.join(timeout=5.0)
        self._registry.gauge("fleet.backend_up").set(0.0)
        self.server.shutdown(drain=drain)

    # -- op dispatch ---------------------------------------------------

    def handle(self, msg: Dict[str, Any]) -> Any:
        """One request → one reply payload.  ServeErrors propagate (the
        wire layer serializes them typed); anything else becomes a
        QueryFailed on the wire."""
        op = msg.get("op")
        fn = getattr(self, f"_op_{op}", None)
        if fn is None:
            raise QueryFailed(f"unknown fleet op {op!r}")
        self._registry.counter(f"fleet.ops.{op}").inc()
        return fn(msg)

    def _op_ping(self, msg) -> Dict[str, Any]:
        return {"name": self.spec.name, "pid": os.getpid(),
                "health": self.server.health(),
                "snapshot_version": self._snapshot_version()}

    def _snapshot_version(self) -> Optional[int]:
        if getattr(self.graph, "graph_is_versioned", False):
            return self.graph.current().snapshot_version
        return None

    def _submit(self, msg) -> Tuple[list, Dict[str, Any]]:
        deadline = msg.get("deadline_s", _UNSET)
        kwargs: Dict[str, Any] = {}
        if deadline is not _UNSET:
            kwargs["deadline_s"] = deadline
        if msg.get("priority") is not None:
            kwargs["priority"] = int(msg["priority"])
        handle = self.server.submit(msg.get("query", ""),
                                    msg.get("params") or {}, **kwargs)
        rows = handle.rows()
        return rows, handle.info

    def _op_query(self, msg) -> Dict[str, Any]:
        if self.spec.service_dwell_s > 0.0:
            clock.sleep(self.spec.service_dwell_s)
        rows, info = self._submit(msg)
        out = {"rows": rows,
               "ledger": info.get("ledger"),
               "snapshot_version": info.get("snapshot_version"),
               "queue_depth": self.server.admission.depth()}
        if msg.get("digest"):
            out["digest"] = rows_digest(rows)
        return out

    def _op_write(self, msg) -> Dict[str, Any]:
        """An update query against the owned versioned graph; the reply
        carries the post-commit version so the router can measure
        snapshot lag per peer."""
        if not getattr(self.graph, "graph_is_versioned", False):
            raise ReplicationUnsupported(
                f"backend {self.spec.name!r} serves a non-versioned "
                f"graph; writes need a versioned owner")
        rows, info = self._submit(msg)
        return {"rows": rows,
                "version": self.graph.current().snapshot_version,
                "queue_depth": self.server.admission.depth()}

    def _op_export_delta(self, msg) -> Dict[str, Any]:
        """Replication source: the current snapshot's full delta state.
        Deltas are cumulative over the shared base (the spec'd graph),
        so one pull brings ANY stale peer exactly current — no
        per-version chain to replay."""
        from caps_tpu.relational.updates import delta_state_to_payload
        if not getattr(self.graph, "graph_is_versioned", False):
            raise ReplicationUnsupported(
                f"backend {self.spec.name!r} serves a non-versioned "
                f"graph; nothing to export")
        snap = self.graph.current()
        return {"version": snap.snapshot_version,
                "state": delta_state_to_payload(snap.state)}

    def _op_sync_from(self, msg) -> Dict[str, Any]:
        """Replication sink: pull the owner's delta and flip the local
        version atomically.  Monotonic — a concurrent newer local
        version wins (install_state refuses to go backwards)."""
        from caps_tpu.relational.updates import delta_state_from_payload
        if not getattr(self.graph, "graph_is_versioned", False):
            raise ReplicationUnsupported(
                f"backend {self.spec.name!r} serves a non-versioned "
                f"graph; cannot install snapshots")
        with wire.WireClient(str(msg["host"]), int(msg["port"]),
                             timeout_s=30.0) as owner:
            delta = owner.call("export_delta")
        state = delta_state_from_payload(delta["state"])

        def _publish(new_snap) -> None:
            # runs under the commit lock BEFORE the reference swap
            # (relational/updates.py install_state): superseded result-
            # cache entries retire and the version gauge updates
            # happens-before any reader can be admitted at the new
            # version — the rejoin fencing fix (no read is ever served
            # a version the gauges don't yet report)
            self._registry.counter("fleet.snapshots_installed").inc()
            self._registry.gauge("fleet.snapshot_version").set(
                float(new_snap.snapshot_version))

        snap = self.graph.install_state(state, int(delta["version"]),
                                        on_install=_publish)
        return {"version": snap.snapshot_version}

    def _op_stats(self, msg) -> Dict[str, Any]:
        return self.server.stats()

    def _op_health(self, msg) -> Dict[str, Any]:
        return {"health": self.server.health()}

    def _op_health_report(self, msg) -> Dict[str, Any]:
        return self.server.health_report()

    def _op_metrics_snapshot(self, msg) -> Dict[str, Any]:
        return self._registry.snapshot()

    def _op_metrics_text(self, msg) -> str:
        return self.server.metrics_text()

    def _op_telemetry(self, msg) -> Dict[str, Any]:
        return self.server.telemetry.summary()

    def _op_warmup_report(self, msg) -> Dict[str, Any]:
        return self.server.warmup_report(msg.get("families"))

    def _op_warmup_wait(self, msg) -> Dict[str, Any]:
        warmer = self.server.warmer
        if warmer is None:
            return {"state": "none", "done": True}
        done = warmer.wait(msg.get("timeout"))
        return {"state": warmer.report().get("state", "?"), "done": done}

    def _op_shutdown(self, msg) -> Dict[str, Any]:
        # reply first, then tear down from another thread — the client
        # gets its ack before the socket dies
        threading.Thread(target=self.shutdown,
                         kwargs={"drain": bool(msg.get("drain", True))},
                         name=f"caps-fleet-shutdown-{self.spec.name}",
                         daemon=True).start()
        return {"closing": True}


# -- process entry point ----------------------------------------------


def backend_main(spec_json: str) -> None:  # pragma: no cover — child
    """Entry point of a spawned backend process: build the backend,
    report the bound port on stdout, serve until killed."""
    backend = FleetBackend(BackendSpec.from_json(spec_json))
    print(f"CAPS_FLEET_PORT {backend.port}", flush=True)
    try:
        backend._shutting_down.wait()
    except KeyboardInterrupt:
        pass
    backend.shutdown(drain=False)


def spawn_backend(spec: BackendSpec, env: Optional[Dict[str, str]] = None):
    """Launch ``python -m caps_tpu.serve.fleet`` with ``spec`` and wait
    for its port line.  Returns ``(process, port)``; the caller owns
    the process (terminate/kill/wait)."""
    import subprocess
    child_env = dict(os.environ)
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    # the child must import caps_tpu regardless of the caller's cwd:
    # put the package's parent dir on its PYTHONPATH explicitly
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    parent = os.path.dirname(pkg_root)
    existing = child_env.get("PYTHONPATH")
    child_env["PYTHONPATH"] = (
        parent if not existing else parent + os.pathsep + existing)
    if env:
        child_env.update(env)
    proc = subprocess.Popen(
        [sys.executable, "-m", "caps_tpu.serve.fleet", spec.to_json()],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=child_env, text=True)
    line = proc.stdout.readline()
    while line and not line.startswith("CAPS_FLEET_PORT"):
        line = proc.stdout.readline()
    if not line:
        proc.kill()
        raise QueryFailed(
            f"fleet backend {spec.name!r} exited before reporting a port")
    return proc, int(line.split()[1])


if __name__ == "__main__":  # pragma: no cover — child process
    backend_main(sys.argv[1])

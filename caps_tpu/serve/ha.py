"""Router high availability: epoch-fenced active/standby replication.

PR 19 gave every *backend* a failover story — durable WALs, an
epoch-fenced write lease, election by longest replayed log — but the
router itself stayed a single process: kill it and the fleet goes dark
with every backend healthy.  This module closes that last single point
of failure with the SAME machinery, one layer up:

* **Router lease.**  N :class:`HARouter` processes share the fleet's
  durable directory and contend for a second
  :class:`~caps_tpu.durability.lease.LeaseStore` namespace
  (``lease-router`` — same CAS-through-``O_EXCL``-claim-files epoch
  fence as the write lease, independent epochs).  Exactly one router is
  **active** at a time; the rest are **standbys** polling the lease.

* **Takeover.**  When the active's TTL lapses, the first standby to win
  the epoch CAS becomes active and rebuilds its routing state from
  shared truth, not from the dead peer: the write lease file names the
  current owner and epoch, ``scan_durable_dir`` names the highest
  durable version, and a ``ping`` probe per backend establishes
  liveness — router state is host-only metadata (docs/tpu.md), so
  nothing compiled migrates and takeover costs milliseconds.

* **Zombie fencing.**  The active router stamps its router-lease epoch
  on every write-coordination frame
  (:attr:`FleetRouter.router_epoch`); backends compare it against the
  published router lease and refuse older stamps with the typed
  :class:`~caps_tpu.serve.errors.StaleEpoch` — a deposed active that
  missed its own deposition can coordinate nothing, exactly like a
  zombie write owner.

* **RouterSet.**  The client facade: callers see availability, not
  topology.  It walks the router set, fails over on
  :class:`~caps_tpu.serve.errors.WireError`, retries standby refusals
  until the takeover lands (bounded by its wait budget), and adopts the
  active a :class:`StaleEpoch` names.

Determinism: the control loop is a public :meth:`HARouter.step` — the
background thread just calls it on a ``clock``-disciplined cadence, so
fake-clock tests drive elections one step at a time with zero real
waiting.
"""
from __future__ import annotations

import dataclasses
import json
import os
import socket
import sys
import threading
from typing import Any, Dict, List, Optional, Tuple

from caps_tpu.durability.lease import ROUTER_LEASE_NAME, LeaseStore
from caps_tpu.obs import clock
from caps_tpu.obs.lockgraph import make_rlock
from caps_tpu.obs.metrics import MetricsRegistry, global_registry
from caps_tpu.serve import wire
from caps_tpu.serve.errors import (FleetUnavailable, QueryFailed, ServeError,
                                   ServerClosed, StaleEpoch, WireError)
from caps_tpu.serve.router import FleetRouter, RouterConfig
from caps_tpu.serve.wire import WireClient

_UNSET = object()


@dataclasses.dataclass(frozen=True)
class RouterSpec:
    """Declarative description of one replicated router — everything a
    fresh process needs to join the router set."""

    #: lease identity (stable across restarts)
    name: str
    #: backend address map: name -> (host, port)
    backends: Dict[str, Tuple[str, int]]
    #: the fleet's shared durable directory — the router lease and the
    #: write lease both live here
    durable_dir: str
    #: initial write owner hint; None defaults to the first backend
    owner: Optional[str] = None
    #: router-lease TTL: how long after the active's last renewal a
    #: standby may take over (the read-availability gap bound)
    lease_ttl_s: float = 2.0
    #: control-loop cadence (renew / poll-for-takeover)
    poll_s: float = 0.25
    #: forwarded into RouterConfig
    failover_wait_s: float = 10.0
    timeout_s: float = 60.0
    hedge_reads: bool = False
    hedge_max_fraction: float = 0.1
    hedge_delay_s: Optional[float] = None
    host: str = "127.0.0.1"
    #: 0 = ephemeral (the listener reports the bound port)
    port: int = 0

    def to_json(self) -> str:
        raw = dataclasses.asdict(self)
        raw["backends"] = {n: list(hp) for n, hp in self.backends.items()}
        return json.dumps(raw, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "RouterSpec":
        fields = {f.name for f in dataclasses.fields(cls)}
        raw = json.loads(text)
        raw["backends"] = {n: (str(hp[0]), int(hp[1]))
                           for n, hp in raw.get("backends", {}).items()}
        return cls(**{k: v for k, v in raw.items() if k in fields})


class HARouter:
    """One replicated router process: a :class:`FleetRouter` behind a
    wire listener, holding (or contending for) the router lease."""

    def __init__(self, spec: RouterSpec, start: bool = True,
                 control: bool = True,
                 registry: Optional[MetricsRegistry] = None):
        self.spec = spec
        self.registry = registry if registry is not None \
            else global_registry()
        self.lease = LeaseStore(spec.durable_dir, ttl_s=spec.lease_ttl_s,
                                lease_name=ROUTER_LEASE_NAME,
                                registry=self.registry)
        self.router = FleetRouter(
            dict(spec.backends), owner=spec.owner,
            config=RouterConfig(failover_wait_s=spec.failover_wait_s,
                                timeout_s=spec.timeout_s,
                                hedge_reads=spec.hedge_reads,
                                hedge_max_fraction=spec.hedge_max_fraction,
                                hedge_delay_s=spec.hedge_delay_s),
            registry=self.registry)
        #: "active" holds the router lease; "standby" polls it.  The
        #: held epoch mirrors into ``router.router_epoch`` so every
        #: write frame carries it (the zombie fence's stamp).
        self.role = "standby"
        self.epoch: Optional[int] = None
        # re-entrant: step() runs under it and calls _demote/_takeover
        self._lock = make_rlock("ha.HARouter._lock")
        self._active_gauge = self.registry.gauge("router.ha_active")
        self._active_gauge.set(0.0)
        self._shutting_down = threading.Event()
        self._conn_threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._control_thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        if start:
            self.start(control=control)

    # -- lease control --------------------------------------------------

    def step(self) -> str:
        """ONE control-loop iteration: the active renews (demoting
        itself the moment renewal is refused — a deposed router must
        stop coordinating before its next write), a standby tries the
        epoch CAS and takes over on success.  Returns the role after
        the step; deterministic tests call this directly."""
        with self._lock:
            if self.role == "active":
                if not self.lease.renew(self.spec.name):
                    self.registry.counter("router.ha_renew_failures").inc()
                    self._demote()
                return self.role
            current = self.lease.read()
            if current is not None and not self.lease.expired(current) \
                    and current["owner"] != self.spec.name:
                return self.role
            epoch = self.lease.acquire(self.spec.name)
            if epoch is not None:
                self._takeover(epoch)
            return self.role

    def _demote(self) -> None:
        self.role = "standby"
        self.epoch = None
        self.router.router_epoch = None
        self._active_gauge.set(0.0)
        self.registry.counter("router.ha_demotions").inc()

    def _takeover(self, epoch: int) -> None:
        """Become active at ``epoch`` and rebuild routing state from
        shared truth: the write lease names the current owner (and its
        epoch), and a ping probe per backend establishes liveness and
        snapshot versions — never trust the dead peer's view.  Probe
        results tie-break exactly like the owner election (longest
        replayed log, then lexicographic name), so repeated takeovers
        under chaos are reproducible."""
        self.role = "active"
        self.epoch = int(epoch)
        self.router.router_epoch = self.epoch
        write_lease = LeaseStore(self.spec.durable_dir,
                                 ttl_s=self.spec.lease_ttl_s,
                                 registry=self.registry).read()
        probes: List[Tuple[int, str]] = []
        for name in sorted(self.spec.backends):
            try:
                info = self.router._clients[name].call("ping")
            except (WireError, ServerClosed):
                self.router.mark_dead(name)
                continue
            with self.router._lock:
                self.router._state[name] = {"live": True, "depth": 0,
                                            "burn": 0.0}
            version = info.get("snapshot_version")
            probes.append((-int(version if version is not None else 0),
                           name))
        if write_lease is not None \
                and write_lease["owner"] in self.spec.backends:
            with self.router._lock:
                self.router.owner = write_lease["owner"]
                self.router._owner_epoch = int(write_lease["epoch"])
        elif probes:
            # no published write lease: adopt the deterministic
            # election order's head as the owner hint (the first write
            # will elect for real through acquire_lease)
            probes.sort()
            with self.router._lock:
                self.router.owner = probes[0][1]
        self.registry.counter("router.ha_takeovers").inc()
        self._active_gauge.set(1.0)

    def _control_loop(self) -> None:
        while not self._shutting_down.is_set():
            try:
                self.step()
            except OSError:  # pragma: no cover — shared-store hiccup
                self.registry.counter("router.ha_step_errors").inc()
            clock.wait(self._shutting_down, self.spec.poll_s)

    # -- listener (same shape as FleetBackend's) ------------------------

    def start(self, control: bool = True) -> int:
        with self._lock:
            if self._listener is not None:
                return self.port
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.bind((self.spec.host, self.spec.port))
            listener.listen(64)
            self._listener = listener
            self.port = listener.getsockname()[1]
            t = threading.Thread(target=self._accept_loop,
                                 name=f"caps-harouter-{self.spec.name}",
                                 daemon=True)
            self._accept_thread = t
            t.start()
            if control:
                ct = threading.Thread(
                    target=self._control_loop,
                    name=f"caps-harouter-control-{self.spec.name}",
                    daemon=True)
                self._control_thread = ct
                ct.start()
            return self.port

    def _accept_loop(self) -> None:
        while not self._shutting_down.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed — shutdown
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            t = threading.Thread(
                target=wire.serve_connection,
                args=(conn, self.handle, self._shutting_down),
                name=f"caps-harouter-conn-{self.spec.name}", daemon=True)
            t.start()
            self._conn_threads.append(t)

    def shutdown(self) -> None:
        """Stop the listener, control loop, and backend clients.  Safe
        to call twice.  Does NOT release the lease early — the TTL is
        the failure-detection contract, and a clean shutdown should
        look exactly like a crash to the standbys (one code path)."""
        self._shutting_down.set()
        with self._lock:
            listener, self._listener = self._listener, None
        if listener is not None:
            for fn in (lambda: listener.shutdown(socket.SHUT_RDWR),
                       listener.close):
                try:
                    fn()
                except OSError:  # pragma: no cover — teardown must not raise
                    pass
        for conn in self._conns:
            for fn in (lambda c=conn: c.shutdown(socket.SHUT_RDWR),
                       conn.close):
                try:
                    fn()
                except OSError:  # pragma: no cover — teardown must not raise
                    pass
        for t in (self._accept_thread, self._control_thread):
            if t is not None and t is not threading.current_thread():
                t.join(timeout=5.0)
        for t in self._conn_threads:
            if t is not threading.current_thread():
                t.join(timeout=5.0)
        self.router.close()

    # -- op dispatch ----------------------------------------------------

    def handle(self, msg: Dict[str, Any]) -> Any:
        op = msg.get("op")
        fn = getattr(self, f"_op_{op}", None)
        if fn is None:
            raise QueryFailed(f"unknown router op {op!r}")
        return fn(msg)

    def _refuse_standby(self) -> None:
        """A standby refuses traffic — serving reads off a stale
        liveness view would be silent, serving writes would split
        coordination.  The refusal names the takeover horizon so
        clients back off for at most ~1 TTL."""
        self.registry.counter("router.ha_standby_refusals").inc()
        raise FleetUnavailable(
            f"router {self.spec.name!r} is standby — the active router "
            f"holds the lease",
            retry_after_s=min(self.spec.lease_ttl_s, 1.0))

    def _op_ping(self, msg) -> Dict[str, Any]:
        return {"name": self.spec.name, "pid": os.getpid(),
                "role": self.role, "epoch": self.epoch,
                "owner": self.router.owner}

    def _query_kwargs(self, msg) -> Dict[str, Any]:
        kwargs: Dict[str, Any] = {}
        if "deadline_s" in msg:
            kwargs["deadline_s"] = msg["deadline_s"]
        if msg.get("priority") is not None:
            kwargs["priority"] = int(msg["priority"])
        return kwargs

    def _op_query(self, msg) -> Dict[str, Any]:
        if self.role != "active":
            self._refuse_standby()
        kwargs = self._query_kwargs(msg)
        if msg.get("family") is not None:
            kwargs["family"] = str(msg["family"])
        return self.router.query(msg.get("query", ""),
                                 msg.get("params") or {},
                                 graph=str(msg.get("graph", "default")),
                                 digest=bool(msg.get("digest")), **kwargs)

    def _op_write(self, msg) -> Dict[str, Any]:
        if self.role != "active":
            self._refuse_standby()
        kwargs = self._query_kwargs(msg)
        kwargs.pop("priority", None)
        return self.router.write(msg.get("query", ""),
                                 msg.get("params") or {},
                                 ship=bool(msg.get("ship", True)), **kwargs)

    def _op_stats(self, msg) -> Dict[str, Any]:
        out = self.router.stats()
        out["role"] = self.role
        out["epoch"] = self.epoch
        return out

    def _op_metrics_snapshot(self, msg) -> Dict[str, Any]:
        return self.registry.snapshot()

    def _op_metrics_text(self, msg) -> str:
        return self.router.metrics_text()

    def _op_step(self, msg) -> Dict[str, Any]:
        """Drive one control iteration over the wire — the chaos bench
        steers subprocess routers deterministically with this."""
        return {"role": self.step(), "epoch": self.epoch}

    def _op_shutdown(self, msg) -> Dict[str, Any]:
        threading.Thread(target=self.shutdown,
                         name=f"caps-harouter-shutdown-{self.spec.name}",
                         daemon=True).start()
        return {"closing": True}


class RouterSet:
    """The client facade over a replicated router set: callers see one
    endpoint's availability, not the topology behind it.

    Transport failures (:class:`WireError` — the active died) and
    standby refusals (:class:`FleetUnavailable`) rotate to the next
    router and retry until ``wait_s`` lapses — one takeover TTL is
    inside that budget by construction, so a SIGKILLed active costs a
    bounded availability dip, not an outage.  A :class:`StaleEpoch`
    naming a router in the set adopts it as preferred and retries; any
    other typed error propagates verbatim (availability machinery must
    never mask application errors)."""

    def __init__(self, routers: Dict[str, Tuple[str, int]], *,
                 timeout_s: float = 30.0, wait_s: float = 10.0,
                 poll_s: float = 0.05,
                 registry: Optional[MetricsRegistry] = None):
        if not routers:
            raise FleetUnavailable("RouterSet needs at least one router")
        self.registry = registry if registry is not None \
            else global_registry()
        self.wait_s = float(wait_s)
        self.poll_s = float(poll_s)
        self._clients = {name: WireClient(host, port, timeout_s=timeout_s)
                         for name, (host, port) in routers.items()}
        self._order = list(routers)
        self._preferred = self._order[0]

    def _rotation(self) -> List[str]:
        at = self._order.index(self._preferred)
        return self._order[at:] + self._order[:at]

    def _call(self, op: str, fields: Dict[str, Any],
              wait_s: Optional[float] = None) -> Any:
        budget = self.wait_s if wait_s is None else float(wait_s)
        admitted = clock.now()
        last_err: Optional[ServeError] = None
        while True:
            for name in self._rotation():
                try:
                    reply = self._clients[name].call(op, **fields)
                except (WireError, ServerClosed) as ex:
                    # the router process is gone: fail over to the
                    # standby (counted — availability is never free)
                    last_err = ex
                    self.registry.counter(
                        "router.ha_client_failovers").inc()
                    continue
                except FleetUnavailable as ex:
                    # a standby refusing, or a fleet-level outage the
                    # NEXT router may see past — rotate, then wait out
                    # the takeover horizon
                    last_err = ex
                    continue
                except StaleEpoch as ex:
                    if ex.owner is not None and ex.owner in self._clients:
                        self._preferred = ex.owner
                        last_err = ex
                        continue
                    raise
                if self._preferred != name:
                    self._preferred = name
                return reply
            elapsed = clock.now() - admitted
            if elapsed >= budget:
                raise last_err if last_err is not None else \
                    FleetUnavailable("no router answered")
            clock.sleep(min(self.poll_s, max(budget - elapsed, 0.0)))

    def query(self, query: str,
              parameters: Optional[Dict[str, Any]] = None, *,
              family: Optional[str] = None, graph: str = "default",
              deadline_s: Any = _UNSET, priority: Optional[int] = None,
              digest: bool = False,
              wait_s: Optional[float] = None) -> Dict[str, Any]:
        fields: Dict[str, Any] = {"query": query,
                                  "params": parameters or {},
                                  "graph": graph}
        if family is not None:
            fields["family"] = family
        if deadline_s is not _UNSET:
            fields["deadline_s"] = deadline_s
        if priority is not None:
            fields["priority"] = priority
        if digest:
            fields["digest"] = True
        return self._call("query", fields, wait_s)

    def write(self, query: str,
              parameters: Optional[Dict[str, Any]] = None, *,
              ship: bool = True, deadline_s: Any = _UNSET,
              wait_s: Optional[float] = None) -> Dict[str, Any]:
        fields: Dict[str, Any] = {"query": query,
                                  "params": parameters or {},
                                  "ship": ship}
        if deadline_s is not _UNSET:
            fields["deadline_s"] = deadline_s
        return self._call("write", fields, wait_s)

    def active(self) -> Optional[str]:
        """Probe the set: the name of the router reporting active, or
        None when nobody does (mid-takeover)."""
        for name in self._rotation():
            try:
                info = self._clients[name].call("ping")
            except (WireError, ServerClosed):
                continue
            if info.get("role") == "active":
                self._preferred = name
                return name
        return None

    def stats(self) -> Dict[str, Any]:
        return self._call("stats", {})

    def close(self) -> None:
        for client in self._clients.values():
            client.close()


# -- process entry point ------------------------------------------------


def router_main(spec_json: str) -> None:  # pragma: no cover — child
    """Entry point of a spawned router process: build the router,
    report the bound port on stdout, serve until killed."""
    router = HARouter(RouterSpec.from_json(spec_json))
    print(f"CAPS_ROUTER_PORT {router.port}", flush=True)
    try:
        router._shutting_down.wait()
    except KeyboardInterrupt:
        pass
    router.shutdown()


def spawn_router(spec: RouterSpec,
                 env: Optional[Dict[str, str]] = None):
    """Launch ``python -m caps_tpu.serve.ha`` with ``spec`` and wait for
    its port line.  Returns ``(process, port)``; the caller owns the
    process (terminate/kill/wait) — the chaos bench SIGKILLs the active
    one mid-soak."""
    import subprocess
    child_env = dict(os.environ)
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    parent = os.path.dirname(pkg_root)
    existing = child_env.get("PYTHONPATH")
    child_env["PYTHONPATH"] = (
        parent if not existing else parent + os.pathsep + existing)
    if env:
        child_env.update(env)
    proc = subprocess.Popen(
        [sys.executable, "-m", "caps_tpu.serve.ha", spec.to_json()],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=child_env, text=True)
    line = proc.stdout.readline()
    while line and not line.startswith("CAPS_ROUTER_PORT"):
        line = proc.stdout.readline()
    if not line:
        proc.kill()
        raise QueryFailed(
            f"router {spec.name!r} exited before reporting a port")
    return proc, int(line.split()[1])


if __name__ == "__main__":  # pragma: no cover — child process
    router_main(sys.argv[1])

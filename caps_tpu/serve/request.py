"""In-flight request objects and the client-facing result handle.

``QueryServer.submit`` returns a :class:`QueryHandle` immediately; the
worker pool completes it.  The handle is a minimal Future: ``result()``
blocks (with an optional wait timeout), ``cancel()`` is cooperative
(a queued request is dropped at dequeue, a running one stops at its next
engine checkpoint), and ``info`` carries the per-request serving
telemetry (queue wait, batch size, total latency) the bench and the
stress tests assert on.
"""
from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, Mapping, Optional, Tuple

from caps_tpu.serve.deadline import CancelScope
from caps_tpu.serve.errors import Cancelled, WaitTimeout

#: Priority classes (lower value = served first).  INTERACTIVE is the
#: latency-sensitive default; BATCH work queues behind it and is the
#: first to shed under pressure (per-priority admission limits).
INTERACTIVE = 0
BATCH = 1

_request_ids = itertools.count(1)


class QueryHandle:
    """Future-style handle for one submitted query."""

    def __init__(self, request: "Request"):
        self._request = request
        self._done = threading.Event()
        self._result: Any = None
        self._rows: Optional[list] = None
        self._exception: Optional[BaseException] = None
        #: serving telemetry, filled in as the request progresses:
        #: queue_wait_s, batch_size, latency_s, worker
        self.info: Dict[str, Any] = {}

    # -- completion (worker side) --------------------------------------

    def _complete(self, result: Any = None, rows: Optional[list] = None,
                  exception: Optional[BaseException] = None) -> None:
        self._result = result
        self._rows = rows
        self._exception = exception
        self._done.set()

    # -- client side ---------------------------------------------------

    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self) -> bool:
        """Request cooperative cancellation.  Returns False if the
        request already completed (nothing to cancel)."""
        if self._done.is_set():
            return False
        self._request.scope.cancel()
        return True

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def exception(self, timeout: Optional[float] = None
                  ) -> Optional[BaseException]:
        if not self._done.wait(timeout):
            raise WaitTimeout("request not complete")
        return self._exception

    def result(self, timeout: Optional[float] = None) -> Any:
        """The CypherResult, or raises the request's typed error.
        ``timeout`` bounds the *wait*, not the query (that is what the
        request's deadline is for)."""
        if not self._done.wait(timeout):
            raise WaitTimeout("request not complete")
        if self._exception is not None:
            raise self._exception
        return self._result

    def rows(self, timeout: Optional[float] = None) -> list:
        """Materialized result rows (list of dicts).  Materialization
        happens on the worker when the server's ``materialize`` config
        is on (the default), else lazily here on the client thread."""
        result = self.result(timeout)
        if self._rows is None:
            self._rows = result.to_maps()
        return self._rows

    def __repr__(self):
        state = "done" if self._done.is_set() else "pending"
        return f"QueryHandle(#{self._request.request_id}, {state})"


class Request:
    """One admitted unit of work, owned by the queue then a worker."""

    __slots__ = ("request_id", "query", "params", "graph", "priority",
                 "scope", "batch_key", "mode", "handle", "enqueued_t",
                 "plan_key", "cache_key")

    def __init__(self, query: str, params: Mapping[str, Any], graph: Any,
                 priority: int, scope: CancelScope,
                 batch_key: Optional[Tuple], mode: Optional[str],
                 plan_key: Optional[Tuple] = None):
        self.request_id = next(_request_ids)
        self.query = query
        self.params = dict(params)
        self.graph = graph
        self.priority = priority
        self.scope = scope
        #: micro-batch compatibility key (serve/batcher.py); None =
        #: never batched (EXPLAIN/PROFILE, uncacheable graphs).  With
        #: ragged bucket batching this is the SHAPE key, wider than the
        #: plan family.
        self.batch_key = batch_key
        #: the exact plan-cache key family — what breakers, quarantine,
        #: and telemetry labels stay keyed by (defaults to batch_key for
        #: requests built before ragged batching existed)
        self.plan_key = plan_key if plan_key is not None else batch_key
        #: "explain" | "profile" | None — PROFILE is executed alone
        self.mode = mode
        self.handle = QueryHandle(self)
        self.enqueued_t = 0.0
        #: ``(result-cache key, snapshot version)`` stamped at admission
        #: when the read missed the result cache — completion offers the
        #: materialized rows back under exactly this key (serve/server.py)
        self.cache_key: Optional[Tuple] = None

    def drop_cancelled(self) -> bool:
        """Complete a dequeued-but-cancelled request without executing.
        Returns True when the request was dropped."""
        if self.scope.cancelled:
            self.handle._complete(
                exception=Cancelled(phase=self.scope.phase))
            return True
        return False

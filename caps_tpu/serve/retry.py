"""Transient-error retry with exponential backoff and deterministic jitter.

The policy object is pure arithmetic — it decides *how long* attempt N
backs off and *whether* a request's remaining deadline budget can afford
it; the serving worker (serve/server.py) owns the loop.  Two contracts
matter:

* **deadline-charged**: backoff sleeps spend the request's existing
  budget.  A retry never fires when the remaining budget is smaller
  than the next backoff — the give-up error carries the backoff as its
  ``retry_after_s`` hint (the client can retry with a fresh budget;
  the server won't burn a doomed sleep).
* **deterministic jitter**: the jitter term is a hash of (request id,
  attempt), not a PRNG draw — two runs of the same workload back off
  identically, so fault tests assert exact backoff sequences against a
  fake :mod:`caps_tpu.obs.clock` with no real sleeping.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from caps_tpu.obs import clock


def _jitter_unit(token: int, attempt: int) -> float:
    """Deterministic pseudo-uniform in [0, 1): a Knuth multiplicative
    hash of (token, attempt).  No PRNG state, no process seed — the
    same (request, attempt) always jitters the same way."""
    h = (token * 1_000_003 + attempt * 97 + 1) * 2_654_435_761
    return (h % (1 << 32)) / float(1 << 32)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Knobs for the worker-side transient retry loop (ServerConfig.retry).

    ``max_attempts`` counts *executions*, not re-executions: 3 means the
    original run plus at most two retries."""

    max_attempts: int = 3
    backoff_base_s: float = 0.05
    backoff_multiplier: float = 2.0
    backoff_max_s: float = 2.0
    #: +/- fraction of the backoff spread by the deterministic jitter
    #: (0.1 = each backoff lands within ±10% of its nominal value)
    jitter: float = 0.1

    def backoff_s(self, attempt: int, token: int = 0) -> float:
        """Backoff charged before retry number ``attempt`` (1-based:
        attempt 1 is the first RE-execution).  ``token`` feeds the
        deterministic jitter — the server passes the request id, so
        coalesced requests retrying after one fault don't thundering-herd
        on identical sleeps."""
        raw = min(self.backoff_max_s,
                  self.backoff_base_s
                  * self.backoff_multiplier ** max(0, attempt - 1))
        if self.jitter:
            raw *= 1.0 + self.jitter * (2.0 * _jitter_unit(token, attempt)
                                        - 1.0)
        return raw

    def budget_allows(self, remaining_s: Optional[float],
                      backoff_s: float) -> bool:
        """True when a request with ``remaining_s`` of deadline budget
        can afford to sleep ``backoff_s`` and still have time to
        execute.  None = no deadline = always affordable."""
        if remaining_s is None:
            return True
        return remaining_s > backoff_s

    def sleep(self, backoff_s: float, scope=None) -> None:
        """The one sanctioned backoff wait (stubbed by fake clocks in
        tests).  With a :class:`~caps_tpu.serve.deadline.CancelScope`
        the sleep is INTERRUPTIBLE: it blocks on the scope's cancel
        event via ``clock.wait``, so ``cancel()`` (or a non-drain
        shutdown cancelling in-flight requests) wakes the worker
        immediately instead of burning the rest of the backoff — the
        caller re-checks ``scope.cancelled`` on return."""
        if backoff_s <= 0:
            return
        if scope is None:
            clock.sleep(backoff_s)
            return
        clock.wait(scope.cancel_event, backoff_s)

"""Fleet router: consistent-hash routing with load-aware spill.

A thin, STATELESS process in front of N fleet backends
(serve/fleet.py).  Routing is a consistent hash of ``(graph,
plan-family key)`` over a virtual-node ring — the same family always
lands on the same process, so plan caches, fused replay memos, and the
warm-path store stay hot per process (the fleet-granularity version of
"compiled state never migrates", docs/tpu.md).  The hash is
``blake2b`` — stable across processes and Python builds, unlike the
per-process-randomized builtin ``hash``.

**Load-aware spill.**  Affinity must not let one hot family serialize
the fleet (the JSPIM skew lesson): every reply piggybacks the
backend's queue depth, and the router keeps a windowed view per
backend.  When the primary's last-known depth crosses
``RouterConfig.spill_queue_depth`` — or its SLO burn rate crosses
``spill_burn_rate`` — overflow traffic walks to the next ring node
instead of queueing behind the hot spot.  Spill is bounded: it walks
the preference order, so a family's traffic concentrates on at most a
few adjacent nodes rather than spraying the fleet cold.

**Failover.**  A transport failure marks the backend dead and retries
the SAME request on the next preference node — the ring segment
degrades, nothing rehashes, and the surviving nodes' cache affinity is
untouched (~1/N keys move is the consistent-hash contract, exercised
in tests/test_fleet.py).  A rejoining process is pinged, waits for its
PlanStore warmup, catches up on snapshots, and only then takes
traffic again.

**Writes** go to the single owner backend; the router then ships the
owner's delta snapshot to every live peer (peers pull from the owner
directly — the router only coordinates) and measures the lag
(``fleet.snapshot_lag_s``): the read-your-writes bound a client
observes across the whole fleet.  On a durable fleet
(caps_tpu/durability) owner death triggers an election instead of
read-only mode: the peer with the longest replayed log claims the
epoch-fenced lease, and every write frame carries the router's epoch so
a stale view (or a zombie owner) is fenced, never split-brained.
"""
from __future__ import annotations

import bisect
import collections
import dataclasses
import hashlib
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from caps_tpu.obs import clock
from caps_tpu.obs.lockgraph import make_lock, make_rlock
from caps_tpu.obs.metrics import (MetricsRegistry, global_registry,
                                  merge_snapshots)
from caps_tpu.obs.telemetry import RollingHistogram
from caps_tpu.serve.errors import (DeadlineExceeded, FleetUnavailable,
                                   Overloaded, ServeError, ServerClosed,
                                   StaleEpoch, WireError)
from caps_tpu.serve.wire import WireClient

_UNSET = object()

#: per-family latency windows kept for hedge-delay derivation (LRU —
#: same bound discipline as ServingTelemetry's family windows)
_MAX_LATENCY_FAMILIES = 64


def _ring_hash(key: str) -> int:
    """Position on the 64-bit ring — blake2b, NOT the builtin ``hash``
    (which is salted per process: two fleet members would disagree on
    every placement)."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big")


class HashRing:
    """Consistent-hash ring with virtual nodes.

    ``vnodes`` replicas per node smooth placement so each node owns
    ~1/N of the key space; add/remove moves only the segments adjacent
    to the changed node's vnodes (~1/N of keys)."""

    def __init__(self, nodes: Sequence[str] = (), vnodes: int = 64):
        self.vnodes = int(vnodes)
        self._points: List[Tuple[int, str]] = []
        self._keys: List[int] = []
        self._nodes: List[str] = []
        for n in nodes:
            self.add(n)

    def add(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.append(node)
        for i in range(self.vnodes):
            h = _ring_hash(f"{node}#{i}")
            at = bisect.bisect_left(self._points, (h, node))
            self._points.insert(at, (h, node))
        self._keys = [h for h, _ in self._points]

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.remove(node)
        self._points = [(h, n) for h, n in self._points if n != node]
        self._keys = [h for h, _ in self._points]

    def nodes(self) -> Tuple[str, ...]:
        return tuple(self._nodes)

    def lookup(self, key: str) -> Optional[str]:
        if not self._points:
            return None
        at = bisect.bisect_right(self._keys, _ring_hash(key))
        if at == len(self._points):
            at = 0
        return self._points[at][1]

    def preference(self, key: str, n: Optional[int] = None) -> List[str]:
        """Distinct nodes in ring-walk order from ``key``'s position —
        the failover/spill order.  Stable: removing a node leaves the
        relative order of the others unchanged."""
        if not self._points:
            return []
        want = len(self._nodes) if n is None else min(n, len(self._nodes))
        out: List[str] = []
        at = bisect.bisect_right(self._keys, _ring_hash(key))
        for i in range(len(self._points)):
            _h, node = self._points[(at + i) % len(self._points)]
            if node not in out:
                out.append(node)
                if len(out) == want:
                    break
        return out


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    #: virtual nodes per backend on the ring
    vnodes: int = 64
    #: spill when the primary's last-known queue depth reaches this
    spill_queue_depth: int = 8
    #: spill when the primary's fast SLO burn rate reaches this
    #: (telemetry burn > 1.0 already eats budget faster than allowed)
    spill_burn_rate: float = 4.0
    #: distinct ring nodes tried per request before FleetUnavailable
    max_attempts: int = 3
    #: per-call wire timeout
    timeout_s: float = 60.0
    #: how long a failover election waits for the dead owner's lease
    #: TTL to lapse before giving up (durable fleets only)
    failover_wait_s: float = 10.0
    #: hedge reads: when the primary has not replied after the
    #: per-family p99-derived delay, issue the SAME read to the next
    #: preference node — first reply wins, the loser's reply is
    #: discarded (tail tolerance for one slow backend)
    hedge_reads: bool = False
    #: hard bound on the hedged share of reads — hedges stop once
    #: ``router.hedges`` would exceed this fraction of reads routed
    hedge_max_fraction: float = 0.1
    #: fixed hedge delay override (seconds); None derives the delay
    #: from the family's rolling latency window at ``hedge_quantile``
    hedge_delay_s: Optional[float] = None
    #: quantile of the per-family latency window the hedge fires at
    hedge_quantile: float = 0.99


class FleetRouter:
    """Stateless request router over a set of fleet backends."""

    def __init__(self, backends: Dict[str, Tuple[str, int]],
                 owner: Optional[str] = None,
                 config: Optional[RouterConfig] = None,
                 registry: Optional[MetricsRegistry] = None):
        if not backends:
            raise FleetUnavailable("router needs at least one backend")
        self.config = config or RouterConfig()
        self.registry = registry if registry is not None \
            else global_registry()
        self._addrs = dict(backends)
        #: the single write owner (snapshot-shipping source); defaults
        #: to the first backend in insertion order
        self.owner = owner if owner is not None else next(iter(backends))
        if self.owner not in self._addrs:
            raise FleetUnavailable(f"owner {self.owner!r} is not a backend")
        #: the lease epoch writes are stamped with (durable fleets):
        #: learned from write acks and failover elections, fenced by the
        #: backends — a router holding a stale view is told so
        self._owner_epoch: Optional[int] = None
        #: the ROUTER lease epoch (serve/ha.py): when this router runs
        #: replicated, its HA wrapper stamps the held epoch here and
        #: every write-coordination frame carries it — a deposed zombie
        #: router is fenced by the backends exactly like a zombie owner
        self.router_epoch: Optional[int] = None
        #: per-family read-latency windows (hedge-delay source) and the
        #: hedge-rate bound's counters — guarded by their own leaf lock
        #: so the hedge race never contends with routing state
        self._latency: "collections.OrderedDict[str, RollingHistogram]" = \
            collections.OrderedDict()
        self._latency_lock = make_lock("router.FleetRouter._latency_lock")
        self._reads_routed = 0
        self._hedges_issued = 0
        self.ring = HashRing(backends.keys(), vnodes=self.config.vnodes)
        self._clients = {name: WireClient(host, port,
                                          timeout_s=self.config.timeout_s)
                         for name, (host, port) in self._addrs.items()}
        self._state = {name: {"live": True, "depth": 0, "burn": 0.0}
                       for name in self._addrs}
        self._last_ship: Dict[str, Any] = {"version": None, "lag_s": None,
                                           "peers": {}}
        self._lock = make_rlock("router.FleetRouter._lock")
        self._live_gauge = self.registry.gauge("fleet.backends_live")
        self._live_gauge.set(float(len(self._addrs)))

    # -- health bookkeeping --------------------------------------------

    def _live_count(self) -> int:
        return sum(1 for s in self._state.values() if s["live"])

    def mark_dead(self, name: str) -> None:
        with self._lock:
            if not self._state[name]["live"]:
                return
            self._state[name]["live"] = False
        self.registry.counter("router.backend_down").inc()
        self._live_gauge.set(float(self._live_count()))
        self._clients[name].close()

    def rejoin(self, name: str, warm_timeout_s: Optional[float] = 30.0,
               port: Optional[int] = None) -> Dict[str, Any]:
        """Readmit ``name`` to its ring segment — but only after the
        process proves it is actually ready: it answers a ping, its
        PlanStore warmup has finished (a cold rejoin taking traffic
        would compile on the client's clock), and its snapshot is
        caught up with the write owner.  Returns the readiness report."""
        with self._lock:
            if port is not None:
                host = self._addrs[name][0]
                self._addrs[name] = (host, port)
                self._clients[name].close()
                self._clients[name] = WireClient(
                    host, port, timeout_s=self.config.timeout_s)
            client = self._clients[name]
        info = client.call("ping")
        warm = client.call("warmup_wait", timeout=warm_timeout_s)
        synced = None
        if name != self.owner and info.get("snapshot_version") is not None:
            ohost, oport = self._addrs[self.owner]
            try:
                synced = client.call("sync_from", host=ohost, port=oport)
            except ServeError:
                self.registry.counter("fleet.ship_failures").inc()
        with self._lock:
            self._state[name] = {"live": True, "depth": 0, "burn": 0.0}
        self.registry.counter("router.rejoined").inc()
        self._live_gauge.set(float(self._live_count()))
        return {"ping": info, "warmup": warm, "synced": synced}

    def _note_reply(self, name: str, reply: Any) -> None:
        if isinstance(reply, dict) and "queue_depth" in reply:
            with self._lock:
                self._state[name]["depth"] = int(reply["queue_depth"])

    def note_burn(self, name: str, burn: float) -> None:
        """Feed a backend's scraped SLO burn rate into spill decisions
        (a health poller calls this from ``health_report``'s fast-burn
        field)."""
        with self._lock:
            self._state[name]["burn"] = float(burn)

    def _overloaded(self, name: str) -> bool:
        s = self._state[name]
        return (s["depth"] >= self.config.spill_queue_depth
                or s["burn"] >= self.config.spill_burn_rate)

    # -- read path -----------------------------------------------------

    @staticmethod
    def routing_key(graph: str, family: Optional[str], query: str) -> str:
        """(graph, plan-family) — the cache-affinity unit.  ``family``
        defaults to the query text, which IS the plan-family key for a
        parameterized workload (parameters don't change the plan)."""
        return f"{graph}|{family if family is not None else query}"

    def _observe_latency(self, key: str, elapsed_s: float) -> None:
        with self._latency_lock:
            hist = self._latency.get(key)
            if hist is None:
                while len(self._latency) >= _MAX_LATENCY_FAMILIES:
                    self._latency.popitem(last=False)
                hist = self._latency[key] = RollingHistogram()
            else:
                self._latency.move_to_end(key)
            hist.observe(clock.now(), elapsed_s)

    def _hedge_delay(self, key: str) -> Optional[float]:
        """The delay after which a read hedges: the configured override,
        else the family window's p99 — None (never hedge) until the
        window has observations, so a cold family cannot hedge off a
        guessed latency."""
        if self.config.hedge_delay_s is not None:
            return float(self.config.hedge_delay_s)
        with self._latency_lock:
            hist = self._latency.get(key)
            if hist is None:
                return None
            q = hist.quantile(clock.now(), self.config.hedge_quantile)
        return q if q is not None and q > 0.0 else None

    def _hedge_allowed(self) -> bool:
        """Honest rate bound: hedges never exceed the configured share
        of reads routed, so tail tolerance cannot silently double the
        fleet's read load."""
        with self._latency_lock:
            return (self._hedges_issued
                    < self.config.hedge_max_fraction
                    * max(1, self._reads_routed))

    def _hedged_call(self, primary: str, hedge_to: Optional[str],
                     fields: Dict[str, Any], delay_s: float,
                     wait_budget_s: float) -> Tuple[str, Any]:
        """Race one read between ``primary`` and (after ``delay_s``
        without a primary reply) ``hedge_to``.  First successful reply
        wins and is the ONLY reply returned — the loser's is discarded,
        never merged, so results cannot duplicate.  A backend whose leg
        died at the transport level is marked dead here (health is
        honest even when the other leg wins).  Raises the primary leg's
        error when no leg succeeds."""
        results: List[Tuple[str, bool, Any]] = []
        arrived = threading.Event()
        results_lock = threading.Lock()

        def leg(name: str) -> None:
            try:
                item = (name, True, self._clients[name].call(
                    "query", **fields))
            except BaseException as ex:
                item = (name, False, ex)
            with results_lock:
                results.append(item)
                arrived.set()

        threading.Thread(target=leg, args=(primary,), daemon=True,
                         name="caps-router-read").start()
        t0 = clock.now()
        hedged = False
        errors: Dict[str, BaseException] = {}
        legs = 1
        while True:
            with results_lock:
                batch, results[:] = list(results), []
                arrived.clear()
            for name, ok, value in batch:
                if ok:
                    if hedged and name != primary:
                        self.registry.counter("router.hedge_wins").inc()
                    return name, value
                errors[name] = value
                if isinstance(value, (WireError, ServerClosed)):
                    self.mark_dead(name)
            if len(errors) == legs:
                if not hedged and hedge_to is not None \
                        and self._hedge_allowed():
                    # the primary leg FAILED before the hedge delay:
                    # fall through and launch the hedge immediately —
                    # it is now the only leg left
                    pass
                else:
                    raise errors.get(primary,
                                     next(iter(errors.values())))
            elapsed = clock.now() - t0
            if elapsed >= wait_budget_s:
                raise DeadlineExceeded("route", wait_budget_s, elapsed)
            if not hedged and hedge_to is not None \
                    and (elapsed >= delay_s or primary in errors) \
                    and self._hedge_allowed():
                hedged = True
                legs += 1
                with self._latency_lock:
                    self._hedges_issued += 1
                self.registry.counter("router.hedges").inc()
                threading.Thread(target=leg, args=(hedge_to,),
                                 daemon=True,
                                 name="caps-router-hedge").start()
            elif len(errors) == legs:
                raise errors.get(primary, next(iter(errors.values())))
            horizon = wait_budget_s - elapsed
            if not hedged and hedge_to is not None:
                horizon = min(horizon, max(delay_s - elapsed, 0.0))
            clock.wait(arrived, max(horizon, 0.001))

    def query(self, query: str,
              parameters: Optional[Dict[str, Any]] = None, *,
              family: Optional[str] = None, graph: str = "default",
              deadline_s: Any = _UNSET, priority: Optional[int] = None,
              digest: bool = False) -> Dict[str, Any]:
        """Route one read.  The reply dict carries ``rows`` plus the
        backend's ledger/snapshot_version/queue_depth and the name it
        ran on (``backend``).  Raises the backend's typed error
        verbatim, or :class:`FleetUnavailable` when every candidate
        ring node failed at the transport level.

        **Deadline fidelity**: ``deadline_s`` is the caller's TOTAL
        budget, stamped at admission on ``obs.clock``.  Every hop —
        spill, failover retry, hedge — forwards the *remaining* budget
        recomputed from that stamp, never the original figure, so a
        2-hop failover cannot silently double the caller's wall budget.

        **Hedged reads** (``RouterConfig.hedge_reads``): after the
        family's p99-derived delay without a primary reply the read is
        ALSO issued to the next preference node; first reply wins, the
        loser is discarded.  Hedges are rate-bounded
        (``hedge_max_fraction``) and counted (``router.hedges`` /
        ``router.hedge_wins``) — a hedge win is one served request,
        never two."""
        key = self.routing_key(graph, family, query)
        admitted = clock.now()
        budget = (float(deadline_s)
                  if deadline_s is not _UNSET and deadline_s is not None
                  else None)
        prefs = self.ring.preference(key)
        candidates = [n for n in prefs if self._state[n]["live"]]
        if not candidates:
            raise FleetUnavailable("no live backends on the ring")
        if len(candidates) > 1 and self._overloaded(candidates[0]):
            # bounded spill: overflow walks to the NEXT ring node — the
            # hot family warms exactly one extra cache, not the fleet
            self.registry.counter("router.spilled").inc()
            candidates = candidates[1:] + candidates[:1]
        candidates = candidates[:max(1, self.config.max_attempts)]
        fields: Dict[str, Any] = {"query": query,
                                  "params": parameters or {}}
        if deadline_s is not _UNSET:
            fields["deadline_s"] = deadline_s
        if priority is not None:
            fields["priority"] = priority
        if digest:
            fields["digest"] = True
        with self._latency_lock:
            self._reads_routed += 1
        hint = 0.0
        for i, name in enumerate(candidates):
            if i:
                self.registry.counter("router.retries").inc()
            if budget is not None:
                elapsed = clock.now() - admitted
                if budget - elapsed <= 0.0:
                    raise DeadlineExceeded("route", budget, elapsed)
                # forward the REMAINING budget, not the original: the
                # backend's admission clock starts fresh per hop, so a
                # verbatim resend would extend the caller's deadline
                fields["deadline_s"] = budget - elapsed
            started = clock.now()
            hedge_to = None
            if self.config.hedge_reads and i + 1 < len(candidates):
                hedge_to = candidates[i + 1]
            try:
                if hedge_to is not None:
                    delay = self._hedge_delay(key)
                    if delay is None:
                        hedge_to = None
                if hedge_to is not None:
                    wait = (budget - (clock.now() - admitted)
                            if budget is not None
                            else self.config.timeout_s)
                    name, reply = self._hedged_call(
                        name, hedge_to, fields, delay, wait)
                else:
                    reply = self._clients[name].call("query", **fields)
            except (WireError, ServerClosed):
                # the process is gone (or lame-duck draining): degrade
                # its ring segment and retry the request on the next
                # node — in-flight work on a dead backend requeues here
                self.mark_dead(name)
                continue
            except Overloaded as ex:
                self._note_reply(name, {"queue_depth": ex.queue_depth})
                hint = max(hint, ex.retry_after_s)
                self.registry.counter("router.spilled").inc()
                continue
            self._observe_latency(key, clock.now() - started)
            self._note_reply(name, reply)
            self.registry.counter("router.requests").inc()
            if isinstance(reply, dict):
                reply["backend"] = name
            return reply
        raise FleetUnavailable(
            f"all {len(candidates)} candidate backends failed for "
            f"key {key!r}", retry_after_s=hint)

    # -- write path + snapshot shipping --------------------------------

    def write(self, query: str,
              parameters: Optional[Dict[str, Any]] = None, *,
              ship: bool = True,
              deadline_s: Any = _UNSET) -> Dict[str, Any]:
        """Route one write to the owner, then ship its post-commit
        snapshot to every live peer.  The reply carries the committed
        ``version`` and the shipping report (per-peer version + lag).

        **Failover** (durable fleets): when the owner is dead, the
        router elects the live peer with the longest replayed log and
        has it claim the epoch-fenced lease (waiting out the dead
        owner's TTL), then retries the write there.  Every write frame
        carries the router's known epoch, so a stale ownership view is
        fenced by the backend (:class:`StaleEpoch`) and corrected from
        the error's fields.  Non-durable fleets keep the legacy
        behavior: owner death makes the fleet read-only until rejoin.

        ``deadline_s`` is the caller's TOTAL budget (admission-stamped
        here): the failover retry forwards the remaining budget, never
        the original figure.  When this router runs replicated
        (serve/ha.py) every frame also carries its ``router_epoch`` —
        a deposed zombie router's coordination is fenced by the
        backends."""
        admitted = clock.now()
        budget = (float(deadline_s)
                  if deadline_s is not _UNSET and deadline_s is not None
                  else None)
        if not self._state[self.owner]["live"]:
            if not self._failover_owner():
                raise FleetUnavailable(
                    f"write owner {self.owner!r} is down — the fleet is "
                    f"read-only until it rejoins")
        for attempt in (0, 1):
            fields: Dict[str, Any] = {"query": query,
                                      "params": parameters or {}}
            if budget is not None:
                elapsed = clock.now() - admitted
                if budget - elapsed <= 0.0:
                    raise DeadlineExceeded("route", budget, elapsed)
                fields["deadline_s"] = budget - elapsed
            elif deadline_s is not _UNSET:
                fields["deadline_s"] = deadline_s
            if self._owner_epoch is not None:
                fields["epoch"] = self._owner_epoch
            if self.router_epoch is not None:
                fields["router_epoch"] = self.router_epoch
            try:
                reply = self._clients[self.owner].call("write", **fields)
            except WireError:
                dead = self.owner
                self.mark_dead(dead)
                if attempt or not self._failover_owner():
                    raise FleetUnavailable(
                        f"write owner {dead!r} failed mid-write")
                continue
            except StaleEpoch as ex:
                # the lease names the true owner — adopt and retry once
                self.registry.counter("router.stale_epochs").inc()
                if (attempt or ex.owner is None
                        or ex.owner not in self._addrs
                        or not self._state[ex.owner]["live"]):
                    raise
                with self._lock:
                    self.owner = ex.owner
                    self._owner_epoch = ex.lease_epoch
                continue
            if isinstance(reply, dict) and reply.get("epoch") is not None:
                self._owner_epoch = int(reply["epoch"])
            self._note_reply(self.owner, reply)
            self.registry.counter("router.writes").inc()
            if ship:
                reply["ship"] = self.ship_snapshots()
            return reply
        raise FleetUnavailable(  # pragma: no cover — loop always exits
            f"write owner {self.owner!r} failed mid-write")

    def _failover_owner(self) -> bool:
        """Elect a new write owner after owner death (durable fleets):
        the live peer with the longest replayed log wins (max snapshot
        version, ties by name), replays every backend's WAL tail from
        the shared store, and claims the epoch-fenced lease — polling
        until the dead owner's TTL lapses.  False when the fleet has no
        durability (legacy read-only-until-rejoin) or nobody can win."""
        candidates = []
        for name in sorted(self._addrs):
            if name == self.owner or not self._state[name]["live"]:
                continue
            try:
                version = self._clients[name].call(
                    "ping").get("snapshot_version")
            except WireError:
                self.mark_dead(name)
                continue
            if version is not None:
                candidates.append((-int(version), name))
        # deterministic election order: longest replayed log first,
        # equal logs broken LEXICOGRAPHICALLY by backend name — repeated
        # elections under chaos reproduce the same winner (the router
        # takeover in serve/ha.py elects by the same rule)
        candidates.sort()
        for _neg_version, name in candidates:
            try:
                out = self._clients[name].call(
                    "acquire_lease", wait_s=self.config.failover_wait_s)
            except WireError:
                self.mark_dead(name)
                continue
            if not out.get("durable"):
                return False  # no lease machinery anywhere in this fleet
            if out.get("epoch") is None:
                continue  # lost the epoch CAS — try the next-longest log
            with self._lock:
                self.owner = name
                self._owner_epoch = int(out["epoch"])
            self.registry.counter("router.failovers").inc()
            return True
        return False

    def ship_snapshots(self) -> Dict[str, Any]:
        """Bring every live peer current with the owner: each peer
        pulls the owner's delta (peer→owner direct; the router only
        coordinates) and flips its version atomically.  Records the
        measured lag — commit-to-everywhere-visible — in
        ``fleet.snapshot_lag_s``."""
        ohost, oport = self._addrs[self.owner]
        started = clock.now()
        peers: Dict[str, Any] = {}
        for name, state in list(self._state.items()):
            if name == self.owner or not state["live"]:
                continue
            try:
                out = self._clients[name].call("sync_from",
                                               host=ohost, port=oport)
                peers[name] = out.get("version")
            except WireError:
                self.registry.counter("fleet.ship_failures").inc()
                self.mark_dead(name)
            except ServeError:
                # typed refusal (e.g. non-versioned peer) — the peer is
                # alive, it just cannot replicate this graph
                self.registry.counter("fleet.ship_failures").inc()
        lag = clock.now() - started
        self.registry.gauge("fleet.snapshot_lag_s").set(lag)
        self.registry.counter("fleet.snapshots_shipped").inc(len(peers))
        with self._lock:
            self._last_ship = {"lag_s": lag, "peers": peers}
        return {"lag_s": lag, "peers": peers}

    # -- fleet-wide observability --------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            backends = {name: dict(state)
                        for name, state in self._state.items()}
        return {"owner": self.owner,
                "ring_nodes": list(self.ring.nodes()),
                "live": self._live_count(),
                "backends": backends,
                "last_ship": dict(self._last_ship)}

    def snapshot_report(self) -> Dict[str, Any]:
        """Owner + per-peer snapshot versions (a direct ping each) and
        the last measured shipping lag."""
        versions: Dict[str, Any] = {}
        for name, state in self._state.items():
            if not state["live"]:
                continue
            try:
                versions[name] = self._clients[name].call(
                    "ping").get("snapshot_version")
            except WireError:
                self.mark_dead(name)
        return {"owner": self.owner,
                "versions": versions,
                "lag_s": self._last_ship.get("lag_s")}

    def metrics_text(self) -> str:
        """ONE Prometheus scrape for the whole fleet: the router's own
        ``router.*``/``fleet.*`` series, plus every live backend's
        registry snapshot summed across processes
        (:func:`~caps_tpu.obs.metrics.merge_snapshots`)."""
        snaps = []
        for name, state in list(self._state.items()):
            if not state["live"]:
                continue
            try:
                snaps.append(self._clients[name].call("metrics_snapshot"))
            except WireError:
                self.mark_dead(name)
        return self.registry.expose_text(extra=merge_snapshots(snaps))

    def close(self) -> None:
        for client in self._clients.values():
            client.close()

"""QueryServer: the multi-client query-serving tier.

Turns an engine session into a service: clients ``submit()`` queries
from any thread and get Future-style handles back; a worker pool
executes them through the session's prepared-plan path with

* **admission control** — a bounded priority queue that sheds load with
  a typed ``Overloaded`` (retry_after hint) instead of queuing
  unboundedly (serve/admission.py);
* **micro-batching** — compatible in-flight requests (same normalized
  query / plan-cache key family) execute as one batched pass over the
  cached plan (serve/batcher.py, ``session.cypher_batch``);
* **deadlines + cooperative cancellation** — per-request budgets
  checked at engine phase boundaries (serve/deadline.py), with the
  expiry phase attributed in the error and the trace;
* **device fault domains** — with ``ServerConfig.devices=N`` the pool
  runs one worker per device replica (serve/devices.py): each worker
  owns a device with its own session (per-device plan cache, string
  pool, fused memos) and a replicated copy of the served graph, so N
  dispatch streams run in parallel.  Transient failures retry on a
  DIFFERENT device; ``device_failure_threshold`` consecutive
  device-attributed failures quarantine the device (its claimed work
  drains back to the dispatcher, capacity degrades to N-1, and the
  admission controller's retry_after estimator is told so), and a
  background canary probe reinstates it after ``device_cooldown_s``.

With ``devices=None`` (the default) execution is serialized through one
device stream exactly as before: workers share replica 0 — the caller's
own session — and overlap admission, timeout handling, and
materialization while one executes.

**Writes.**  Against a versioned default graph
(relational/updates.py), reads pin the latest committed snapshot AT
ADMISSION and finish on it — batch members, retries, degraded
re-executions, and cross-device failovers all replay that exact
version (no torn reads); write statements keep the mutable handle
(mode ``"write"``: never batched, pinned to device 0), commit
failure-atomically, and flow through the same classify/retry ladder as
reads — a transient mid-commit fault rolled back completely, so the
retry is safe.  ``ServerConfig.compaction_threshold_rows`` enables the
background compactor (serve/compaction.py), surfaced in
``stats()["compaction"]``.

Serving metrics land in the session's registry under ``serve.*``
(queue depth gauge, admitted/shed/completed/requeued counters, latency +
queue-wait + batch-size histograms, device quarantine/reinstate
transitions) and show up in ``session.metrics_snapshot()`` next to
everything else.

**Windowed telemetry** (obs/telemetry.py) sits on top of the cumulative
counters: rolling p50/p95/p99 latency, queue wait, batch occupancy,
shed/retry/abort rates and per-device utilization over the last
``telemetry_window_s`` seconds; an optional SLO (``ServerConfig.slo``)
evaluated into error-budget burn rates; a bounded per-request **flight
recorder** dumped automatically on breaker trips, device quarantines,
and compaction failures (``server.dump_flight_recorder()`` on demand).
``health_report()`` is the structured rollup, ``stats()["telemetry"]``
/ ``stats()["slo"]`` / ``stats()["batching"]`` the stats view, and
``server.metrics_text()`` the Prometheus text exposition of the whole
registry (windowed gauges included).

**Resource accounting** (ISSUE 10, obs/compile.py + obs/ledger.py +
obs/log.py): every finished request carries a ``ledger`` dict on its
handle (bytes in/out, compile seconds charged, peak rows) and in its
flight record; the per-plan-family compile ledger surfaces in
``stats()["compile"]`` / ``health_report()`` and drives
``warmup_report()`` (which hot families never compiled here — ROADMAP
item 2's AOT-warmup precondition); byte footprints (plan cache, string
pool, base+delta per snapshot, device HBM) in ``stats()["memory"]``;
and a structured event log (``server.events()``) plus a slow-query log
(``ServerConfig.slow_query_threshold_s`` → ``server.slow_queries()``,
records mergeable with flight dumps) correlate it all by request id /
plan family / snapshot version.
"""
from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Any, Dict, List, Mapping, Optional

from caps_tpu.obs import clock
from caps_tpu.obs.lockgraph import make_lock
from caps_tpu.relational.result_cache import (CachedRows, ResultCacheConfig,
                                              graph_version,
                                              result_cache_key)
from caps_tpu.obs.log import EventLog, SlowQueryLog
from caps_tpu.obs.telemetry import ServingTelemetry, SLOConfig
from caps_tpu.serve import batcher as _batcher
from caps_tpu.serve.admission import AdmissionController
from caps_tpu.serve.batcher import MicroBatcher
from caps_tpu.serve.breaker import REJECT, TRIAL, CircuitBreaker
from caps_tpu.serve.deadline import CancelScope, cancel_scope
from caps_tpu.serve.devices import DeviceReplica, ReplicaSet
from caps_tpu.serve.errors import (Cancelled, CancellationError, CircuitOpen,
                                   DeadlineExceeded, Overloaded, QueryFailed,
                                   ServerClosed)
from caps_tpu.serve.failure import (FATAL, TRANSIENT, attribute_device,
                                    classify, device_of,
                                    quarantine_plan_state)
from caps_tpu.serve.request import INTERACTIVE, QueryHandle, Request
from caps_tpu.serve.retry import RetryPolicy
from caps_tpu.serve.shards import ShardGroup, ShardGroupConfig
from caps_tpu.serve.warmup import ServerWarmup, WarmupConfig

_UNSET = object()

#: batch-size histogram buckets (powers of two up to the queue bound)
_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)

#: degraded execution ladder (failure containment): 0 = the normal
#: serving path (cached plan, fused TPU replay); 1 = plan-cache bypass —
#: a fresh plan, fused execution re-records from scratch; 2 = fresh plan
#: AND per-operator unfused execution (no shared cached state at all).
_LADDER = ("fused", "replan", "unfused")

#: upper bound on a quarantined worker's nap between probe checks —
#: keeps it responsive to shutdown without hot-spinning
_PROBE_NAP_S = 0.05


def _fresh_copy(ex: BaseException) -> BaseException:
    """A fresh same-type exception for fanning one batch-level setup
    failure out to every member (handles must never share one mutable
    error object).  The classification markers ride along — a copy that
    lost ``caps_transient`` would send its member down the quarantine
    ladder while the original retried.  Exception types with
    non-reconstructible constructors fall back to the original
    instance."""
    try:
        fresh = type(ex)(*ex.args)
    except Exception:
        return ex
    for attr in ("caps_transient", "caps_device_fault", "caps_failed_op",
                 "caps_device_index"):
        val = getattr(ex, attr, None)
        if val is not None:
            try:
                setattr(fresh, attr, val)
            except Exception:  # pragma: no cover — slotted exception
                return ex
    return fresh


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    #: worker threads when ``devices`` is None: execution then runs one
    #: serialized device stream, extra workers overlap admission and
    #: materialization.  With ``devices=N`` the pool is one worker per
    #: device and this field is ignored.
    workers: int = 2
    #: device replicas (serve/devices.py): N parallel dispatch streams,
    #: each worker owning a device with a replicated graph and its own
    #: compiled state.  None = single-stream legacy mode on the caller's
    #: session.  On CPU the replicas are simulated devices; on a TPU
    #: platform they pin to real ``jax.devices()``.
    devices: Optional[int] = None
    #: global queue bound — beyond it submit() sheds with Overloaded
    max_queue: int = 64
    #: optional per-priority queue caps, e.g. {BATCH: 16} keeps
    #: background traffic from filling the queue
    per_priority_limits: Optional[Dict[int, int]] = None
    #: max requests coalesced into one micro-batch
    max_batch: int = 8
    #: seconds a batch leader waits for followers (0 = batch only what
    #: is already queued — no added leader latency)
    batch_window_s: float = 0.0
    #: ragged bucket batching (serve/batcher.py + relational/shapes.py):
    #: the batch key widens from the exact plan family to the parameter
    #: SHAPE-BUCKET signature, so different queries' shape-compatible
    #: launches pack into one shared batch window.  Members keep their
    #: own cached plans (results stay exact) and their own plan-family
    #: breakers/quarantine (``Request.plan_key``).
    ragged_batching: bool = False
    #: AOT warmup at server start (serve/warmup.py): precompile the hot
    #: families — from an explicit list or a persistent plan store —
    #: through the normal compile boundaries, so the compile ledger
    #: proves coverage before traffic arrives.  None = no warmup.
    warmup: Optional["WarmupConfig"] = None
    #: shard-group capacity members (serve/shards.py): with ``shards=N``
    #: the server fronts ONE hash-partitioned graph — the ``shard_graph``
    #: passed at construction, defaulting to the default graph — behind
    #: a group of N member devices: single-shard queries route to the
    #: owning member, cross-shard patterns ride the group's mesh-sharded
    #: session, and the failure ladder runs at GROUP level (a dead shard
    #: device degrades its group, never the server).  Replica members
    #: (``devices``) keep serving every other graph.
    shards: Optional[int] = None
    #: knobs for the group (partition property, paging budget, ladder
    #: thresholds); ``members`` is overridden by ``shards``
    shard_config: Optional["ShardGroupConfig"] = None
    #: default per-request budget (None = no deadline)
    default_deadline_s: Optional[float] = None
    default_priority: int = INTERACTIVE
    #: materialize rows on the worker (handle.rows() is then free)
    materialize: bool = True
    #: transient-error retry (serve/retry.py): exponential backoff with
    #: deterministic jitter, charged against the request's deadline;
    #: with multiple devices the re-execution fails over to a DIFFERENT
    #: healthy device
    retry: RetryPolicy = dataclasses.field(default_factory=RetryPolicy)
    #: consecutive request-level failures (whole containment ladder
    #: exhausted) before a plan family's circuit breaker opens
    breaker_threshold: int = 3
    #: seconds an open breaker fast-fails a family before letting one
    #: half-open trial through
    breaker_cooldown_s: float = 5.0
    #: consecutive DEVICE-attributed failures (serve/failure.py
    #: ``device_fault``) before a device replica is quarantined; only
    #: meaningful with ``devices >= 2`` (there is no failover target
    #: for a single device)
    device_failure_threshold: int = 3
    #: seconds a quarantined device sits out before each background
    #: half-open canary probe
    device_cooldown_s: float = 1.0
    #: delta-store backlog (rows) that triggers background compaction of
    #: a versioned default graph (serve/compaction.py); None disables
    #: the row trigger (explicit ``graph.compact()`` still works)
    compaction_threshold_rows: Optional[int] = None
    #: delta-store backlog (bytes — ``graph.delta_nbytes()``) that
    #: triggers background compaction; crossing EITHER threshold folds.
    #: A few huge property rows can now trigger compaction long before
    #: the row count would.
    compaction_threshold_bytes: Optional[int] = None
    #: cadence of the compactor's backlog checks
    compaction_interval_s: float = 0.05
    #: structured slow-query log (obs/log.py): any request whose total
    #: latency crosses this captures a full record — plan text, per-op
    #: stats, ledger (bytes in/out, compile seconds, peak rows) — in
    #: ``server.slow_queries()``; None disables capture
    slow_query_threshold_s: Optional[float] = None
    #: bounded ring size of captured slow-query records
    slow_query_log_size: int = 64
    #: bounded ring size of the structured event log (compile charges,
    #: breaker trips, quarantines, compaction failures, slow queries —
    #: ``server.events()``)
    event_log_capacity: int = 1024
    #: optional JSON-lines sink: every structured event also appends to
    #: this file (off-process ingestion)
    event_log_path: Optional[str] = None
    #: serving SLO (obs/telemetry.py): a latency target + objectives
    #: evaluated over the telemetry window into error-budget burn rates
    #: (``health_report()``, ``slo.*`` gauges); None = no SLO evaluation
    #: (windowed telemetry is still collected)
    slo: Optional[SLOConfig] = None
    #: rolling telemetry window: ``telemetry_buckets`` ring slots
    #: spanning ``telemetry_window_s`` seconds, rotated on obs.clock
    telemetry_window_s: float = 60.0
    telemetry_buckets: int = 60
    #: bounded ring of per-request flight records (the postmortem black
    #: box, dumped on breaker-trip / quarantine / compaction-failure
    #: and via ``dump_flight_recorder()``)
    flight_recorder_size: int = 256
    #: snapshot-keyed result & subplan cache (relational/result_cache.py):
    #: hot repeated reads return at ADMISSION — no worker slot, no device
    #: dwell, no batch window (flight records stamp outcome="cache_hit").
    #: None = every read pays the device path.
    result_cache: Optional["ResultCacheConfig"] = None


class QueryServer:
    """Concurrent serving facade over one session.

    >>> server = QueryServer(session, graph=g)
    >>> h = server.submit("MATCH (n:Person) WHERE n.age > $a "
    ...                   "RETURN n.name AS name", {"a": 30})
    >>> h.rows()
    [...]
    >>> server.shutdown()
    """

    def __init__(self, session, graph=None,
                 config: Optional[ServerConfig] = None, start: bool = True,
                 shard_graph=None):
        self.session = session
        self.config = config or ServerConfig()
        self._default_graph = graph if graph is not None \
            else session._ambient
        self._shard_graph = shard_graph
        registry = session.metrics_registry
        #: windowed telemetry + SLO + flight recorder (obs/telemetry.py):
        #: rolling p50/p95/p99, error-budget burn rates, the per-request
        #: black box, and the live ``telemetry.*``/``slo.*`` gauges
        self.telemetry = ServingTelemetry(
            registry, window_s=self.config.telemetry_window_s,
            buckets=self.config.telemetry_buckets, slo=self.config.slo,
            flight_recorder_size=self.config.flight_recorder_size)
        #: structured event log (obs/log.py): compile charges, breaker
        #: trips, quarantines, compaction failures, slow queries — every
        #: event correlated by request id / plan family
        self.event_log = EventLog(capacity=self.config.event_log_capacity,
                                  registry=registry,
                                  path=self.config.event_log_path)
        #: divergence-triggered re-planning (relational/session.py
        #: ``_maybe_replan``): the session retires a cached family whose
        #: executions keep diverging from the cost model's estimates;
        #: this listener lands the ``replan.*`` transitions in the
        #: structured event log so the loop is observable end-to-end
        #: (the re-plan's compile charge follows as ``compile.charged``)
        listeners = getattr(session, "replan_listeners", None)
        if listeners is not None:
            listeners.append(self._on_replan)
        #: slow-query log: over-threshold requests captured with plan
        #: text, per-op stats, and the resource ledger (None = disabled)
        self.slow_log = None
        if self.config.slow_query_threshold_s is not None:
            self.slow_log = SlowQueryLog(
                self.config.slow_query_threshold_s,
                capacity=self.config.slow_query_log_size,
                registry=registry, event_log=self.event_log)
        #: memory ledger (obs/ledger.py): account the served graph so
        #: ``stats()["memory"]`` carries its base/delta footprint.
        #: Tracked under THIS server as owner: several servers on one
        #: session each hold their own "default" slot, and shutdown
        #: releases only ours — a short-lived sibling can never drop a
        #: live server's accounting.
        ledger = getattr(session, "memory_ledger", None)
        if ledger is not None:
            ledger.track("default", self._default_graph, owner=self)
        #: snapshot-keyed result & subplan cache (relational/
        #: result_cache.py): consulted at admission, fed at completion.
        #: Attached to the session so the execution paths seed/store
        #: subplan intermediates and the memory ledger's
        #: mem.result_cache_bytes gauge sees it.
        self.result_cache = None
        if self.config.result_cache is not None \
                and self.config.result_cache.enabled:
            from caps_tpu.relational.result_cache import ResultCache
            self.result_cache = ResultCache(self.config.result_cache,
                                            registry=registry)
            session.result_cache = self.result_cache
        #: shard-group capacity members (serve/shards.py): one group of
        #: ``config.shards`` member devices fronting the partitioned
        #: ``shard_graph`` (default: the server's default graph).  Built
        #: BEFORE the replica set so both kinds of member sit behind the
        #: same dispatch/claim machinery.
        self.shard_groups: List[ShardGroup] = []
        if self.config.shards:
            target = shard_graph if shard_graph is not None \
                else self._default_graph
            gcfg = self.config.shard_config or ShardGroupConfig()
            gcfg = dataclasses.replace(gcfg, members=self.config.shards)
            self.shard_groups.append(ShardGroup(
                session, target, gcfg, registry=registry,
                event_log=self.event_log,
                index=(self.config.devices or 1),
                on_change=lambda: self.admission.set_active_workers(
                    self.devices.live_count() or 1)))
        self.admission = AdmissionController(
            registry, max_queue=self.config.max_queue,
            per_priority_limits=self.config.per_priority_limits,
            workers=(self.config.devices or self.config.workers)
            + len(self.shard_groups),
            telemetry=self.telemetry)
        self.batcher = MicroBatcher(self.admission,
                                    max_batch=self.config.max_batch,
                                    window_s=self.config.batch_window_s)
        self.retry_policy = self.config.retry or RetryPolicy()
        self.breaker = CircuitBreaker(
            registry, failure_threshold=self.config.breaker_threshold,
            cooldown_s=self.config.breaker_cooldown_s)
        #: the device fault domains: replica 0 is the caller's session;
        #: replicas 1..N-1 are clones with re-ingested graph copies.
        #: Quarantine/reinstate transitions re-tell the admission
        #: controller how many parallel streams are actually live.
        #: replicas never eagerly ingest a group-served default graph —
        #: capacity lives on the group's members, that is the point
        replica_default = graph
        if self.shard_groups and \
                self.shard_groups[0].serves(self._default_graph):
            replica_default = None
        self.devices = ReplicaSet(
            session, graph=replica_default,
            n_devices=self.config.devices or 1,
            registry=registry,
            failure_threshold=self.config.device_failure_threshold,
            cooldown_s=self.config.device_cooldown_s,
            on_change=lambda: self.admission.set_active_workers(
                self.devices.live_count() or 1),
            groups=self.shard_groups)
        #: AOT warmup driver (serve/warmup.py) — None unless configured.
        #: ``start()`` runs it (inline or background per its config);
        #: progress/outcome ride ``stats()["warmup"]``.
        self.warmer = (ServerWarmup(self, self.config.warmup)
                       if self.config.warmup is not None else None)
        self._completed = registry.counter("serve.completed")
        self._failed = registry.counter("serve.failed")
        self._cancelled = registry.counter("serve.cancelled")
        self._deadline_exceeded = registry.counter("serve.deadline_exceeded")
        self._batches = registry.counter("serve.batches")
        self._retries = registry.counter("serve.retries")
        self._quarantines = registry.counter("serve.quarantined")
        self._degraded_runs = registry.counter("serve.degraded_exec")
        self._batch_hist = registry.histogram("serve.batch_size",
                                              buckets=_BATCH_BUCKETS)
        self._latency = registry.histogram("serve.latency_s")
        self._queue_wait = registry.histogram("serve.queue_wait_s")
        self._registry = registry
        self._threads: List[threading.Thread] = []
        self._started = False
        #: requests currently claimed by workers — a non-drain shutdown
        #: cancels their scopes so backoff sleeps and engine checkpoints
        #: end them promptly
        self._inflight: set = set()
        self._inflight_lock = make_lock("server.QueryServer"
                                        "._inflight_lock")
        #: background compaction of a versioned default graph
        #: (serve/compaction.py) — None unless configured AND the graph
        #: is versioned
        self.compactor = None
        if ((self.config.compaction_threshold_rows is not None
             or self.config.compaction_threshold_bytes is not None)
                and getattr(self._default_graph, "graph_is_versioned",
                            False)):
            from caps_tpu.serve.compaction import Compactor
            self.compactor = Compactor(
                self._default_graph, registry,
                threshold_rows=self.config.compaction_threshold_rows,
                threshold_bytes=self.config.compaction_threshold_bytes,
                interval_s=self.config.compaction_interval_s,
                on_failure=self._compaction_failed)
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "QueryServer":
        """Start the worker pool (idempotent).  ``start=False`` at
        construction lets tests and benchmarks pre-load the queue so the
        first batch demonstrably coalesces.  With ``devices=N`` the pool
        is one worker per device replica; otherwise ``workers`` threads
        share replica 0 (one serialized stream)."""
        if self._started:
            return self
        self._started = True
        if self.warmer is not None:
            # inline warmup (background=False) completes BEFORE the
            # worker pool spins up — the first admitted request then
            # finds a fully compiled hot set; background warmup runs
            # concurrently with serving and reports progress in stats()
            self.warmer.start()
        if self.config.devices is not None:
            bindings = list(self.devices.replicas)
        else:
            bindings = [self.devices.replicas[0]] \
                * max(1, self.config.workers)
        # one dispatch stream per shard group, plus its background
        # maintenance loop (probe + rebuild off the serving path)
        bindings.extend(self.shard_groups)
        for group in self.shard_groups:
            group.start_maintenance()
        for i, replica in enumerate(bindings):
            t = threading.Thread(
                target=self._worker_loop, args=(replica,),
                name=f"caps-tpu-serve-{i}-dev{replica.index}", daemon=True)
            self._threads.append(t)
            t.start()
        if self.compactor is not None:
            self.compactor.start()
        return self

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> bool:
        """Stop accepting work.  ``drain=True`` (default) completes
        everything already queued before workers exit; ``drain=False``
        fails queued requests with ``Cancelled`` AND cancels in-flight
        ones (their backoff sleeps wake immediately — serve/retry.py).
        ``timeout`` bounds the TOTAL wait for workers; returns False
        (with the worker handles retained, so a later call can finish
        the join) when they are still running at the deadline."""
        self.admission.close()
        if not drain:
            for req in self.admission.drain_remaining():
                req.scope.cancel()
                req.handle._complete(
                    exception=Cancelled(phase="queued"))
                self._cancelled.inc()
            with self._inflight_lock:
                inflight = list(self._inflight)
            for req in inflight:
                req.scope.cancel()
        elif not self._started and self.admission.depth() > 0:
            # never-started server with a backlog: draining means the
            # queued work still completes — spin the workers up; they
            # exit once the (closed) queue is empty
            self.start()
        if not self._started:
            self._release_resources()
            return True
        deadline = None if timeout is None else clock.now() + timeout
        for t in self._threads:
            t.join(None if deadline is None
                   else max(0.0, deadline - clock.now()))
        still_running = [t for t in self._threads if t.is_alive()]
        self._threads = still_running
        if self.compactor is not None:
            self.compactor.stop()
        if not still_running:
            # fully stopped: the windowed gauges must not keep reading
            # (or pinning) this server's telemetry — same contract as
            # the admission depth gauge's deregistration
            self._release_resources()
        return not still_running

    def _release_resources(self) -> None:
        """Full-stop cleanup: the warmer persists its store (before the
        event log closes, so a save failure still events), telemetry
        gauges leave the live set, the event-log file sink closes, and
        the memory ledger drops this server's graph slot (only if a
        newer server has not re-tracked it) so a dead server stops
        inflating ``mem.tracked_graph_bytes``."""
        if self.warmer is not None:
            self.warmer.finalize()
        for group in self.shard_groups:
            group.close()
        listeners = getattr(self.session, "replan_listeners", None)
        if listeners is not None and self._on_replan in listeners:
            listeners.remove(self._on_replan)
        self.telemetry.close()
        self.event_log.close()
        ledger = getattr(self.session, "memory_ledger", None)
        if ledger is not None:
            ledger.untrack_if("default", self._default_graph, owner=self)
        if self.result_cache is not None:
            # detach only OUR cache — a newer server may have attached
            # its own meanwhile (same discipline as untrack_if above)
            if getattr(self.session, "result_cache", None) \
                    is self.result_cache:
                self.session.result_cache = None
            self.result_cache.clear()

    def __enter__(self) -> "QueryServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=True)

    # -- client API ----------------------------------------------------

    def submit(self, query: str,
               parameters: Optional[Mapping[str, Any]] = None, *,
               graph=None, deadline_s: Any = _UNSET,
               priority: Optional[int] = None) -> QueryHandle:
        """Enqueue a query; returns immediately with a handle.

        Raises :class:`ServerClosed` after shutdown began and
        :class:`Overloaded` when admission sheds the request —
        synchronous, so the caller's backpressure is immediate.
        ``deadline_s`` is the request's total budget (queue wait
        included); ``deadline_s=None`` explicitly disables the
        server-default deadline for this request."""
        if deadline_s is _UNSET:
            deadline_s = self.config.default_deadline_s
        if priority is None:
            priority = self.config.default_priority
        graph = graph if graph is not None else self._default_graph
        params = dict(parameters or {})
        scope = CancelScope(budget_s=deadline_s)
        if getattr(graph, "graph_is_versioned", False):
            # snapshot isolation at ADMISSION: a read pins the latest
            # committed snapshot here and finishes on it — coalesced
            # batch members, retries, degraded re-executions, and
            # cross-device failovers all replay against this exact
            # version, whatever writes commit meanwhile.  Writes keep
            # the handle (they serialize on its commit lock and always
            # see the latest state).  Resolve BEFORE keying so the
            # admission path computes the batch key exactly once.
            # Shard-group graphs never reach this branch: the group
            # versions its partitions INTERNALLY (serve/shards.py), so
            # writes pass through untouched and commit via the group's
            # own lineage inside ShardGroup.execute.
            from caps_tpu.relational.updates import is_update_query
            if not is_update_query(query):
                graph = graph.current()
        group = self.devices.group_for(graph)
        if group is not None:
            # group-level admission: a QUARANTINED group sheds its
            # traffic here with an honest retry hint (the remaining
            # rebuild cooldown) instead of queueing work nobody can
            # serve — replica members keep serving everything else
            retry_after = group.shed_retry_after()
            if retry_after is not None:
                self.telemetry.note_shed()
                raise Overloaded(
                    f"shard group {group.name!r} is quarantined "
                    f"(rebuild pending; retry after {retry_after:.3f}s)",
                    retry_after_s=retry_after,
                    queue_depth=self.admission.depth(), priority=priority)
        mode, plan_key, key = _batcher.request_keys(
            graph, query, params, ragged=self.config.ragged_batching,
            lattice=getattr(self.session, "shape_lattice", None))
        req = Request(query, params, graph, priority, scope, key, mode,
                      plan_key=plan_key)
        if getattr(graph, "snapshot_version", None) is not None:
            req.handle.info["snapshot_version"] = graph.snapshot_version
        if self.result_cache is not None and mode is None \
                and plan_key is not None:
            # result-cache fast path, BEFORE the queue: a hit returns
            # without consuming a worker slot, device dwell, or batch
            # window.  Writes/EXPLAIN/PROFILE (mode set) and
            # unanchorable graphs (plan_key None) never consult it.
            ck = result_cache_key(graph, query, params)
            if ck is not None:
                version = graph_version(graph)
                rows = self.result_cache.lookup(ck, version)
                if rows is not None:
                    self._serve_cache_hit(req, rows)
                    return req.handle
                # miss: completion offers the rows back under this key
                req.cache_key = (ck, version)
        self.admission.offer(req)  # may raise ServerClosed / Overloaded
        return req.handle

    def run(self, query: str,
            parameters: Optional[Mapping[str, Any]] = None,
            **kwargs) -> Any:
        """submit + result(): the blocking convenience call."""
        return self.submit(query, parameters, **kwargs).result()

    def stats(self) -> Dict[str, Any]:
        """The ``serve.*`` slice of the metrics registry, unprefixed,
        plus the failure-containment summary (``health``, per-family
        breaker states), the per-device fault-domain view
        (``devices``: health, request counts, quarantine/reinstate
        transition counters per replica), the windowed telemetry and SLO
        views (``telemetry`` / ``slo``), micro-batch occupancy
        (``batching``), the per-family compile ledger (``compile``),
        byte footprints (``memory``), and the slow-query count
        (``slow_queries``)."""
        snap = self._registry.snapshot()
        out = {k[len("serve."):]: v for k, v in snap.items()
               if k.startswith("serve.")}
        out["health"] = self.health()
        out["breakers"] = self.breaker.summary()
        out["devices"] = self.devices.summary()
        out["shards"] = self.devices.group_summaries()
        out["compaction"] = (self.compactor.summary()
                             if self.compactor is not None else None)
        out["telemetry"] = self.telemetry.summary()
        out["slo"] = self.telemetry.slo_report()
        out["batching"] = self._batching_stats(snap)
        out["compile"] = self._compile_summary()
        out["memory"] = self._memory_report()
        out["warmup"] = (self.warmer.report()
                         if self.warmer is not None else None)
        out["slow_queries"] = (len(self.slow_log.records())
                               if self.slow_log is not None else None)
        return out

    def _compile_summary(self) -> Optional[Dict[str, Any]]:
        ledger = getattr(self.session, "compile_ledger", None)
        return ledger.summary() if ledger is not None else None

    def _memory_report(self) -> Optional[Dict[str, Any]]:
        ledger = getattr(self.session, "memory_ledger", None)
        return ledger.report() if ledger is not None else None

    def _batching_stats(self, snap: Dict[str, Any]) -> Dict[str, Any]:
        """Micro-batch occupancy (ROADMAP item 2's missing number):
        cumulative members/batch from the ``serve.batch_size`` histogram
        plus the window-averaged occupancy, and — on the TPU backend —
        the fused executor's batch counters."""
        batches = snap.get("serve.batch_size.count", 0)
        members = snap.get("serve.batch_size.sum", 0.0)
        out = {
            "batches": batches,
            "members": int(members),
            "mean_occupancy": round(members / batches, 4) if batches
            else 0.0,
            "window_occupancy": self.telemetry.batch_occupancy(),
        }
        fused = getattr(self.session, "fused", None)
        if fused is not None:
            out["fused_batches"] = fused.batches
            out["fused_batch_members"] = fused.batch_members
        return out

    def health_report(self) -> Dict[str, Any]:
        """Structured serving health: the one-word :meth:`health` string
        plus the windowed SLO evaluation (error-budget burn rates), the
        telemetry window summary, and the breaker / device / compaction
        detail — everything a capacity dashboard or an alerting rule
        needs in one call."""
        return {
            "status": self.health(),
            "slo": self.telemetry.slo_report(),
            "window": self.telemetry.summary(),
            "breakers": self.breaker.summary(),
            "devices": self.devices.summary(),
            # per-group shard health: member ladder states, rebuild
            # counts, paging gauges (serve/shards.py)
            "shards": self.devices.group_summaries(),
            "compaction": (self.compactor.summary()
                           if self.compactor is not None else None),
            # the resource-accounting sections (ISSUE 10): per-family
            # compile ledger, byte footprints, and the observed-stats
            # rollup (the item-4 re-plan signal) — visible without
            # scraping the registry
            "compile": self._compile_summary(),
            "memory": self._memory_report(),
            "opstats": self.session.op_stats.summary(),
            # AOT warmup progress/outcome (serve/warmup.py) — the
            # cold-start story next to the compile ledger it spends
            "warmup": (self.warmer.report()
                       if self.warmer is not None else None),
        }

    def warmup_report(self, families: Optional[List[str]] = None
                      ) -> Dict[str, Any]:
        """Warmup coverage: which hot plan families have NEVER compiled
        on this process — the direct precondition for ROADMAP item 2's
        AOT warmup (warm exactly the cold ones at server start).

        ``families`` defaults to the families the observed-statistics
        store has seen execute (``session.op_stats``); pass an explicit
        list (e.g. the hot families from a previous process's dump) to
        plan a cold start.  A family counts as compiled when the compile
        ledger holds ANY charge for it (cold plan phase included), so on
        a warmed server ``cold_families`` is empty."""
        ledger = getattr(self.session, "compile_ledger", None)
        hot = (list(families) if families is not None
               else self.session.op_stats.families())
        compiled = set(ledger.families()) if ledger is not None else set()
        for group in self.shard_groups:
            # a family that only ever compiled on a shard group (its
            # members' sessions or its cross-shard session) is covered:
            # that is where its traffic executes
            compiled |= group.compiled_families()
        cold = [f for f in hot if f not in compiled]
        return {
            "hot_families": len(hot),
            "compiled_hot_families": len(hot) - len(cold),
            "cold_families": cold,
            "compile_s_by_family": {
                f[:120]: round(ledger.seconds_for(f), 6)
                for f in hot if f in compiled} if ledger is not None
            else {},
        }

    def events(self, event: Optional[str] = None) -> List[Dict[str, Any]]:
        """Snapshot of the structured event log (obs/log.py), optionally
        filtered by event name."""
        return self.event_log.records(event)

    def slow_queries(self) -> List[Dict[str, Any]]:
        """Captured slow-query records (empty when
        ``slow_query_threshold_s`` is unset)."""
        return self.slow_log.records() if self.slow_log is not None else []

    def metrics_text(self) -> str:
        """Prometheus text-exposition of the session registry — the
        windowed ``telemetry.*``/``slo.*`` gauges are registered with
        live callbacks, so the scrape includes them automatically."""
        return self._registry.expose_text()

    def dump_flight_recorder(self, reason: str = "manual"
                             ) -> Dict[str, Any]:
        """On-demand snapshot of the per-request flight ring (plan
        family, device, attempts history, phase timings, outcome per
        record).  Automatic dumps (breaker trip, device quarantine,
        compaction failure) accumulate in
        ``server.telemetry.flight_dumps``."""
        return self.telemetry.dump_flight_recorder(reason)

    def health(self) -> str:
        """One-word serving health: ``healthy`` (all plan families
        closed, all devices serving), ``degraded`` (>= 1 family breaker
        open / half-open OR >= 1 device quarantined / probing — the rest
        keeps serving at reduced capacity), or ``lame-duck`` (shutdown
        began: draining, accepting nothing new).  Per-device detail is
        in :meth:`device_health` / ``stats()["devices"]``."""
        if self.admission.closed:
            return "lame-duck"
        if self.breaker.open_count() or self.devices.quarantined_count():
            return "degraded"
        if any(g.health() != "healthy" for g in self.shard_groups):
            # a degraded group still serves its healthy shards, but
            # capacity planning must see the lost member
            return "degraded"
        if self.compactor is not None and self.compactor.failing:
            # serving still works, but the delta overlay has stopped
            # shrinking — capacity planning must see it
            return "degraded"
        return "healthy"

    def device_health(self) -> Dict[int, str]:
        """Per-device health ladder states:
        ``{device_index: healthy | quarantined | probing}``."""
        return self.devices.health()

    # -- worker pool ---------------------------------------------------

    def _worker_loop(self, replica: DeviceReplica) -> None:
        while True:
            if not self.devices.is_healthy(replica):
                if not self._quarantined_idle(replica):
                    return
                continue
            # blocking take: idle workers sleep on the queue's condition
            # variable (close() wakes them) instead of polling
            batch = self.batcher.next_batch(timeout=None)
            if not batch:
                if self.admission.closed:
                    return
                continue
            try:
                self._execute_batch(batch, replica)
            except BaseException as ex:  # pragma: no cover — last resort
                for req in batch:
                    if not req.handle.done():
                        req.handle._complete(exception=ex)

    def _quarantined_idle(self, replica: DeviceReplica) -> bool:
        """What a worker does while ITS device is quarantined: the other
        workers keep draining the shared queue (capacity degrades to the
        live devices); this one drives the BACKGROUND half-open probe on
        the ladder's cooldown cadence — user requests are never spent as
        probes.  Returns False when the worker should exit (shutdown
        with nothing left this worker could help with)."""
        if self.admission.closed:
            if self.devices.live_count() == 0:
                # nobody can serve the backlog: fail it loudly instead
                # of hanging the drain forever
                for req in self.admission.drain_remaining():
                    self._finish(req, QueryFailed(
                        "shutdown with no healthy devices left to drain "
                        "the queue"))
            if self.admission.depth() == 0:
                return False
        verdict, retry_after = self.devices.try_probe(replica)
        if verdict == TRIAL:
            self.devices.probe(replica)
        else:
            clock.sleep(min(max(retry_after, 1e-3), _PROBE_NAP_S))
        return True

    def _observed(self):
        """Activate the session tracer for worker-side checks (queue
        admission, materialization) so their deadline events land in
        the trace like the engine-side ones do.  Reuses the session's
        own activation helper (one enabled-check contract)."""
        session_observed = getattr(self.session, "_observed", None)
        if session_observed is not None:
            return session_observed()
        return contextlib.nullcontext()

    @contextlib.contextmanager
    def _tracked(self, reqs: List[Request]):
        """In-flight bookkeeping: shutdown(drain=False) cancels these
        scopes so retries and backoff sleeps end promptly."""
        with self._inflight_lock:
            self._inflight.update(reqs)
        try:
            yield
        finally:
            with self._inflight_lock:
                self._inflight.difference_update(reqs)

    def _admit_for_execution(self, batch: List[Request]) -> List[Request]:
        """Drop members that were cancelled or expired while queued and
        complete their handles; record queue wait for the rest."""
        live: List[Request] = []
        now = clock.now()
        for req in batch:
            if req.drop_cancelled():
                self._cancelled.inc()
                continue
            try:
                with self._observed():
                    req.scope.raise_if_done("queued")
            except CancellationError as ex:
                self._count_failure(ex)
                req.handle._complete(exception=ex)
                continue
            wait_s = now - req.enqueued_t
            req.handle.info["queue_wait_s"] = wait_s
            self._queue_wait.observe(wait_s)
            self.telemetry.note_queue_wait(wait_s)
            live.append(req)
        return live

    def _family(self, req: Request):
        """The circuit breaker's key: the EXACT plan-cache key family
        (not the ragged bucket key — a poisoned plan must trip only its
        own family's breaker), or a per-query fallback for requests
        that can never anchor one (EXPLAIN/PROFILE, uncacheable
        graphs)."""
        if req.plan_key is not None:
            return req.plan_key
        return ("solo", req.mode, req.query)

    def _requeue(self, reqs: List[Request]) -> None:
        """Drain claimed-but-unexecuted work back to the dispatcher —
        the quarantine path: another device's worker serves it.  Front
        of the queue, original order preserved."""
        for req in reversed(reqs):
            self.admission.requeue(req)

    def _execute_batch(self, batch: List[Request],
                       replica: DeviceReplica) -> None:
        live = self._admit_for_execution(batch)
        if not live:
            return
        if not self.devices.is_healthy(replica):
            # the device was quarantined between the claim and now (a
            # cross-device retry recorded the tripping failure): hand
            # the whole batch back to the dispatcher
            self._requeue(live)
            return
        # non-replicable graphs (union/catalog) pin to device 0; shard-
        # group graphs redirect to their group whoever claimed them
        replica = self.devices.replica_for(replica, live[0].graph)
        if isinstance(replica, ShardGroup) and \
                not self.devices.is_healthy(replica):
            # the batch's shard GROUP quarantined between admission and
            # the claim: requeue — the in-flight group requests drain
            # back to the dispatcher and complete once the rebuild
            # reinstates it (or expire on their own deadlines); new
            # traffic sheds at submit.  The nap keeps a healthy claimer
            # from hot-spinning on work only the rebuilt member can
            # serve.  Scoped to groups: a batch PINNED to a quarantined
            # device 0 still executes and fails through the retry
            # ladder — the client gets an answer, not an infinite loop.
            self._requeue(live)
            clock.sleep(_PROBE_NAP_S)
            return
        with self._tracked(live):
            self._execute_live(live, replica)

    def _execute_live(self, live: List[Request],
                      replica: DeviceReplica) -> None:
        if len({self._family(r) for r in live}) > 1:
            # ragged bucket batch: members belong to DIFFERENT plan
            # families.  Breaker admission is per member — an open
            # family fast-fails only its own members, a half-open one's
            # member runs alone as that family's probe, and the rest
            # proceed as the shared batch below.
            live = self._admit_ragged(live, replica)
            if not live:
                return
            return self._dispatch_batch(live, replica)
        family = self._family(live[0])
        verdict, retry_after = self.breaker.admit(family)
        if verdict == REJECT:
            # open breaker: fast-fail the whole family without touching
            # the device — a FRESH exception per member (handles must
            # never share one mutable error object)
            for req in live:
                self._finish(req, CircuitOpen(
                    f"plan family circuit breaker is open "
                    f"(retry after {retry_after:.3f}s)",
                    retry_after_s=retry_after))
            return
        if verdict == TRIAL:
            # half-open: exactly ONE probe executes (degraded replan —
            # the cached entry was quarantined when the breaker opened).
            # Its verdict decides the rest of the batch: success closes
            # the breaker and the siblings serve normally below; failure
            # re-opens it and the siblings fast-fail.  A probe that was
            # cancelled / expired decided NOTHING — the next member
            # becomes the probe instead of being failed with a
            # breaker error it never earned.
            healed = False
            while live:
                probe, live = live[0], live[1:]
                probe.handle.info["batch_size"] = 1
                self._batches.inc()
                self._batch_hist.observe(1)
                self.telemetry.note_batch(1)
                outcome = self._execute_single(probe, 1, replica)
                if isinstance(outcome, BaseException):
                    outcome = self._recover(probe, outcome, 1, replica)
                if isinstance(outcome, CancellationError):
                    self.breaker.abort_trial(family)
                    self._finish(probe, outcome)
                    continue
                if isinstance(outcome, BaseException):
                    self.breaker.record_failure(family, outcome)
                    self._finish(probe, outcome)
                    for req in live:
                        self._finish(req, CircuitOpen(
                            f"plan family circuit breaker re-opened by a "
                            f"failed half-open trial (retry after "
                            f"{self.breaker.cooldown_s:.3f}s)",
                            retry_after_s=self.breaker.cooldown_s))
                    # the probe (and its fast-failed siblings) are in the
                    # ring by now: the dump carries their attempt history
                    self.telemetry.auto_dump("breaker_trip")
                    self.event_log.emit(
                        "breaker.trip", request_id=probe.request_id,
                        family=self._family_label(probe),
                        trigger="failed_half_open_trial")
                    return
                self.breaker.record_success(family)
                self._finish(probe, outcome)
                healed = True
                break
            if not live or not healed:
                return
        self._dispatch_batch(live, replica)

    def _admit_ragged(self, live: List[Request],
                      replica: DeviceReplica) -> List[Request]:
        """Per-member breaker admission for a mixed-family (ragged
        bucket) batch: open families fast-fail their members, a
        half-open family's first member executes ALONE as its probe
        (success closes the breaker, failure re-opens it — exactly the
        single-family trial semantics, scoped to one member), everyone
        else is returned for the shared dispatch."""
        kept: List[Request] = []
        for req in live:
            family = self._family(req)
            verdict, retry_after = self.breaker.admit(family)
            if verdict == REJECT:
                self._finish(req, CircuitOpen(
                    f"plan family circuit breaker is open "
                    f"(retry after {retry_after:.3f}s)",
                    retry_after_s=retry_after))
                continue
            if verdict == TRIAL:
                req.handle.info["batch_size"] = 1
                self._batches.inc()
                self._batch_hist.observe(1)
                self.telemetry.note_batch(1)
                outcome = self._execute_single(req, 1, replica)
                if isinstance(outcome, BaseException):
                    outcome = self._recover(req, outcome, 1, replica)
                if isinstance(outcome, CancellationError):
                    self.breaker.abort_trial(family)
                elif isinstance(outcome, BaseException):
                    self.breaker.record_failure(family, outcome)
                    self._finish(req, outcome)
                    self.telemetry.auto_dump("breaker_trip")
                    self.event_log.emit(
                        "breaker.trip", request_id=req.request_id,
                        family=self._family_label(req),
                        trigger="failed_half_open_trial")
                    continue
                else:
                    self.breaker.record_success(family)
                self._finish(req, outcome)
                continue
            kept.append(req)
        return kept

    def _dispatch_batch(self, live: List[Request],
                        replica: DeviceReplica) -> None:
        """One shared device dispatch of breaker-admitted requests, with
        per-member outcome bookkeeping (breaker records land on each
        member's OWN plan family — a ragged batch mixes several)."""
        n = len(live)
        self._batches.inc()
        self._batch_hist.observe(n)
        self.telemetry.note_batch(n)
        for req in live:
            req.handle.info["batch_size"] = n
            req.handle.info["device"] = replica.index
        with replica.lock:
            # service time starts INSIDE the lock: time spent queued
            # behind another batch on this device's stream is queueing,
            # not service, and must not inflate the retry_after estimator
            t0 = clock.now()
            if n > 1:
                try:
                    with replica.activate():
                        graph = replica.graph_for(live[0].graph)
                        outcomes = replica.session.cypher_batch(
                            graph, [(r.query, r.params) for r in live],
                            scopes=[r.scope for r in live])
                except BaseException as ex:  # replication / setup failed
                    outcomes = [ex] + [_fresh_copy(ex)
                                       for _ in live[1:]]
            else:
                req = live[0]
                try:
                    with cancel_scope(req.scope), replica.activate():
                        graph = replica.graph_for(req.graph)
                        outcomes = [replica.session.cypher_on_graph(
                            graph, req.query, req.params)]
                except BaseException as ex:
                    outcomes = [ex]
            exec_s = clock.now() - t0
        # feed the admission controller's retry_after estimator and the
        # telemetry window (service-time + per-device utilization)
        self.admission.observe_service(exec_s / n)
        self.telemetry.note_service(exec_s / n)
        self.telemetry.note_device_busy(replica.index, exec_s)
        # per-device fault-domain bookkeeping on the RAW outcomes: the
        # device that produced a failure owns it, whatever device the
        # recovery below lands on
        for outcome in outcomes:
            if isinstance(outcome, BaseException):
                attribute_device(outcome, replica.index)
        self._note_device_outcomes(replica, outcomes)
        # successful members complete FIRST: a failed sibling's recovery
        # (backoff sleeps + serialized re-executions) must not sit
        # between a finished result and the client waiting on it
        pending = []
        for req, outcome in zip(live, outcomes):
            if isinstance(outcome, BaseException):
                pending.append((req, outcome))
            else:
                self.breaker.record_success(self._family(req))
                self._finish(req, outcome)
        for req, exc in pending:
            outcome = self._recover(req, exc, 0, replica)
            # breaker bookkeeping on the request's FINAL outcome — onto
            # the member's OWN plan family; cancellation/deadline expiry
            # is the budget's verdict, not the family's
            tripped = False
            if isinstance(outcome, BaseException):
                if not isinstance(outcome, CancellationError):
                    tripped = self.breaker.record_failure(
                        self._family(req), outcome)
                    if tripped and not req.handle.info.get("quarantined"):
                        # this failure tripped the family open: evict its
                        # shared cached state so the half-open trial (and
                        # the eventual recovery) re-plans from scratch —
                        # unless the recovery ladder already did
                        self._quarantine(req, replica)
            else:
                self.breaker.record_success(self._family(req))
            self._finish(req, outcome)
            if tripped:
                # AFTER the finish: the tripping request is in the
                # flight ring, so the dump carries its attempt history
                self.telemetry.auto_dump("breaker_trip")
                self.event_log.emit(
                    "breaker.trip", request_id=req.request_id,
                    family=self._family_label(req),
                    trigger="failure_threshold")

    def _note_device_outcomes(self, replica: DeviceReplica,
                              outcomes: List[Any]) -> None:
        """Feed one batch of raw outcomes to the device health ladder.
        Cancellation/deadline expiry is the budget's verdict — it says
        nothing about the device."""
        for outcome in outcomes:
            replica.note(requests=1)
            if isinstance(outcome, CancellationError):
                continue
            if isinstance(outcome, BaseException):
                tripped = self.devices.record_failure(replica, outcome)
                if tripped and isinstance(replica, ShardGroup):
                    # a member (or the whole group) tripped its ladder:
                    # black-box it — the group keeps serving healthy
                    # shards while the background rebuild runs
                    from caps_tpu.serve.shards import member_of
                    self.telemetry.auto_dump(f"shard_{tripped}_quarantine")
                    self.event_log.emit(
                        "shard.quarantine", request_id=None, family=None,
                        group=replica.name, level=tripped,
                        member=member_of(outcome),
                        error=type(outcome).__name__)
                elif tripped:
                    # this failure quarantined the device: black-box the
                    # in-flight picture for the postmortem
                    self.telemetry.auto_dump("device_quarantine")
                    self.event_log.emit(
                        "device.quarantine", request_id=None, family=None,
                        device=replica.index,
                        error=type(outcome).__name__)
            else:
                self.devices.record_success(replica)

    # -- failure containment (retry / quarantine / degraded ladder) ----

    def _recover(self, req: Request, exc: BaseException, level: int,
                 replica: DeviceReplica) -> Any:
        """Containment ladder for ONE failed request: classify the
        error, then either return it (fatal / cancelled), retry with
        deadline-charged backoff on a DIFFERENT healthy device
        (transient — the failed device may be the problem; a lone
        device retries on itself), or quarantine the cached plan and
        climb the degraded ladder on the same device (poisoned).
        Returns the final outcome — a CypherResult or the exception to
        complete the handle with.  Never raises."""
        policy = self.retry_policy
        attempts = [self._attempt_entry(exc, level, replica)]
        executions = 1
        #: every device index that failed during THIS recovery, in
        #: order: with several members unhealthy mid-window a later
        #: retry must exclude ALL of them, not just the latest
        #: (ReplicaSet.retry_target takes the whole collection)
        failed_devices = [replica.index]
        current: BaseException = exc
        while True:
            if isinstance(current, CancellationError):
                break  # the budget's verdict stands
            kind = attempts[-1]["classified"]
            if kind == FATAL:
                break
            if kind == TRANSIENT:
                if executions >= policy.max_attempts:
                    current = QueryFailed(
                        f"still failing transiently after {executions} "
                        f"attempts: {type(current).__name__}: {current}",
                        attempts=tuple(attempts),
                        retry_after_s=policy.backoff_s(executions,
                                                       req.request_id))
                    break
                backoff = policy.backoff_s(executions, req.request_id)
                if not policy.budget_allows(req.scope.remaining(), backoff):
                    # a retry never fires when the remaining deadline
                    # budget cannot cover the next backoff: give up NOW
                    # with the backoff as the client's retry hint
                    current = QueryFailed(
                        f"transient failure, but remaining deadline "
                        f"budget < next backoff ({backoff:.3f}s): "
                        f"{type(current).__name__}: {current}",
                        attempts=tuple(attempts), retry_after_s=backoff)
                    break
                attempts[-1]["backoff_s"] = backoff
                self._retries.inc()
                self.telemetry.note_retry()
                tracer = self.session.tracer
                if tracer.enabled:
                    tracer.event("retry.attempt", attempt=executions,
                                 backoff_s=backoff, mode=_LADDER[level],
                                 device=replica.index,
                                 error=type(current).__name__)
                policy.sleep(backoff, scope=req.scope)
                if req.scope.cancelled:
                    # cancel() fired DURING the backoff: the wait woke
                    # immediately (serve/retry.py) and the request ends
                    # here — no doomed re-execution, no burned sleep
                    current = Cancelled(phase="backoff")
                    break
                # device failover: re-execute on a different healthy
                # device when one exists — routed through replica_for,
                # so non-replicable graphs keep retrying on device 0
                # and shard-group graphs come back to their group
                replica = self.devices.replica_for(
                    self.devices.retry_target(
                        exclude_index=failed_devices), req.graph)
            else:  # POISONED_PLAN: quarantine once, then climb the ladder
                if level >= len(_LADDER) - 1:
                    current = QueryFailed(
                        f"degraded ladder exhausted after {executions} "
                        f"attempts: {type(current).__name__}: {current}",
                        attempts=tuple(attempts))
                    break
                if level == 0:
                    self._quarantine(req, replica)
                level += 1
                self._degraded_runs.inc()
            executions += 1
            outcome = self._execute_single(req, level, replica)
            if not isinstance(outcome, BaseException):
                attempts.append({"mode": _LADDER[level], "ok": True,
                                 "device": replica.index})
                req.handle.info["attempts"] = attempts
                return outcome
            attempts.append(self._attempt_entry(outcome, level, replica))
            if replica.index not in failed_devices:
                failed_devices.append(replica.index)
            current = outcome
        req.handle.info["attempts"] = attempts
        return current

    @staticmethod
    def _attempt_entry(exc: BaseException, level: int,
                       replica: DeviceReplica) -> Dict[str, Any]:
        """One attempt-history record.  A fresh dict per attempt per
        request — failure context lives HERE, never as mutations of the
        exception object (which a badly-behaved injector might share
        across batch members)."""
        dev = device_of(exc)
        entry = {"mode": _LADDER[level], "error": type(exc).__name__,
                 "message": str(exc)[:200], "classified": classify(exc),
                 "device": replica.index if dev is None else dev}
        failed_op = getattr(exc, "caps_failed_op", None)
        if failed_op is not None:
            entry["op"] = failed_op
        return entry

    def _execute_single(self, req: Request, level: int,
                        replica: DeviceReplica) -> Any:
        """One (re-)execution of a single request at a ladder level on
        ``replica``'s device.  Returns the result or the raised
        exception; device-ladder bookkeeping included."""
        with replica.lock:
            t0 = clock.now()
            try:
                with cancel_scope(req.scope), replica.activate():
                    graph = replica.graph_for(req.graph)
                    if level == 0:
                        out: Any = replica.session.cypher_on_graph(
                            graph, req.query, req.params)
                    else:
                        out = replica.session.cypher_degraded(
                            graph, req.query, req.params,
                            no_plan_cache=True, no_fused=(level >= 2))
            except BaseException as ex:
                attribute_device(ex, replica.index)
                out = ex
            finally:
                exec_s = clock.now() - t0
        self.admission.observe_service(exec_s)
        self.telemetry.note_service(exec_s)
        self.telemetry.note_device_busy(replica.index, exec_s)
        self._note_device_outcomes(replica, [out])
        return out

    def _quarantine(self, req: Request, replica: DeviceReplica) -> None:
        """Evict the request family's shared cached state ON THE REPLICA
        THAT SERVED IT: that session's plan-cache entry
        (relational/plan_cache.py) and, on the TPU backend, its fused
        size memos (backends/tpu/fused.py) — a poisoned entry must not
        keep failing every future hit, and per-device caches mean the
        eviction never touches another device's compiled state.
        Stamped on the handle so one request quarantines at most once
        (the ladder and a breaker trip must not double-count)."""
        req.handle.info["quarantined"] = True
        self._quarantines.inc()
        if self.result_cache is not None and req.plan_key is not None:
            # a quarantined family may have produced poisoned rows — its
            # cached results (and every shared memoized intermediate)
            # must go with the plan (relational/result_cache.py)
            self.result_cache.evict_family(req.plan_key[1])
        if isinstance(replica, ShardGroup):
            # group-routed: evict on the session that actually served
            # this family (owning member or the cross-shard session)
            replica.quarantine_family(req.query, req.params)
            self.event_log.emit(
                "plan.quarantine", request_id=req.request_id,
                family=self._family_label(req), device=replica.index)
            return
        session = replica.session
        try:
            graph = replica.graph_for(req.graph)
        except Exception:  # pragma: no cover — containment must not fail
            return
        # the shared eviction sequence (serve/failure.py): plan-cache
        # quarantine + fused memo drop under the replica's exec lock
        quarantine_plan_state(session, graph, req.query, req.params,
                              exec_lock=replica.lock)
        tracer = session.tracer
        if tracer.enabled:
            tracer.event("plan.quarantined", query=req.query,
                         device=replica.index)
        self.event_log.emit(
            "plan.quarantine", request_id=req.request_id,
            family=self._family_label(req), device=replica.index)

    def _finish(self, req: Request, outcome: Any) -> None:
        """Materialize (deadline-checked) and complete one handle."""
        if isinstance(outcome, BaseException):
            self._count_failure(outcome)
            self._flight(req, outcome)
            req.handle._complete(exception=outcome)
            return
        rows = None
        try:
            with cancel_scope(req.scope), self._observed():
                if self.config.materialize:
                    req.scope.raise_if_done("materialize")
                    rows = outcome.to_maps()
                    req.scope.raise_if_done("materialize")
        except BaseException as ex:
            self._count_failure(ex)
            self._flight(req, ex)
            req.handle._complete(exception=ex)
            return
        self._note_ledger(req, outcome)
        self._store_result(req, rows)
        req.handle.info["latency_s"] = req.scope.elapsed()
        self._latency.observe(req.handle.info["latency_s"])
        self._completed.inc()
        self._flight(req, None, outcome)
        req.handle._complete(result=outcome, rows=rows)

    def _serve_cache_hit(self, req: Request, rows: list) -> None:
        """Complete a request AT ADMISSION from the result cache: no
        worker slot, no device dwell, no batch window.  The flight
        record stamps ``outcome="cache_hit"`` / ``phase="cache"`` so the
        black box distinguishes memory-served reads from device-served
        ones, and windowed telemetry counts the hit as an ok result
        (hits ARE served traffic — qps/availability must see them)."""
        info = req.handle.info
        try:
            # a zero/negative deadline budget expires even here
            req.scope.raise_if_done("cache")
        except CancellationError as ex:
            self._count_failure(ex)
            self._flight(req, ex)
            req.handle._complete(exception=ex)
            return
        info["cache"] = "hit"
        info["queue_wait_s"] = 0.0
        info["ledger"] = {"bytes_in": 0, "bytes_out": 0,
                          "compile_s": 0.0, "peak_rows": len(rows)}
        latency_s = req.scope.elapsed()
        info["latency_s"] = latency_s
        self._latency.observe(latency_s)
        self._completed.inc()
        family = self._family_label(req)
        self.telemetry.note_result(family, latency_s, "ok")
        rec: Dict[str, Any] = {
            "request_id": req.request_id,
            "family": family,
            "priority": req.priority,
            "device": None,
            "batch_size": None,
            "queue_wait_s": 0.0,
            "latency_s": round(latency_s, 6),
            "phase": "cache",
            "outcome": "cache_hit",
            "ledger": info["ledger"],
        }
        if info.get("snapshot_version") is not None:
            rec["snapshot_version"] = info["snapshot_version"]
        self.telemetry.recorder.record(rec)
        req.handle._complete(result=CachedRows(rows), rows=rows)

    def _observed_service_s(self, req: Request) -> float:
        """Observed per-execution seconds for this request's plan family
        (session.op_stats) — the admission benefit estimate.  Falls back
        to the request's own measured latency when the family has no
        folded statistics yet."""
        try:
            stats = self.session.op_stats.stats(self._family_label(req))
            total = execs = 0.0
            for entry in stats.values():
                total += float(entry.get("wall_s_total") or 0.0)
                execs = max(execs, float(entry.get("executions") or 0))
            if execs > 0 and total > 0:
                return total / execs
        except Exception:  # pragma: no cover — estimation must not fail
            pass
        return max(0.0, req.scope.elapsed())

    def _store_result(self, req: Request, rows: Optional[list]) -> None:
        """Completion-side feed: offer the materialized rows back to the
        result cache under the key stamped at admission (cost-aware —
        the cache decides)."""
        if self.result_cache is None or req.cache_key is None \
                or rows is None:
            return
        key, version = req.cache_key
        ledger = req.handle.info.get("ledger") or {}
        nbytes = int(ledger.get("bytes_out") or 0)
        self.result_cache.offer(key, version, rows, nbytes=nbytes,
                                service_s=self._observed_service_s(req))

    def _note_ledger(self, req: Request, result: Any) -> None:
        """The per-request resource ledger (ISSUE 10): bytes pulled
        through memory, result bytes out, compile seconds charged to
        this execution (obs/compile.py via the session's per-query
        stamp), and peak operator cardinality — stamped on the handle
        and carried by the flight-recorder and slow-query records.
        Compile charges also land in the telemetry window and the
        structured event log."""
        m = getattr(result, "metrics", None) or {}
        compile_s = float(m.get("compile_s_charged") or 0.0)
        peak = 0
        for entry in m.get("operators") or ():
            r = entry.get("rows") or 0
            if r > peak:
                peak = r
        if not peak:
            peak = int(m.get("rows") or 0)
        bytes_out = 0
        records = getattr(result, "records", None)
        if records is not None:
            try:
                bytes_out = int(records.table.nbytes)
            except Exception:  # pragma: no cover — accounting only
                bytes_out = 0
        req.handle.info["ledger"] = {
            "bytes_in": int(m.get("bytes_touched") or 0),
            "bytes_out": bytes_out,
            "compile_s": round(compile_s, 9),
            "peak_rows": int(peak),
        }
        if compile_s > 0.0:
            self.telemetry.note_compile(compile_s)
            self.event_log.emit(
                "compile.charged", request_id=req.request_id,
                family=self._family_label(req),
                seconds=round(compile_s, 6),
                snapshot_version=req.handle.info.get("snapshot_version"))

    def _on_replan(self, event: str, info: Dict[str, Any]) -> None:
        """Session re-plan transition → structured event (no request to
        correlate: the trigger is an aggregate over executions, not one
        request).  ``replan.triggered`` carries the quarantined-plan
        count; ``replan.completed`` the re-plan seconds and the new
        plan's calibrated root estimate."""
        fields = {k: v for k, v in info.items() if k != "family"}
        self.event_log.emit(event, request_id=None,
                            family=str(info.get("family"))[:120],
                            **fields)

    def _compaction_failed(self, ex: BaseException) -> None:
        """Compaction-failure incident hook (serve/compaction.py): flight
        dump plus a structured event (no request to correlate — the
        fields are explicit Nones, never absent)."""
        self.telemetry.auto_dump("compaction_failure")
        self.event_log.emit(
            "compaction.failure", request_id=None, family=None,
            error=f"{type(ex).__name__}: {str(ex)[:200]}")

    def _family_label(self, req: Request) -> str:
        """Human-meaningful plan-family label for telemetry and the
        flight recorder: the normalized query text for batchable
        requests (the batch key's middle element), else mode + raw
        text."""
        if req.plan_key is not None:
            return str(req.plan_key[1])[:120]
        return f"{req.mode or 'solo'}:{req.query[:100]}"

    def _flight(self, req: Request, exc: Optional[BaseException],
                result: Any = None) -> None:
        """One finished request's black-box record + windowed outcome
        note.  Cancellation AND deadline expiry count as aborts
        (excluded from availability — the budget's verdict, not the
        server's, same exemption the breaker and device ladder apply);
        every other failure counts against availability.  Every record
        carries the request's resource ledger; over-threshold requests
        additionally capture plan text + per-op stats in the slow-query
        log (same record shape, so dumps and slow entries merge)."""
        info = req.handle.info
        latency_s = req.scope.elapsed()
        family = self._family_label(req)
        if exc is None:
            kind = "ok"
        elif isinstance(exc, CancellationError):
            kind = "abort"
        else:
            kind = "error"
        self.telemetry.note_result(family, latency_s, kind)
        rec: Dict[str, Any] = {
            "request_id": req.request_id,
            "family": family,
            "priority": req.priority,
            "device": info.get("device"),
            "batch_size": info.get("batch_size"),
            "queue_wait_s": info.get("queue_wait_s"),
            "latency_s": round(latency_s, 6),
            "phase": req.scope.phase,
            "outcome": "ok" if exc is None else type(exc).__name__,
            "ledger": info.get("ledger", {"bytes_in": 0, "bytes_out": 0,
                                          "compile_s": 0.0,
                                          "peak_rows": 0}),
        }
        if info.get("snapshot_version") is not None:
            rec["snapshot_version"] = info["snapshot_version"]
        if exc is not None:
            rec["error"] = str(exc)[:200]
        if info.get("attempts"):
            rec["attempts"] = info["attempts"]
        if info.get("quarantined"):
            rec["quarantined"] = True
        self.telemetry.recorder.record(rec)
        if self.slow_log is not None:
            plan = operators = None
            if result is not None:
                plans = getattr(result, "plans", None) or {}
                plan = plans.get("relational") or plans.get("ir")
                m = getattr(result, "metrics", None) or {}
                operators = [dict(e)
                             for e in (m.get("operators") or ())][:64]
            self.slow_log.consider(rec, plan=plan, operators=operators)

    def _count_failure(self, ex: BaseException) -> None:
        if isinstance(ex, DeadlineExceeded):
            self._deadline_exceeded.inc()
        elif isinstance(ex, Cancelled):
            self._cancelled.inc()
        else:
            self._failed.inc()

"""QueryServer: the multi-client query-serving tier.

Turns a single engine session into a service: clients ``submit()``
queries from any thread and get Future-style handles back; a worker
pool executes them through the session's prepared-plan path with

* **admission control** — a bounded priority queue that sheds load with
  a typed ``Overloaded`` (retry_after hint) instead of queuing
  unboundedly (serve/admission.py);
* **micro-batching** — compatible in-flight requests (same normalized
  query / plan-cache key family) execute as one batched pass over the
  cached plan (serve/batcher.py, ``session.cypher_batch``);
* **deadlines + cooperative cancellation** — per-request budgets
  checked at engine phase boundaries (serve/deadline.py), with the
  expiry phase attributed in the error and the trace.

Execution is serialized through one lock by default: the engine drives
ONE device, and on TPU throughput comes from keeping that device's
dispatch stream dense (fused replay + batching), not from concurrent
host threads racing into it.  Workers still overlap usefully — while
one executes, others admit, time out, and materialize results.  The
engine-side structures a serving session shares across threads (plan
cache, catalog, metrics registry) are individually locked, so the
submit path never contends with execution.

Serving metrics land in the session's registry under ``serve.*``
(queue depth gauge, admitted/shed/completed counters, latency +
queue-wait + batch-size histograms) and show up in
``session.metrics_snapshot()`` next to everything else.
"""
from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Any, Dict, List, Mapping, Optional

from caps_tpu.obs import clock
from caps_tpu.serve import batcher as _batcher
from caps_tpu.serve.admission import AdmissionController
from caps_tpu.serve.batcher import MicroBatcher
from caps_tpu.serve.breaker import REJECT, TRIAL, CircuitBreaker
from caps_tpu.serve.deadline import CancelScope, cancel_scope
from caps_tpu.serve.errors import (Cancelled, CancellationError, CircuitOpen,
                                   DeadlineExceeded, QueryFailed,
                                   ServerClosed)
from caps_tpu.serve.failure import FATAL, TRANSIENT, classify
from caps_tpu.serve.request import INTERACTIVE, QueryHandle, Request
from caps_tpu.serve.retry import RetryPolicy

_UNSET = object()

#: batch-size histogram buckets (powers of two up to the queue bound)
_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)

#: degraded execution ladder (failure containment): 0 = the normal
#: serving path (cached plan, fused TPU replay); 1 = plan-cache bypass —
#: a fresh plan, fused execution re-records from scratch; 2 = fresh plan
#: AND per-operator unfused execution (no shared cached state at all).
_LADDER = ("fused", "replan", "unfused")

_session_locks_guard = threading.Lock()


def _session_exec_lock(session) -> threading.Lock:
    """The ONE execution lock of a session, attached on first use: every
    QueryServer over the same session must serialize through the same
    lock (the engine's execution state — fused record/replay activation,
    profiling flags — is per-session, not per-server)."""
    lock = getattr(session, "_serve_exec_lock", None)
    if lock is None:
        with _session_locks_guard:
            lock = getattr(session, "_serve_exec_lock", None)
            if lock is None:
                lock = threading.Lock()
                session._serve_exec_lock = lock
    return lock


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    #: worker threads; execution itself is serialized (one device
    #: stream), extra workers overlap admission and materialization
    workers: int = 2
    #: global queue bound — beyond it submit() sheds with Overloaded
    max_queue: int = 64
    #: optional per-priority queue caps, e.g. {BATCH: 16} keeps
    #: background traffic from filling the queue
    per_priority_limits: Optional[Dict[int, int]] = None
    #: max requests coalesced into one micro-batch
    max_batch: int = 8
    #: seconds a batch leader waits for followers (0 = batch only what
    #: is already queued — no added leader latency)
    batch_window_s: float = 0.0
    #: default per-request budget (None = no deadline)
    default_deadline_s: Optional[float] = None
    default_priority: int = INTERACTIVE
    #: materialize rows on the worker (handle.rows() is then free)
    materialize: bool = True
    #: transient-error retry (serve/retry.py): exponential backoff with
    #: deterministic jitter, charged against the request's deadline
    retry: RetryPolicy = dataclasses.field(default_factory=RetryPolicy)
    #: consecutive request-level failures (whole containment ladder
    #: exhausted) before a plan family's circuit breaker opens
    breaker_threshold: int = 3
    #: seconds an open breaker fast-fails a family before letting one
    #: half-open trial through
    breaker_cooldown_s: float = 5.0


class QueryServer:
    """Concurrent serving facade over one session.

    >>> server = QueryServer(session, graph=g)
    >>> h = server.submit("MATCH (n:Person) WHERE n.age > $a "
    ...                   "RETURN n.name AS name", {"a": 30})
    >>> h.rows()
    [...]
    >>> server.shutdown()
    """

    def __init__(self, session, graph=None,
                 config: Optional[ServerConfig] = None, start: bool = True):
        self.session = session
        self.config = config or ServerConfig()
        self._default_graph = graph if graph is not None \
            else session._ambient
        registry = session.metrics_registry
        self.admission = AdmissionController(
            registry, max_queue=self.config.max_queue,
            per_priority_limits=self.config.per_priority_limits,
            workers=self.config.workers)
        self.batcher = MicroBatcher(self.admission,
                                    max_batch=self.config.max_batch,
                                    window_s=self.config.batch_window_s)
        self.retry_policy = self.config.retry or RetryPolicy()
        self.breaker = CircuitBreaker(
            registry, failure_threshold=self.config.breaker_threshold,
            cooldown_s=self.config.breaker_cooldown_s)
        # ONE device stream: execution is serialized; workers overlap
        # on admission, timeout handling, and materialization.  The
        # lock is per-SESSION (shared by every server over it).
        self._exec_lock = _session_exec_lock(session)
        self._completed = registry.counter("serve.completed")
        self._failed = registry.counter("serve.failed")
        self._cancelled = registry.counter("serve.cancelled")
        self._deadline_exceeded = registry.counter("serve.deadline_exceeded")
        self._batches = registry.counter("serve.batches")
        self._retries = registry.counter("serve.retries")
        self._quarantines = registry.counter("serve.quarantined")
        self._degraded_runs = registry.counter("serve.degraded_exec")
        self._batch_hist = registry.histogram("serve.batch_size",
                                              buckets=_BATCH_BUCKETS)
        self._latency = registry.histogram("serve.latency_s")
        self._queue_wait = registry.histogram("serve.queue_wait_s")
        self._registry = registry
        self._threads: List[threading.Thread] = []
        self._started = False
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "QueryServer":
        """Start the worker pool (idempotent).  ``start=False`` at
        construction lets tests and benchmarks pre-load the queue so the
        first batch demonstrably coalesces."""
        if self._started:
            return self
        self._started = True
        for i in range(max(1, self.config.workers)):
            t = threading.Thread(target=self._worker_loop,
                                 name=f"caps-tpu-serve-{i}", daemon=True)
            self._threads.append(t)
            t.start()
        return self

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> bool:
        """Stop accepting work.  ``drain=True`` (default) completes
        everything already queued before workers exit; ``drain=False``
        fails queued requests with ``Cancelled``.  ``timeout`` bounds
        the TOTAL wait for workers; returns False (with the worker
        handles retained, so a later call can finish the join) when
        they are still running at the deadline."""
        self.admission.close()
        if not drain:
            for req in self.admission.drain_remaining():
                req.scope.cancel()
                req.handle._complete(
                    exception=Cancelled(phase="queued"))
                self._cancelled.inc()
        elif not self._started and self.admission.depth() > 0:
            # never-started server with a backlog: draining means the
            # queued work still completes — spin the workers up; they
            # exit once the (closed) queue is empty
            self.start()
        if not self._started:
            return True
        deadline = None if timeout is None else clock.now() + timeout
        for t in self._threads:
            t.join(None if deadline is None
                   else max(0.0, deadline - clock.now()))
        still_running = [t for t in self._threads if t.is_alive()]
        self._threads = still_running
        return not still_running

    def __enter__(self) -> "QueryServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=True)

    # -- client API ----------------------------------------------------

    def submit(self, query: str,
               parameters: Optional[Mapping[str, Any]] = None, *,
               graph=None, deadline_s: Any = _UNSET,
               priority: Optional[int] = None) -> QueryHandle:
        """Enqueue a query; returns immediately with a handle.

        Raises :class:`ServerClosed` after shutdown began and
        :class:`Overloaded` when admission sheds the request —
        synchronous, so the caller's backpressure is immediate.
        ``deadline_s`` is the request's total budget (queue wait
        included); ``deadline_s=None`` explicitly disables the
        server-default deadline for this request."""
        if deadline_s is _UNSET:
            deadline_s = self.config.default_deadline_s
        if priority is None:
            priority = self.config.default_priority
        graph = graph if graph is not None else self._default_graph
        params = dict(parameters or {})
        scope = CancelScope(budget_s=deadline_s)
        mode, key = _batcher.batch_key(graph, query, params)
        req = Request(query, params, graph, priority, scope, key, mode)
        self.admission.offer(req)  # may raise ServerClosed / Overloaded
        return req.handle

    def run(self, query: str,
            parameters: Optional[Mapping[str, Any]] = None,
            **kwargs) -> Any:
        """submit + result(): the blocking convenience call."""
        return self.submit(query, parameters, **kwargs).result()

    def stats(self) -> Dict[str, Any]:
        """The ``serve.*`` slice of the metrics registry, unprefixed,
        plus the failure-containment summary (``health``, per-family
        breaker states)."""
        snap = self._registry.snapshot()
        out = {k[len("serve."):]: v for k, v in snap.items()
               if k.startswith("serve.")}
        out["health"] = self.health()
        out["breakers"] = self.breaker.summary()
        return out

    def health(self) -> str:
        """One-word serving health: ``healthy`` (all families closed),
        ``degraded`` (>= 1 family's breaker open / half-open — those
        families fast-fail or probe while everything else serves), or
        ``lame-duck`` (shutdown began: draining, accepting nothing
        new)."""
        if self.admission.closed:
            return "lame-duck"
        return "degraded" if self.breaker.open_count() else "healthy"

    # -- worker pool ---------------------------------------------------

    def _worker_loop(self) -> None:
        while True:
            # blocking take: idle workers sleep on the queue's condition
            # variable (close() wakes them) instead of polling
            batch = self.batcher.next_batch(timeout=None)
            if not batch:
                if self.admission.closed:
                    return
                continue
            try:
                self._execute_batch(batch)
            except BaseException as ex:  # pragma: no cover — last resort
                for req in batch:
                    if not req.handle.done():
                        req.handle._complete(exception=ex)

    def _observed(self):
        """Activate the session tracer for worker-side checks (queue
        admission, materialization) so their deadline events land in
        the trace like the engine-side ones do.  Reuses the session's
        own activation helper (one enabled-check contract)."""
        session_observed = getattr(self.session, "_observed", None)
        if session_observed is not None:
            return session_observed()
        return contextlib.nullcontext()

    def _admit_for_execution(self, batch: List[Request]) -> List[Request]:
        """Drop members that were cancelled or expired while queued and
        complete their handles; record queue wait for the rest."""
        live: List[Request] = []
        now = clock.now()
        for req in batch:
            if req.drop_cancelled():
                self._cancelled.inc()
                continue
            try:
                with self._observed():
                    req.scope.raise_if_done("queued")
            except CancellationError as ex:
                self._count_failure(ex)
                req.handle._complete(exception=ex)
                continue
            wait_s = now - req.enqueued_t
            req.handle.info["queue_wait_s"] = wait_s
            self._queue_wait.observe(wait_s)
            live.append(req)
        return live

    def _family(self, req: Request):
        """The circuit breaker's key: the plan-cache key family the
        micro-batcher groups by, or a per-query fallback for requests
        that can never batch (EXPLAIN/PROFILE, uncacheable graphs)."""
        if req.batch_key is not None:
            return req.batch_key
        return ("solo", req.mode, req.query)

    def _execute_batch(self, batch: List[Request]) -> None:
        live = self._admit_for_execution(batch)
        if not live:
            return
        family = self._family(live[0])
        verdict, retry_after = self.breaker.admit(family)
        if verdict == REJECT:
            # open breaker: fast-fail the whole family without touching
            # the device — a FRESH exception per member (handles must
            # never share one mutable error object)
            for req in live:
                self._finish(req, CircuitOpen(
                    f"plan family circuit breaker is open "
                    f"(retry after {retry_after:.3f}s)",
                    retry_after_s=retry_after))
            return
        if verdict == TRIAL:
            # half-open: exactly ONE probe executes (degraded replan —
            # the cached entry was quarantined when the breaker opened).
            # Its verdict decides the rest of the batch: success closes
            # the breaker and the siblings serve normally below; failure
            # re-opens it and the siblings fast-fail.  A probe that was
            # cancelled / expired decided NOTHING — the next member
            # becomes the probe instead of being failed with a
            # breaker error it never earned.
            healed = False
            while live:
                probe, live = live[0], live[1:]
                probe.handle.info["batch_size"] = 1
                self._batches.inc()
                self._batch_hist.observe(1)
                outcome = self._execute_single(probe, level=1)
                if isinstance(outcome, BaseException):
                    outcome = self._recover(probe, outcome, 1)
                if isinstance(outcome, CancellationError):
                    self.breaker.abort_trial(family)
                    self._finish(probe, outcome)
                    continue
                if isinstance(outcome, BaseException):
                    self.breaker.record_failure(family, outcome)
                    self._finish(probe, outcome)
                    for req in live:
                        self._finish(req, CircuitOpen(
                            f"plan family circuit breaker re-opened by a "
                            f"failed half-open trial (retry after "
                            f"{self.breaker.cooldown_s:.3f}s)",
                            retry_after_s=self.breaker.cooldown_s))
                    return
                self.breaker.record_success(family)
                self._finish(probe, outcome)
                healed = True
                break
            if not live or not healed:
                return
        n = len(live)
        self._batches.inc()
        self._batch_hist.observe(n)
        for req in live:
            req.handle.info["batch_size"] = n
        with self._exec_lock:
            # service time starts INSIDE the lock: time spent queued
            # behind another worker's batch is queueing, not service,
            # and must not inflate the retry_after estimator
            t0 = clock.now()
            if n > 1:
                outcomes = self.session.cypher_batch(
                    live[0].graph, [(r.query, r.params) for r in live],
                    scopes=[r.scope for r in live])
            else:
                req = live[0]
                try:
                    with cancel_scope(req.scope):
                        outcomes = [self.session.cypher_on_graph(
                            req.graph, req.query, req.params)]
                except BaseException as ex:
                    outcomes = [ex]
            exec_s = clock.now() - t0
        # feed the admission controller's retry_after estimator
        self.admission.observe_service(exec_s / n)
        # successful members complete FIRST: a failed sibling's recovery
        # (backoff sleeps + serialized re-executions) must not sit
        # between a finished result and the client waiting on it
        pending = []
        for req, outcome in zip(live, outcomes):
            if isinstance(outcome, BaseException):
                pending.append((req, outcome))
            else:
                self.breaker.record_success(family)
                self._finish(req, outcome)
        for req, exc in pending:
            outcome = self._recover(req, exc, 0)
            # breaker bookkeeping on the request's FINAL outcome;
            # cancellation/deadline expiry is the budget's verdict, not
            # the family's
            if isinstance(outcome, BaseException):
                if not isinstance(outcome, CancellationError):
                    if self.breaker.record_failure(family, outcome) \
                            and not req.handle.info.get("quarantined"):
                        # this failure tripped the family open: evict its
                        # shared cached state so the half-open trial (and
                        # the eventual recovery) re-plans from scratch —
                        # unless the recovery ladder already did
                        self._quarantine(req)
            else:
                self.breaker.record_success(family)
            self._finish(req, outcome)

    # -- failure containment (retry / quarantine / degraded ladder) ----

    def _recover(self, req: Request, exc: BaseException, level: int) -> Any:
        """Containment ladder for ONE failed request: classify the
        error, then either return it (fatal / cancelled), retry the same
        path with deadline-charged backoff (transient), or quarantine
        the cached plan and climb the degraded ladder (poisoned).
        Returns the final outcome — a CypherResult or the exception to
        complete the handle with.  Never raises."""
        policy = self.retry_policy
        attempts = [self._attempt_entry(exc, level)]
        executions = 1
        current: BaseException = exc
        while True:
            if isinstance(current, CancellationError):
                break  # the budget's verdict stands
            kind = attempts[-1]["classified"]
            if kind == FATAL:
                break
            if kind == TRANSIENT:
                if executions >= policy.max_attempts:
                    current = QueryFailed(
                        f"still failing transiently after {executions} "
                        f"attempts: {type(current).__name__}: {current}",
                        attempts=tuple(attempts),
                        retry_after_s=policy.backoff_s(executions,
                                                       req.request_id))
                    break
                backoff = policy.backoff_s(executions, req.request_id)
                if not policy.budget_allows(req.scope.remaining(), backoff):
                    # a retry never fires when the remaining deadline
                    # budget cannot cover the next backoff: give up NOW
                    # with the backoff as the client's retry hint
                    current = QueryFailed(
                        f"transient failure, but remaining deadline "
                        f"budget < next backoff ({backoff:.3f}s): "
                        f"{type(current).__name__}: {current}",
                        attempts=tuple(attempts), retry_after_s=backoff)
                    break
                attempts[-1]["backoff_s"] = backoff
                self._retries.inc()
                tracer = self.session.tracer
                if tracer.enabled:
                    tracer.event("retry.attempt", attempt=executions,
                                 backoff_s=backoff, mode=_LADDER[level],
                                 error=type(current).__name__)
                policy.sleep(backoff)
            else:  # POISONED_PLAN: quarantine once, then climb the ladder
                if level >= len(_LADDER) - 1:
                    current = QueryFailed(
                        f"degraded ladder exhausted after {executions} "
                        f"attempts: {type(current).__name__}: {current}",
                        attempts=tuple(attempts))
                    break
                if level == 0:
                    self._quarantine(req)
                level += 1
                self._degraded_runs.inc()
            executions += 1
            outcome = self._execute_single(req, level)
            if not isinstance(outcome, BaseException):
                attempts.append({"mode": _LADDER[level], "ok": True})
                req.handle.info["attempts"] = attempts
                return outcome
            attempts.append(self._attempt_entry(outcome, level))
            current = outcome
        req.handle.info["attempts"] = attempts
        return current

    @staticmethod
    def _attempt_entry(exc: BaseException, level: int) -> Dict[str, Any]:
        """One attempt-history record.  A fresh dict per attempt per
        request — failure context lives HERE, never as mutations of the
        exception object (which a badly-behaved injector might share
        across batch members)."""
        entry = {"mode": _LADDER[level], "error": type(exc).__name__,
                 "message": str(exc)[:200], "classified": classify(exc)}
        failed_op = getattr(exc, "caps_failed_op", None)
        if failed_op is not None:
            entry["op"] = failed_op
        return entry

    def _execute_single(self, req: Request, level: int) -> Any:
        """One (re-)execution of a single request at a ladder level.
        Returns the result or the raised exception."""
        with self._exec_lock:
            t0 = clock.now()
            try:
                with cancel_scope(req.scope):
                    if level == 0:
                        return self.session.cypher_on_graph(
                            req.graph, req.query, req.params)
                    return self.session.cypher_degraded(
                        req.graph, req.query, req.params,
                        no_plan_cache=True, no_fused=(level >= 2))
            except BaseException as ex:
                return ex
            finally:
                self.admission.observe_service(clock.now() - t0)

    def _quarantine(self, req: Request) -> None:
        """Evict the request family's shared cached state: the session
        plan-cache entry (relational/plan_cache.py) and, on the TPU
        backend, the fused size memos (backends/tpu/fused.py) — a
        poisoned entry must not keep failing every future hit.
        Stamped on the handle so one request quarantines at most once
        (the ladder and a breaker trip must not double-count)."""
        req.handle.info["quarantined"] = True
        self._quarantines.inc()
        session = self.session
        try:
            key_fn = getattr(session, "_plan_cache_key", None)
            if key_fn is not None:
                key = key_fn(req.graph, req.query, req.params)
                if key is not None:
                    session.plan_cache.quarantine(key)
        except Exception:  # pragma: no cover — containment must not fail
            pass
        fused = getattr(session, "fused", None)
        if fused is not None:
            try:
                # under the exec lock: the memo maps must not shrink
                # under an in-flight fused run on another worker
                with self._exec_lock:
                    fused.forget(req.graph, req.query)
            except Exception:  # pragma: no cover
                pass
        tracer = session.tracer
        if tracer.enabled:
            tracer.event("plan.quarantined", query=req.query)

    def _finish(self, req: Request, outcome: Any) -> None:
        """Materialize (deadline-checked) and complete one handle."""
        if isinstance(outcome, BaseException):
            self._count_failure(outcome)
            req.handle._complete(exception=outcome)
            return
        rows = None
        try:
            with cancel_scope(req.scope), self._observed():
                if self.config.materialize:
                    req.scope.raise_if_done("materialize")
                    rows = outcome.to_maps()
                    req.scope.raise_if_done("materialize")
        except BaseException as ex:
            self._count_failure(ex)
            req.handle._complete(exception=ex)
            return
        req.handle.info["latency_s"] = req.scope.elapsed()
        self._latency.observe(req.handle.info["latency_s"])
        self._completed.inc()
        req.handle._complete(result=outcome, rows=rows)

    def _count_failure(self, ex: BaseException) -> None:
        if isinstance(ex, DeadlineExceeded):
            self._deadline_exceeded.inc()
        elif isinstance(ex, Cancelled):
            self._cancelled.inc()
        else:
            self._failed.inc()
